"""OSD daemon: the object-service process of the mini-cluster.

The asyncio twin of the reference OSD's op path (src/osd/OSD.cc
dispatch -> PrimaryLogPG::do_op -> PGBackend submit, SURVEY.md §3.1):
boots into the mon (MOSDBoot), subscribes to maps, serves client ops as
primary, fans EC chunk writes/reads out to shard peers
(MOSDECSubOpWrite/Read — ECBackend::submit_transaction/handle_sub_*,
src/osd/ECBackend.cc:943,1022,1472), replicates full objects for
replicated pools (MOSDRepOp), and reconstructs missing shards after map
changes (RecoveryBackend::continue_recovery_op, ECBackend.cc:563 →
decode via ECUtil + MOSDPGPush).

Data layout matches the reference: one collection per PG shard
(coll_t(pool, ps, shard), ECTransaction.cc:80-88), chunk payloads at
chunk offsets, per-shard HashInfo crc chains in the ``hinfo`` xattr
(ECUtil.cc:164-248) and the logical size in ``_size`` (the object_info
analogue).

Consistency is log-based (ceph_tpu/osd/pglog.py): every write commits
a pg-log entry with the data; after a map change the primary runs
peering-lite (_recover_pg): pg_info exchange, log adoption from
newer members, per-peer missing sets from the log delta, and full
backfill with authoritative-list stray removal when trimmed past a
peer.  Reads verify object versions across chunks so revived members
with stale shards cannot corrupt results.

Deliberate simplifications vs the reference: the peering state machine
is a linear pass rather than boost::statechart, there is no
ObjectContext rw-locking (recovery races resolve by version guards and
the next pass), and sub-chunk (CLAY) recovery I/O goes through full
chunk reads.
"""

from __future__ import annotations

import asyncio
import errno
import itertools
import logging
import time

import numpy as np

from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.ec import registry as ec_registry
from ceph_tpu.msg.messages import (
    PING,
    PING_REPLY,
    MLogAck,
    MMgrConfigure,
    MMgrMap,
    MMonSubscribe,
    MConfig,
    MOSDBeacon,
    MOSDBoot,
    MOSDECSubOpRead,
    MOSDECSubOpReadReply,
    MOSDECSubOpWrite,
    MOSDECSubOpWriteReply,
    MOSDFailure,
    MOSDMap,
    MOSDPing,
    MWatchNotify,
    MWatchNotifyAck,
    MOSDOp,
    MOSDOpReply,
    MOSDPGPush,
    MOSDPGPushReply,
    MOSDRepOp,
    MOSDRepOpReply,
    MOSDPGInfo,
    MOSDPGLog,
    MOSDPGLogAck,
    MOSDPGQuery,
    MBackfillReserve,
    MOSDScrub,
    MOSDScrubReply,
    OP_APPEND,
    OP_CALL,
    OP_CREATE,
    OP_DELETE,
    OP_GETXATTR,
    OP_GETXATTRS,
    OP_OMAP_CLEAR,
    OP_OMAP_GETKEYS,
    OP_OMAP_GETVALS,
    OP_OMAP_GETVALSBYKEYS,
    OP_OMAP_RMKEYS,
    OP_OMAP_SETKEYS,
    OP_LIST_SNAPS,
    OP_READ,
    OP_RMXATTR,
    OP_ROLLBACK,
    OP_SNAP_CLONE,
    OP_SETXATTR,
    OP_STAT,
    OP_TRUNCATE,
    OP_NOTIFY,
    OP_UNWATCH,
    OP_WATCH,
    OP_WRITE,
    OP_WRITE_FULL,
    OP_ZERO,
)
from ceph_tpu.msg.messenger import Connection, Message, Messenger

# space-freeing write ops stay admissible when FULL — they are how an
# operator digs a cluster out (reference: deletes pass _check_full)
_DELETE_OPS = frozenset(
    {OP_DELETE, OP_OMAP_RMKEYS, OP_OMAP_CLEAR, OP_RMXATTR})
from ceph_tpu.ops.hashing import ceph_str_hash_rjenkins
from ceph_tpu.osd import ecutil
from ceph_tpu.osd.mapenc import apply_map_message
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.pglog import (
    DELETE,
    MODIFY,
    PGMETA_OID,
    ZERO,
    PGLog,
    eversion_t,
    pg_log_entry_t,
)
from ceph_tpu.osd.snaps import (
    NOSNAP,
    SNAPS_ATTR,
    SS_ATTR,
    WHITEOUT_ATTR,
    SnapContext,
    SnapSet,
    decode_snaps,
    encode_snaps,
)
from ceph_tpu.osd.types import PgPool, pg_t
from ceph_tpu.store import MemStore, Transaction, coll_t, ghobject_t

log = logging.getLogger("ceph_tpu.osd")

# shared constants/helpers moved to pgutil (re-exported here: external
# users import object_to_pg/VERSION_ATTR/_v_parse from this module)
from ceph_tpu.osd.pgutil import (  # noqa: E402,F401
    ECConnErrors,
    ECFetchError,
    HINFO_ATTR,
    NO_SHARD,
    SIZE_ATTR,
    STRIPE_UNIT,
    SUBOP_TIMEOUT,
    USER_XATTR_PREFIX,
    VERSION_ATTR,
    _read_extents,
    _v_bytes,
    _v_parse,
    object_to_pg,
)
from ceph_tpu.osd.ec_backend import ECBackendMixin  # noqa: E402
from ceph_tpu.osd.recovery import RecoveryMixin  # noqa: E402
from ceph_tpu.osd.scrubber import ScrubMixin  # noqa: E402
from ceph_tpu.osd.tiering import TieringMixin  # noqa: E402


class OSDDaemon(ECBackendMixin, RecoveryMixin, ScrubMixin, TieringMixin):
    def __init__(
        self,
        osd_id: int,
        mon_addr: tuple[str, int],
        store: MemStore | None = None,
        beacon_interval: float | None = None,
        conf=None,
        auth=None,
        encode_service=None,
    ):
        from ceph_tpu.common import ConfigProxy, get_perf_counters

        self.id = osd_id
        # one address or a monmap; the daemon hunts for a live monitor
        self.mon_addrs: list[tuple[str, int]] = (
            list(mon_addr) if isinstance(mon_addr, list) else [mon_addr]
        )
        self.mon_addr = self.mon_addrs[0]
        self.conf = conf if conf is not None else ConfigProxy()
        # daemon-start plugin preload (ErasureCodePlugin.cc:180-196,
        # driven by osd_erasure_code_plugins): load failures surface at
        # boot, not on the first EC pool op; already-loaded plugins are
        # skipped so repeated daemon constructions are free
        ec_registry.preload(self.conf["osd_erasure_code_plugins"])
        self.store = store or MemStore()
        # scope this store's fault-injection points to this daemon
        # (store.read.osd.<id> etc — see common/fault_injector.py)
        self.store.fault_domain = f"osd.{osd_id}"
        # read-error ledger (the reference's osd_max_object_read_errors
        # escalation): oid -> local medium-error count.  Enough DISTINCT
        # damaged objects means the medium, not the object, is dying —
        # the osd marks itself failed so peering re-places its data.
        self._read_error_ledger: dict[str, int] = {}
        self._disk_escalated = False
        self._death_task: asyncio.Task | None = None
        # multi-device encode farm (production ECSubWrite-fan-out seam,
        # SURVEY.md §2.9); resolved lazily so single-device processes
        # never touch jax at boot
        self._encode_service = encode_service
        self._encode_service_resolved = encode_service is not None
        # recovery-decode batching aggregator (parallel/decode_batcher):
        # per-object recovery decodes coalesce into fixed-shape batched
        # launches; resolved lazily like the farm
        self._decode_aggregator = None
        self._decode_aggregator_resolved = False
        # deep-scrub verification batcher (parallel/scrub_batcher):
        # per-object crc32c + parity re-encode checks coalesce into
        # fixed-shape batched launches; resolved lazily like the farm
        self._scrub_verifier = None
        self._scrub_verifier_resolved = False
        # EC profiles whose fixed-bucket shapes have been prewarmed (the
        # no-compile-in-the-I/O-path discipline; see _warm_ec_profiles)
        self._warmed_profiles: set[str] = set()
        self._warm_tasks: set = set()
        self.messenger = Messenger(
            ("osd", osd_id), self._dispatch, on_reset=self._on_reset,
            auth=auth,
            compress_mode=self.conf["ms_compress_mode"],
            compress_algorithm=self.conf["ms_compress_algorithm"],
            compress_min_size=self.conf["ms_compress_min_size"],
            handshake_timeout=self.conf["ms_connection_ready_timeout"],
        )
        self.messenger.inject_socket_failures = self.conf[
            "ms_inject_socket_failures"
        ]
        self.perf = get_perf_counters(f"osd.{osd_id}")
        from ceph_tpu.common import DoutLogger, OpTracker
        from ceph_tpu.common.tracing import Tracer

        # per-incarnation tracer: a restarted daemon must not inherit a
        # dead daemon's span ring.  Ring size, head-sampling rate and
        # tail capture come from config (trace_* options); the
        # messenger shares it so traced messages grow msg_send/recv
        # net-stage spans
        self.tracer = Tracer(
            f"osd.{osd_id}",
            ring_max=self.conf["trace_ring_max"],
            sample_rate=self.conf["trace_sample_rate"],
            tail_slow_s=(self.conf["trace_tail_slow_s"] or None),
        )
        self.messenger.tracer = self.tracer

        # slow-op forensics (TrackedOp.h:121) + per-subsystem dout
        self.op_tracker = OpTracker(
            history_size=self.conf["osd_op_history_size"],
            slow_threshold=self.conf["osd_op_complaint_time"],
        )
        # eager per-class latency histograms, shared with the local
        # prometheus exposition (proper _bucket/_sum/_count rendering)
        from ceph_tpu.common.optracker import LatencyHistogram

        for cls_ in ("read", "write", "subop_w"):
            h = self.op_tracker.histograms[cls_] = LatencyHistogram()
            self.perf.register_histogram(f"{cls_}_latency", h)
        # mgr report stream (ceph_tpu/mgr/client.py): watches the
        # MgrMap from the mon, streams perf deltas + log2 latency
        # histograms + pg/ledger status to the active mgr
        from ceph_tpu.mgr.client import MgrClient

        from ceph_tpu.common.tracing import device_tracer

        self.mgr_client = MgrClient(
            f"osd.{osd_id}", self.messenger, self.conf,
            self._mgr_collect,
            tracers=(self.tracer, device_tracer()))
        # cluster-log channel (common/logclient.py): operator-relevant
        # events (self-markdown, repair requeues) ship to the mon's
        # replicated log; the local tail ring feeds crash dumps
        from ceph_tpu.common.logclient import LogClient

        self.clog = LogClient(
            f"osd.{osd_id}", self.conf, send=self._send_mon_log)
        self.dlog = DoutLogger("osd", self.conf, name_suffix=str(osd_id))
        self._admin: object | None = None
        self.osdmap: OSDMap | None = None
        self.beacon_interval = (
            beacon_interval
            if beacon_interval is not None
            else self.conf["osd_beacon_report_interval"]
        )
        self.addr: tuple[str, int] | None = None
        self._mon_conn: Connection | None = None
        self._tids = itertools.count(1)
        self._waiters: dict[int, asyncio.Future] = {}
        self._push_waiters: dict[int, asyncio.Future] = {}  # by push tid
        # per-object write serialization (the ObjectContext rw-lock
        # analogue): RMW read/encode/fan-out must not interleave with
        # another write to the same object
        self._obj_locks: dict[tuple[int, str], asyncio.Lock] = {}
        # watch/notify state (primary-local; the reference persists
        # watchers in object_info and re-establishes via client linger —
        # here clients re-watch after a primary change)
        self._watchers: dict[tuple[int, str], dict[tuple, object]] = {}
        self._notify_waiters: dict[tuple, asyncio.Future] = {}
        self._trim_tasks: set = set()
        import contextvars

        # root span of the client op executing in THIS task (ops run as
        # concurrent tasks, so a plain attribute would cross-parent)
        self._op_span = contextvars.ContextVar(
            f"osd{osd_id}_op_span", default=None)
        self._recovering_pgs: set[tuple[int, int]] = set()
        # (pool, ps) -> newest epoch whose recovery pass completed for
        # that pg: a pg is only reported clean once the pass has
        # verified it under the current map (completeness, not just
        # map up-ness)
        self._clean_epoch: dict[tuple[int, int], int] = {}
        # (pool, ps) -> (epoch, acting tuple) of the last PRIMED
        # interval: a primary must adopt the acting set's log state
        # before serving ops in a new interval (peering-before-active,
        # see _prime_interval)
        self._primed_intervals: dict[tuple[int, int], tuple] = {}
        self._prime_locks: dict[tuple[int, int], asyncio.Lock] = {}
        # past_intervals-lite (reference src/osd/osd_types.h:3270
        # PastIntervals): per local PG, the acting sets of recent map
        # intervals since the pg was last clean — recovery consults
        # their still-up members as data SOURCES, so a fully-remapped
        # PG can pull from its previous home.  Bounded; trimmed when
        # the recovery pass completes clean.
        self._past_acting: dict[tuple[int, int], list[list[int]]] = {}
        self._past_acting_loaded = False
        # (pool, ps) -> (last shallow stamp, last deep stamp), monotonic
        self._scrub_stamps: dict[tuple[int, int], tuple[float, float]] = {}
        self._scrub_task: asyncio.Task | None = None
        # primary-side EC stripe cache: (pool, oid) -> (object version,
        # logical lo, bytes) of the most recent write — hot RMW
        # overwrites skip the shard read (ExtentCache role, reference
        # src/osd/ExtentCache.h; entries are version-guarded, so a
        # primary change or missed write can never serve stale bytes)
        from collections import OrderedDict as _OD

        self._extent_cache: "dict[tuple[int, str], tuple]" = _OD()
        self._extent_cache_bytes = 0
        self._ec_cache: dict[str, object] = {}
        self._pg_logs: dict[coll_t, PGLog] = {}
        self._beacon_task: asyncio.Task | None = None
        self._hb_task: asyncio.Task | None = None
        # peer heartbeat state (handle_osd_ping analogue)
        self._hb_last_reply: dict[int, float] = {}
        self._hb_first_ping: dict[int, float] = {}
        self._hb_reported: dict[int, float] = {}
        self.drop_pings = False  # test hook: simulate a silent partition
        self._recovery_task: asyncio.Task | None = None
        # backfill admission control (AsyncReserver twin, reference
        # src/common/AsyncReserver.h + MBackfillReserve handshake):
        # local slots gate PGs WE lead into recovery; remote slots gate
        # how many foreign primaries may backfill onto us at once
        from ceph_tpu.common.reserver import AsyncReserver

        _mb = self.conf["osd_max_backfills"]
        self.local_reserver = AsyncReserver(max_allowed=_mb)
        self.remote_reserver = AsyncReserver(max_allowed=_mb)
        self._remote_grants: dict[tuple[int, int, int], object] = {}
        # in-flight object-reconciliation budget within granted PGs
        # (osd_recovery_max_active role)
        self._recovery_budget = asyncio.Semaphore(
            self.conf["osd_recovery_max_active"])
        self.recovery_stats = {
            "reservation_rejects": 0, "pgs_recovered": 0,
            "peak_local": 0, "peak_remote": 0, "grants_swept": 0,
        }
        self._grant_sweep_task: asyncio.Task | None = None
        self.conf.add_observer(
            ("osd_max_backfills",),
            lambda ch: (
                self.local_reserver.set_max(ch["osd_max_backfills"]),
                self.remote_reserver.set_max(ch["osd_max_backfills"]),
            ),
        )
        # mClock admission gate (OpScheduler seam): top-level work —
        # client ops, recovery reconciliations, scrub chunks — admits
        # here; under saturation dequeue order follows dmclock tags so
        # clients outrank background work.  Sub-op service never
        # admits (see opqueue.py deadlock rule).
        from ceph_tpu.osd.opqueue import MClockGate, parse_qos_profiles
        from ceph_tpu.osd.scheduler import ClientProfile

        self.op_gate = MClockGate(
            max_inflight=self.conf["osd_op_queue_max_inflight"],
            profiles={
                "client": ClientProfile(
                    weight=self.conf["osd_mclock_scheduler_client_wgt"]),
                "recovery": ClientProfile(weight=self.conf[
                    "osd_mclock_scheduler_background_recovery_wgt"]),
                "best_effort": ClientProfile(weight=self.conf[
                    "osd_mclock_scheduler_background_best_effort_wgt"]),
            },
            # per-class qos_* fairness counters land in this OSD's
            # perf collection: `perf dump`, the prometheus exposition
            # and MgrClient report deltas all see them for free
            perf=self.perf,
            tenant_profiles=parse_qos_profiles(
                self.conf["osd_mclock_client_profiles"]),
        )
        self.conf.add_observer(
            ("osd_op_queue_max_inflight",),
            lambda ch: self.op_gate.set_max_inflight(
                ch["osd_op_queue_max_inflight"]),
        )
        self.conf.add_observer(
            ("osd_mclock_client_profiles",),
            lambda ch: self.op_gate.set_tenant_profiles(
                parse_qos_profiles(ch["osd_mclock_client_profiles"])),
        )
        self._map_event = asyncio.Event()
        self.stopping = False
        # fresh per daemon start: lets the mon distinguish a fast
        # restart (new incarnation -> epoch bump, peers re-peer) from a
        # paxos replay of the same boot (no-op)
        self.incarnation = time.time_ns()

    # -- lifecycle -----------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.addr = await self.messenger.bind(host, port)
        sock_path = self.conf["admin_socket"]
        if sock_path:
            from ceph_tpu.common import AdminSocket

            self._admin = AdminSocket(sock_path.replace("$id", str(self.id)))
            self._register_admin_commands(self._admin)
            await self._admin.start()
        await self._mon_hunt()
        self.mgr_client.start()
        self.clog.start()
        if self.beacon_interval > 0:
            self._beacon_task = asyncio.ensure_future(self._beacon())
        if self.conf["osd_heartbeat_interval"] > 0:
            self._hb_task = asyncio.ensure_future(self._heartbeat())
        if self.conf["osd_scrub_interval"] > 0:
            self._scrub_task = asyncio.ensure_future(self._scrub_scheduler())
        if self.conf["osd_tier_agent_interval"] > 0:
            self._tier_task = asyncio.ensure_future(self._tier_agent())
        self._grant_sweep_task = asyncio.ensure_future(self._grant_sweep())
        # wait for the first map so ops can be served
        await asyncio.wait_for(self._map_event.wait(), 10)

    async def _mon_hunt(self) -> None:
        """Find a live monitor, (re)boot and (re)subscribe — the
        MonClient hunting behavior on monitor loss."""
        last: Exception | None = None
        for mhost, mport in self.mon_addrs:
            try:
                conn = await self.messenger.connect(mhost, mport)
                await conn.send_message(MOSDBoot(
                    osd=self.id, host=self.addr[0], port=self.addr[1],
                    incarnation=self.incarnation,
                ))
                await conn.send_message(MMonSubscribe(
                    start_epoch=self.osdmap.epoch if self.osdmap else 0
                ))
                self._mon_conn = conn
                return
            except (ConnectionError, OSError) as e:
                last = e
        raise ConnectionError(f"osd.{self.id}: no monitor reachable: {last}")

    def _register_admin_commands(self, sock) -> None:
        """The reference OSD's admin-socket surface
        (src/osd/OSD.cc::asok_command slice)."""
        sock.register(
            "perf dump", "dump perf counters",
            lambda cmd: self.perf.dump(),
        )
        sock.register(
            "dump_ops_in_flight", "in-flight client ops",
            lambda cmd: self.op_tracker.dump_ops_in_flight(),
        )
        sock.register(
            "dump_historic_ops", "recently completed ops",
            lambda cmd: self.op_tracker.dump_historic_ops(),
        )
        sock.register(
            "dump_historic_slow_ops", "ops over the complaint threshold",
            lambda cmd: self.op_tracker.dump_historic_slow_ops(),
        )
        sock.register(
            "perf histogram dump", "per-op-class log2 latency "
            "histograms (fixed bucket count; the MMgrReport payload)",
            lambda cmd: self.op_tracker.dump_histograms(),
        )
        sock.register(
            "dump_traces", "recent spans (blkin/otel role)",
            lambda cmd: self.tracer.dump(),
        )
        sock.register(
            "dump_qos", "mClock per-class fairness: profiles, "
            "admitted/queued counts, park time and served cost per "
            "dmclock client class (the tenant-differentiation proof)",
            lambda cmd: self.op_gate.qos_dump(),
        )
        sock.register(
            "dump_decode_batch", "recovery-decode aggregator batching "
            "efficiency (per-bucket occupancy/launch/compile counters)",
            lambda cmd: self._dump_decode_batch(),
        )
        sock.register(
            "dump_scrub_batch", "deep-scrub verification batcher "
            "efficiency (batched crc32c + parity re-encode per-bucket "
            "occupancy/launch/compile counters)",
            lambda cmd: self._dump_scrub_batch(),
        )
        sock.register(
            "dump_chaos", "chaos-engine event counters + recent event "
            "spans (process-wide, ceph_tpu/chaos)",
            lambda cmd: __import__(
                "ceph_tpu.chaos", fromlist=["dump_chaos"]).dump_chaos(),
        )
        sock.register(
            "dump_faults", "armed fault-injection points + fired "
            "counters, this osd's read-error ledger, and the "
            "process-wide disk-fault counters/spans",
            lambda cmd: self._dump_faults(),
        )
        sock.register(
            "config show", "effective configuration",
            lambda cmd: self.conf.show(),
        )
        sock.register(
            "config set", "set a config option at runtime",
            lambda cmd: (
                self.conf.apply_changes({cmd["var"]: cmd["val"]}),
                {"success": cmd["var"]},
            )[1],
        )
        sock.register(
            "status", "daemon status",
            lambda cmd: {
                "osd": self.id,
                "epoch": self.epoch,
                "up": not self.stopping,
                "num_pgs": len(self._pg_logs),
            },
        )

    async def stop(self) -> None:
        if getattr(self, "_stopped", False):
            return  # a disk-escalated daemon stops itself; the
            # harness's later stop() must be a no-op
        self._stopped = True
        self.stopping = True
        await self.clog.stop()
        await self.mgr_client.stop()
        if self._admin is not None:
            await self._admin.stop()
        for t in (
            self._beacon_task, self._hb_task, self._recovery_task,
            self._scrub_task, getattr(self, "_rehome_task", None),
            getattr(self, "_tier_task", None),
            getattr(self, "_grant_sweep_task", None),
            *getattr(self, "_repair_tasks", ()),
        ):
            if t:
                t.cancel()
        await self.messenger.shutdown()

    async def _send_mon_log(self, msg: Message) -> None:
        """LogClient send hook: ship one MLog over the current mon
        session (re-homed by the hunt task after mon failover, so
        unacked entries resend to the new mon)."""
        if self._mon_conn is None:
            raise ConnectionError("no monitor session")
        await self._mon_conn.send_message(msg)

    def record_crash(self, reason: str = "",
                     exc: BaseException | None = None) -> str | None:
        """Persist a crash dump (common/crash.py) for an unhandled
        exit or a fault-injector-induced death: entity, exception/
        reason, config fingerprint and the in-memory log tail — the
        mgr crash module collects it (`ceph crash ls`)."""
        from ceph_tpu.common.crash import record_crash

        return record_crash(self.conf, f"osd.{self.id}", exc=exc,
                            reason=reason, log_tail=self.clog.tail())

    def _statfs(self) -> dict:
        """This OSD's store usage; cached per beacon tick.  Also drives
        the local failsafe write gate (_check_full role)."""
        try:
            sf = self.store.statfs()
        except (NotImplementedError, OSError):
            sf = {"total": 1 << 40, "used": 0, "available": 1 << 40}
        self._last_statfs = sf
        return sf

    def _full_ratio(self) -> float:
        sf = getattr(self, "_last_statfs", None)
        if sf is None:
            sf = self._statfs()
        total = sf.get("total", 0)
        return (sf.get("used", 0) / total) if total else 0.0

    async def _beacon(self) -> None:
        import json as _json

        while not self.stopping:
            await asyncio.sleep(self.beacon_interval)
            try:
                stats = b""
                try:
                    stats = self._collect_pg_stats()
                except Exception:
                    log.exception("osd.%d: pg-stat collection failed", self.id)
                await self._mon_conn.send_message(
                    MOSDBeacon(osd=self.id, epoch=self.epoch,
                               pg_stats=stats,
                               statfs=_json.dumps(self._statfs()).encode())
                )
            except ConnectionError:
                continue  # mon died; the rehome task is hunting

    def _collect_pg_stats(self) -> bytes:
        """Per-PG state for the PGs this OSD leads — the MPGStats
        report (reference src/mgr/DaemonServer.cc aggregation source).
        States mirror the reference's pg_state_t vocabulary at the
        granularity this OSD can see: active+clean, active+degraded
        (acting set has holes or down members), active+recovering."""
        import json as _json

        om = self.osdmap
        if om is None:
            return b""
        out = {}
        for pid, pool in om.pools.items():
            for ps in range(pool.pg_num):
                pg = pg_t(pid, ps)
                up, _up, acting, primary = om.pg_to_up_acting_osds(
                    pg, folded=True)
                if primary != self.id:
                    continue
                degraded = any(
                    o == CRUSH_ITEM_NONE or not om.is_up(o) for o in acting
                )
                state = "active"
                if (pid, ps) in self._recovering_pgs:
                    state += "+recovering"
                elif degraded:
                    state += "+degraded"
                elif self._clean_epoch.get((pid, ps), -1) < om.epoch:
                    # the recovery pass has not verified this pg under
                    # the current map yet: data completeness unknown
                    state += "+peering"
                else:
                    state += "+clean"
                my_shard = next(
                    (s for s, o in enumerate(acting) if o == self.id),
                    None,
                )
                n_obj = 0
                n_bytes = 0
                if my_shard is not None:
                    shard = my_shard if pool.is_erasure() else NO_SHARD
                    names = self._local_objects(pool, pg, shard)
                    n_obj = len(names)
                    c = self._shard_coll(pool, pg, shard)
                    for nm in names:
                        try:
                            n_bytes += self.store.stat(c, ghobject_t(nm))
                        except FileNotFoundError:
                            continue
                    if pool.is_erasure():
                        # shard bytes -> logical bytes (k data shards)
                        k = int(self.osdmap.erasure_code_profiles.get(
                            pool.erasure_code_profile, {}).get("k", 1)
                            or 1)
                        n_bytes *= k
                out[f"{pid}.{ps}"] = {
                    "state": state, "objects": n_obj, "bytes": n_bytes,
                    # upmap/reweight moved this pg off its CRUSH-ideal
                    # home: objects are misplaced (not missing) — the
                    # mgr progress module's rebalance-event source
                    "misplaced": (not degraded and up != acting),
                }
        return _json.dumps(out).encode()

    def _mgr_collect(self) -> dict:
        """Raw material for this OSD's MMgrReport (mgr/client.py
        derives counter deltas + interval latency means from it)."""
        import json as _json

        pg_states: dict[str, int] = {}
        pgs_degraded = pgs_misplaced = 0
        try:
            for st in _json.loads(
                    self._collect_pg_stats() or b"{}").values():
                s = st.get("state", "unknown")
                pg_states[s] = pg_states.get(s, 0) + 1
                # the progress module's raw material: PGs this OSD
                # leads that are missing data (degraded/recovering/
                # peering) vs merely living off their CRUSH home
                if ("degraded" in s or "recovering" in s
                        or "peering" in s):
                    pgs_degraded += 1
                elif st.get("misplaced"):
                    pgs_misplaced += 1
        except ValueError:
            pass
        # ops currently in flight past the complaint threshold: the
        # live half of the SLOW_OPS signal (complaints only move when
        # a slow op COMPLETES; a wedged op must still raise the warning)
        thresh = self.op_tracker.slow_threshold
        slow_inflight = sum(
            1 for op in self.op_tracker.inflight.values()
            if op.duration >= thresh
        )
        counters = dict(self.perf.dump())
        # the tracing plane's own telemetry (prometheus module exports
        # these as counters: spans recorded/dropped, sampler verdicts)
        counters.update({
            f"trace_{k}": float(v)
            for k, v in self.tracer.counters.items()
        })
        counters["slow_ops_total"] = float(self.op_tracker.complaints)
        return {
            "counters": counters,
            "gauges": {
                "num_pgs": float(len(self._pg_logs)),
                "inflight_ops": float(len(self.op_tracker.inflight)),
                "slow_ops": float(self.op_tracker.complaints),
                "slow_ops_inflight": float(slow_inflight),
                # event-plane columns (reserved in the analytics
                # store; their integer-exact EWMAs drive progress ETAs)
                "pgs_degraded": float(pgs_degraded),
                "pgs_misplaced": float(pgs_misplaced),
            },
            "histograms": dict(self.op_tracker.histograms),
            "status": {
                "pg_states": pg_states,
                # the disk-fault telemetry devicehealth consumes
                "read_errors": len(self._read_error_ledger),
                "disk_escalated": self._disk_escalated,
                "slow_ops": self.op_tracker.complaints,
                "slow_ops_inflight": slow_inflight,
                "scrub_deprioritized": bool(
                    self.mgr_client.scrub_deprioritized),
            },
        }

    @property
    def epoch(self) -> int:
        return self.osdmap.epoch if self.osdmap else 0

    # -- peer heartbeats (OSD::handle_osd_ping, src/osd/OSD.cc:5735) ---

    async def _heartbeat(self) -> None:
        """Ping every up peer; report peers whose replies stop to the
        mon.  This catches OSD<->OSD partitions that mon beacons cannot
        see (the peer's beacon keeps flowing while its data path is
        dead) — the reference's front/back heartbeat role."""
        interval = self.conf["osd_heartbeat_interval"]
        grace = self.conf["osd_heartbeat_grace"]
        last_iter = time.monotonic()
        while not self.stopping:
            await asyncio.sleep(interval)
            om = self.osdmap
            if om is None:
                continue
            now = time.monotonic()
            starved = now - last_iter > grace
            last_iter = now
            if starved:
                # the shared event loop stalled (big computation, GC):
                # every peer's replies are "late" by exactly our own
                # stall, not dead — re-seed the reply clocks instead of
                # reporting the whole cluster failed at once (the mon's
                # beacon tick has the same guard; the OSD<->OSD plane
                # needs it too or one stall sprays N^2 failure reports
                # and mass-downs live daemons — soak-chaos-found)
                for peer in list(self._hb_first_ping):
                    self._hb_first_ping[peer] = now
                continue
            peers = [
                o for o in range(om.max_osd)
                if o != self.id and om.is_up(o) and o in om.osd_addrs
            ]
            for gone in set(self._hb_first_ping) - set(peers):
                self._hb_first_ping.pop(gone, None)
                self._hb_last_reply.pop(gone, None)
                self._hb_reported.pop(gone, None)
            for peer in peers:
                self._hb_first_ping.setdefault(peer, now)
                try:
                    conn = await self._osd_conn(peer)
                    await conn.send_message(MOSDPing(
                        op=PING, from_osd=self.id, epoch=self.epoch,
                        stamp=time.monotonic_ns(),
                    ))
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    pass  # counts as silence; grace logic judges below
                last_ok = max(
                    self._hb_last_reply.get(peer, 0.0),
                    self._hb_first_ping[peer],
                )
                if (
                    now - last_ok > grace
                    and now - self._hb_reported.get(peer, 0.0) > grace
                ):
                    self._hb_reported[peer] = now
                    log.warning(
                        "osd.%d: peer osd.%d silent for %.1fs; reporting",
                        self.id, peer, now - last_ok,
                    )
                    try:
                        await self._mon_conn.send_message(MOSDFailure(
                            reporter=self.id, failed=peer, epoch=self.epoch,
                        ))
                    except (ConnectionError, OSError):
                        pass

    async def _handle_ping(self, msg: MOSDPing) -> None:
        if msg.op == PING:
            if self.drop_pings:
                # test hook: peers cannot reach us (we still hear their
                # replies to OUR pings, like a one-way-dead link)
                return
            await msg.conn.send_message(MOSDPing(
                op=PING_REPLY, from_osd=self.id, epoch=self.epoch,
                stamp=msg.stamp,
            ))
        elif msg.op == PING_REPLY:
            self._hb_last_reply[msg.from_osd] = time.monotonic()

    # -- plumbing ------------------------------------------------------

    async def _on_reset(self, conn: Connection) -> None:
        """Connection to a peer died: fail pending sub-ops and report
        the peer (the OSD::ms_handle_reset + failure-report path)."""
        if self.stopping or conn.peer is None:
            return
        kind, peer_id = conn.peer
        if kind == "mon" and conn is self._mon_conn:
            async def _rehome():
                for _ in range(20):
                    await asyncio.sleep(0.2)
                    if self.stopping:
                        return
                    try:
                        await self._mon_hunt()
                        return
                    except (ConnectionError, OSError):
                        continue
            self._rehome_task = asyncio.ensure_future(_rehome())
            return
        for tid, fut in list(self._waiters.items()):
            if getattr(fut, "peer", None) == conn.peer and not fut.done():
                fut.set_exception(ConnectionError(f"peer {conn.peer} reset"))
        if kind == "osd" and self.osdmap and self.osdmap.is_up(peer_id):
            try:
                await self._mon_conn.send_message(
                    MOSDFailure(
                        reporter=self.id, failed=peer_id, epoch=self.epoch
                    )
                )
            except ConnectionError:
                pass

    async def _osd_conn(self, osd: int) -> Connection:
        addr = self.osdmap.osd_addrs.get(osd)
        if addr is None:
            raise ConnectionError(f"no address for osd.{osd}")
        return await self.messenger.connect_to(("osd", osd), *addr)

    async def _sub_op(self, osd: int, msg: Message, tid: int):
        """Send a sub-op and await its reply future."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        fut.peer = ("osd", osd)
        self._waiters[tid] = fut
        try:
            conn = await self._osd_conn(osd)
            await conn.send_message(msg)
            return await asyncio.wait_for(fut, SUBOP_TIMEOUT)
        finally:
            self._waiters.pop(tid, None)

    def _ec_for(self, pool: PgPool):
        prof_name = pool.erasure_code_profile
        if prof_name not in self._ec_cache:
            profile = dict(self.osdmap.erasure_code_profiles[prof_name])
            ec = ec_registry.factory(profile.get("plugin", "jax"), profile)
            self._ec_cache[prof_name] = ec
        return self._ec_cache[prof_name]

    def _sinfo(self, ec) -> ecutil.StripeInfo:
        k = ec.get_data_chunk_count()
        chunk = ec.get_chunk_size(STRIPE_UNIT * k)
        return ecutil.StripeInfo(k, chunk * k)

    def _acting(self, pool: PgPool, pg: pg_t) -> tuple[list[int], int]:
        _, _, acting, primary = self.osdmap.pg_to_up_acting_osds(pg)
        return acting, primary

    @property
    def encode_service(self):
        """The process encode farm, per osd_ec_encode_farm config:
        'auto' = farm when >1 local jax device, 'on' = always attach the
        shared service, 'off' = never.  Resolved once, lazily."""
        if not self._encode_service_resolved:
            self._encode_service_resolved = True
            mode = self.conf["osd_ec_encode_farm"]
            if mode != "off":
                from ceph_tpu.parallel import encode_service as es

                svc = es.shared()
                if svc.active() or mode == "on":
                    svc.min_bytes = self.conf["osd_ec_farm_min_bytes"]
                    self._encode_service = svc
        return self._encode_service

    @property
    def decode_aggregator(self):
        """The process recovery-decode aggregator, per
        osd_recovery_decode_batch config.  Device-agnostic (the batched
        XLA kernel is bit-exact on CPU and TPU), so default on."""
        if not self._decode_aggregator_resolved:
            self._decode_aggregator_resolved = True
            if self.conf["osd_recovery_decode_batch"] != "off":
                from ceph_tpu.parallel import decode_batcher as db

                agg = db.shared()
                agg.window_s = self.conf[
                    "osd_recovery_decode_batch_window"]
                self._decode_aggregator = agg
        return self._decode_aggregator

    @property
    def scrub_verifier(self):
        """The process deep-scrub verification batcher, per
        osd_scrub_verify_batch config.  Device-agnostic (batched
        crc32c and re-encode-compare are bit-exact on CPU and TPU),
        so default on."""
        if not self._scrub_verifier_resolved:
            self._scrub_verifier_resolved = True
            if self.conf["osd_scrub_verify_batch"] != "off":
                from ceph_tpu.parallel import scrub_batcher as sb

                ver = sb.shared()
                ver.window_s = self.conf["osd_scrub_verify_batch_window"]
                self._scrub_verifier = ver
        return self._scrub_verifier

    def _dump_scrub_batch(self) -> dict:
        import os as _os

        ver = self.scrub_verifier
        if ver is None:
            return {"active": False}
        # pid lets multi-process harnesses dedupe the process-wide
        # verifier across co-hosted daemons' sockets
        return {"active": True, "pid": _os.getpid(),
                "stats": dict(ver.stats),
                "efficiency": ver.metrics.efficiency(),
                "buckets": ver.metrics.dump()}

    def _dump_decode_batch(self) -> dict:
        import os as _os

        agg = self.decode_aggregator
        if agg is None:
            return {"active": False}
        # pid lets multi-process harnesses dedupe the process-wide
        # aggregator across co-hosted daemons' sockets
        out = {"active": True, "pid": _os.getpid(),
               "stats": dict(agg.stats)}
        out["efficiency"] = agg.metrics.efficiency()
        out["buckets"] = agg.metrics.dump()
        svc = self._encode_service
        if svc is not None:
            out["encode_farm"] = {
                "stats": dict(svc.stats),
                "efficiency": svc.metrics.efficiency(),
            }
        return out

    def _warm_ec_profiles(self) -> None:
        """Map-time warmup: compile the fixed-bucket batched
        decode/encode shapes for every EC profile the new map carries,
        in a background thread — so after a profile's warmup completes,
        no XLA compile can occur inside the recovery/write I/O path
        (the discipline the decode aggregator's cold_launches counter
        verifies).  Idempotent per profile name."""
        om = self.osdmap
        if om is None or self.conf["osd_ec_warmup"] == "off":
            return
        fresh = [
            (name, dict(prof))
            for name, prof in (om.erasure_code_profiles or {}).items()
            if name not in self._warmed_profiles
        ]
        if not fresh:
            return  # BEFORE resolving services: maps without EC
            # profiles must not make replicated-only daemons touch jax
        self._warmed_profiles.update(name for name, _ in fresh)
        agg = self.decode_aggregator
        svc = self.encode_service
        ver = self.scrub_verifier

        def _warm() -> None:
            import jax

            # the farm's mesh/collective shapes are only worth
            # compiling ahead of time on an accelerator backend (where
            # a cold compile stalls the I/O path for ~30 s); on the CPU
            # backend (tests, dev) compiles are milliseconds and the
            # eager virtual-mesh warmup would cost more than it saves
            farm_warm = jax.default_backend() not in ("cpu",)
            for name, prof in fresh:
                try:
                    ec = ec_registry.factory(
                        prof.get("plugin", "jax"), dict(prof))
                    sinfo = self._sinfo(ec)
                    cs = sinfo.chunk_size
                    widths = [max(cs >> 2, 1), cs, cs << 2]
                    if agg is not None:
                        agg.prewarm(ec, widths)
                    if ver is not None:
                        ver.prewarm(ec, widths)
                    if (svc is not None and farm_warm
                            and hasattr(ec, "coding_matrix")):
                        svc.prewarm(ec.coding_matrix, widths)
                except Exception:
                    log.exception(
                        "osd.%d: EC warmup for profile %r failed",
                        self.id, name)
            # every profile's ladder is compiled: the steady state
            # starts here, so arm the runtime transfer guard (the
            # twin of ctlint's transfer rules) — any implicit
            # host<->device transfer on a later decode/scrub/encode
            # launch is counted + answered from the host fallback
            mode = self.conf["osd_transfer_guard"]
            if mode != "off":
                from ceph_tpu.common.transfer_guard import configure

                configure(mode, self.conf["osd_transfer_guard_window"])

        task = asyncio.ensure_future(asyncio.to_thread(_warm))
        self._warm_tasks.add(task)
        task.add_done_callback(self._warm_tasks.discard)

    def _extent_cache_get(self, pool_id, oid, version, lo, hi):
        ent = self._extent_cache.get((pool_id, oid))
        if ent is None:
            return None
        v, elo, arr = ent
        if v != version or elo > lo or elo + len(arr) < hi:
            return None
        self._extent_cache.move_to_end((pool_id, oid))
        self.perf.inc("ec_extent_cache_hit")
        return arr[lo - elo : hi - elo]

    def _extent_cache_put(self, pool_id, oid, version, lo, arr) -> None:
        limit = self.conf["osd_ec_extent_cache_bytes"]
        if limit <= 0 or len(arr) > limit:
            return
        old = self._extent_cache.pop((pool_id, oid), None)
        if old is not None:
            self._extent_cache_bytes -= len(old[2])
        self._extent_cache[(pool_id, oid)] = (version, lo, arr)
        self._extent_cache_bytes += len(arr)
        while self._extent_cache_bytes > limit and self._extent_cache:
            _k, ent = self._extent_cache.popitem(last=False)
            self._extent_cache_bytes -= len(ent[2])

    def _extent_cache_drop(self, pool_id, oid) -> None:
        old = self._extent_cache.pop((pool_id, oid), None)
        if old is not None:
            self._extent_cache_bytes -= len(old[2])

    async def _ecu_encode(self, sinfo, ec, logical):
        """ecutil.encode via the farm (falls back inside).  Traced ops
        get a device-stage span so the critical-path breakdown can
        attribute encode time separately from net/queue/store."""
        with self._maybe_span(
            "ec_encode", parent=self._op_span.get(), stage="device",
            nbytes=len(logical),
        ):
            return await ecutil.encode_async(
                sinfo, ec, logical, service=self.encode_service)

    async def _ecu_decode_concat(self, sinfo, ec, chunks):
        with self._maybe_span(
            "ec_decode", parent=self._op_span.get(), stage="device",
            shards=len(chunks),
        ):
            return await ecutil.decode_concat_async(
                sinfo, ec, chunks, service=self.encode_service)

    def _pg_log(self, c: coll_t) -> PGLog:
        lg = self._pg_logs.get(c)
        if lg is None:
            lg = PGLog(c)
            lg.load(self.store)
            self._pg_logs[c] = lg
        return lg

    def _pg_log_trim(self, t: Transaction, lg: PGLog) -> None:
        """Hysteresis trim driven by the LIVE registered options (the
        reference's PeeringState::calc_trim_to): once a shard's log
        exceeds osd_max_pg_log_entries, cut it back down to
        osd_min_pg_log_entries.  Reading conf here (not a cached ctor
        snapshot) means `config set` takes effect on the next commit —
        the soak scenarios lean on low values to force backfill."""
        if len(lg.entries) > self.conf["osd_max_pg_log_entries"]:
            lg.trim(t, self.conf["osd_min_pg_log_entries"])

    async def _prime_interval(self, pool, pg, acting) -> bool:
        """Adopt the acting peers' pg-log state before this primary
        serves its first op of a NEW interval (the reference's
        peering-before-active contract, PG::activate).

        Without it, a revived primary whose log missed the degraded
        window mints its next version from a stale last_update — the
        counter re-use lands INSIDE the window its peers already hold
        (e.g. peers at 10'6, stale primary mints 11'3), which
        (a) re-bases the version stream, (b) looks contiguous to gap
        detection, and (c) makes every log's last_update equal so
        missing_from() scopes nothing: the stale shard survives until
        scrub.  Adopting first makes the mint collision-free AND
        leaves the adopted entries in the log, where the self-audit
        (log-vs-store) flags the primary's own missing objects for
        the next recovery pass.

        Returns False (caller bounces EAGAIN) while an acting peer is
        unreachable — serving ops without its log state is exactly
        the hole being closed.  Re-primes only when the ACTING SET
        changes; same-set epochs refresh for free."""
        key = (pool.id, pool.raw_pg_to_pg(pg).ps)
        cached = self._primed_intervals.get(key)
        act = tuple(acting)
        if cached is not None and cached[1] == act:
            if cached[0] != self.epoch:
                self._primed_intervals[key] = (self.epoch, act)
            return True
        lock = self._prime_locks.setdefault(key, asyncio.Lock())
        async with lock:
            cached = self._primed_intervals.get(key)
            if cached is not None and cached[1] == act:
                return True
            epoch0 = self.epoch
            pairs = self._pg_members(pool, acting)
            mine = next((s for s, o in pairs if o == self.id), None)
            if mine is None:
                return False  # not a member under this view
            c = self._shard_coll(pool, pg, mine)
            lg = self._pg_log(c)
            for s, o in pairs:
                if o == self.id:
                    continue
                try:
                    info = await self._pg_query(
                        pool, pg, s, o, since=lg.info.last_update)
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    return False  # unseen peer state: stay inactive
                if info.last_update > lg.info.last_update:
                    t = Transaction()
                    self._ensure_coll(t, c)
                    for raw in info.entries:
                        e = pg_log_entry_t.decode(raw)
                        if e.version > lg.info.last_update:
                            lg.append(t, e)
                    self._pg_log_trim(t, lg)
                    if not t.empty():
                        if getattr(self.store, "blocking_commit", False):
                            await asyncio.to_thread(
                                self.store.queue_transaction, t)
                        else:
                            self.store.queue_transaction(t)
            if self.epoch == epoch0:
                self._primed_intervals[key] = (epoch0, act)
            return self.epoch == epoch0

    def _next_version(
        self, c: coll_t, epoch: int | None = None
    ) -> eversion_t | None:
        """``epoch`` must be the op's ADMISSION epoch (captured when the
        primary check passed): maps can advance mid-op, and minting with
        the then-current epoch would let two daemons that were each
        primary under different maps stamp the SAME eversion onto
        different payloads — an undetectable mixed-content write.

        Returns None when the pg log already holds an entry from a
        NEWER epoch (e.g. adopted from the next interval's primary):
        this op must be re-admitted under the newer map (caller replies
        EAGAIN) — minting into a foreign epoch could collide with that
        primary's versions.

        The counter is RESERVED at mint time (PGLog.reserved_version):
        concurrent ops to different objects must never mint the same
        eversion — the second append would silently swallow the
        first's log entry (its object then has no log evidence and no
        recovery pass can ever scope it).  An in-flight mint that dies
        with the daemon just skips a counter — a detectable gap."""
        lg = self._pg_log(c)
        lu = lg.info.last_update
        e = self.epoch if epoch is None else epoch
        if lu.epoch > e or lg.reserved_version.epoch > e:
            return None
        v = eversion_t(e, max(lu.version, lg.reserved_version.version) + 1)
        lg.reserved_version = v
        return v

    def _object_version(self, c: coll_t, o: ghobject_t) -> eversion_t:
        try:
            return _v_parse(self.store.getattr(c, o, VERSION_ATTR))
        except (FileNotFoundError, KeyError):
            return ZERO

    def _maybe_span(self, name: str, parent=None, ctx=None, **tags):
        """A tracer span joined to an existing trace, or a no-op when
        there is none — background work (recovery, repair sweeps) must
        not mint fresh root traces per shard write."""
        import contextlib as _ctx

        if parent is None and ctx is None:
            return _ctx.nullcontext(None)
        return self.tracer.span(name, parent=parent, ctx=ctx, **tags)

    async def _store_latency_gate(self) -> None:
        """Async injected-store-latency point (chaos degraded-disk
        scenario: ``FAULTS.inject("store.latency.osd.<id>", delay=...,
        count=None)``).  Unlike the sync store_fault_check delay this
        sleeps on the event loop, so ONE slow disk slows only its own
        commits — not every daemon co-hosted in the process."""
        from ceph_tpu.common.fault_injector import FAULTS

        if FAULTS._points:
            await FAULTS.check(f"store.latency.osd.{self.id}")

    def _obj_lock(self, pool_id: int, oid: str) -> asyncio.Lock:
        key = (pool_id, oid)
        lk = self._obj_locks.get(key)
        if lk is None:
            if len(self._obj_locks) > 4096:  # prune idle locks
                # a lock is only disposable when nothing holds it AND
                # nothing waits on it: between release and a waiter's
                # wakeup, locked() is False while the waiter still
                # references the old Lock object — pruning then would
                # hand the next writer a fresh lock and break mutual
                # exclusion
                for k in [
                    k for k, v in self._obj_locks.items()
                    if not v.locked() and not getattr(v, "_waiters", None)
                ]:
                    del self._obj_locks[k]
            lk = self._obj_locks[key] = asyncio.Lock()
        return lk

    # -- disk-fault tolerance (read-error ledger + escalation) ---------

    def _dump_faults(self) -> dict:
        """`dump_faults` admin command: the disk-fault observability
        plane (armed injection points are process-global; the ledger
        and escalation flag are this daemon's)."""
        from ceph_tpu.common.fault_injector import (
            FAULTS,
            disk_fault_counters,
            disk_fault_tracer,
        )

        return {
            "armed": FAULTS.dump(),
            "read_error_ledger": dict(self._read_error_ledger),
            "escalated": self._disk_escalated,
            "counters": disk_fault_counters().dump(),
            "recent": disk_fault_tracer().dump(limit=50),
        }

    def _note_medium_error(
        self, pool, pg, shard, oid: str, *, op: str = "read",
        snap: int = NOSNAP,
    ) -> None:
        """A LOCAL store access returned a medium error (checksum-at-
        rest EIO, injected disk fault).  Responses mirror the
        reference's chain: count it (perf + disk_fault span), and for
        reads spawn the verify-quarantine-repair pass
        (:meth:`_quarantine_shard`) whose CONFIRMED damage feeds the
        read-error ledger and, past osd_max_object_read_errors
        distinct objects, escalates to self-markdown.  Write errors
        only count — clients retry them, and a disk that can no longer
        write also fails the constant read traffic, which is where the
        dying-disk verdict belongs."""
        from ceph_tpu.common.fault_injector import (
            disk_fault_counters,
            disk_fault_tracer,
        )

        self.perf.inc(f"{op}_errors")
        disk_fault_counters().inc("medium_errors", op=op)
        with disk_fault_tracer().span(
            "medium_error", osd=self.id, pg=str(pg), oid=oid, op=op,
        ):
            pass
        log.warning(
            "osd.%d: medium error (%s) on %s/%s", self.id, op, pg, oid)
        if op == "read" and self.conf["osd_read_error_repair"]:
            self._spawn_repair_task(
                self._quarantine_shard(pool, pg, shard, oid, snap))

    async def _quarantine_shard(self, pool, pg, shard, oid, snap) -> None:
        """Verify-then-quarantine a shard whose read returned a medium
        error.

        1. RE-READ: a transient EIO (loose cabling, an injected
           one-shot) must not cost a healthy shard — only damage that
           reproduces counts (the bluestore_retry_disk_reads
           discipline).  Confirmed damage enters the read-error ledger
           and can escalate to self-markdown.
        2. Require a HEALTHY ALTERNATIVE (replicated: another member
           serving >= our version; EC: >= k other readable shards)
           before dropping the local object — quarantine repairs
           redundancy, it must never delete the last copy.  Bit rot
           keeps the kv-side version attrs intact, so without the
           removal every probe reports the shard healthy and no repair
           would ever target it.  (Replicated omap is not restored by
           a push — acceptable for a shard whose data plane already
           returned EIO.)
        3. Requeue the background repair when this OSD leads the pg; a
           replica's hole is found by its primary's next
           reconcile/scrub pass."""
        from ceph_tpu.common.fault_injector import disk_fault_counters

        try:
            async with self._obj_lock(pool.id, oid):
                c = self._shard_coll(pool, pg, shard)
                o = (ghobject_t(oid, shard=shard) if snap == NOSNAP
                     else ghobject_t(oid, snap=snap, shard=shard))
                if not self.store.exists(c, o):
                    return
                try:
                    if getattr(self.store, "blocking_commit", False):
                        await asyncio.to_thread(self.store.read, c, o)
                    else:
                        self.store.read(c, o)
                    return  # re-read clean: transient error, keep shard
                except OSError as e:
                    if (e.errno or errno.EIO) != errno.EIO:
                        return
                # persistent damage confirmed: ledger + escalation
                ledger = self._read_error_ledger
                ledger[oid] = ledger.get(oid, 0) + 1
                disk_fault_counters().inc("persistent_damage")
                log.warning(
                    "osd.%d: persistent medium error on %s/%s (%d "
                    "damaged objects on this disk)", self.id, pg, oid,
                    len(ledger))
                thresh = self.conf["osd_max_object_read_errors"]
                if thresh > 0 and len(ledger) >= thresh:
                    self._escalate_disk_failure()
                if not await self._has_healthy_alternative(
                        pool, pg, shard, oid, snap, c, o):
                    log.warning(
                        "osd.%d: NOT quarantining %s/%s: no healthy "
                        "alternative copy reachable", self.id, pg, oid)
                    return
                t = Transaction()
                t.remove(c, o)
                if getattr(self.store, "blocking_commit", False):
                    await asyncio.to_thread(self.store.queue_transaction, t)
                else:
                    self.store.queue_transaction(t)
                disk_fault_counters().inc("quarantined")
        except OSError:
            # a dying disk can refuse the removal too; escalation is
            # the backstop for that state
            log.exception(
                "osd.%d: quarantine of %s/%s failed", self.id, pg, oid)
            return
        if snap == NOSNAP or not pool.is_erasure():
            self._queue_object_repair(pool, pg, oid)

    async def _has_healthy_alternative(
        self, pool, pg, shard, oid, snap, c, o
    ) -> bool:
        """True when the damaged shard is reconstructible without us:
        replicated needs one other member serving >= our version; EC
        needs >= k other shards answering a data read.  (A 1-byte read
        verifies the data plane answers, not every blob — the same
        approximation authoritative-copy selection makes.)"""
        local_v = self._object_version(c, o)
        acting, _primary = self._acting(pool, pg)
        ok = 0
        need = (self._ec_for(pool).get_data_chunk_count()
                if pool.is_erasure() else 1)
        for s, osd in self._pg_members(pool, acting):
            if osd == self.id and s == shard:
                continue
            if osd == CRUSH_ITEM_NONE or not self.osdmap.is_up(osd):
                continue
            payload, attrs, _e = await self._read_shard_quiet(
                pool, pg, s, osd, oid, off=0, length=1, snap=snap)
            if payload is None:
                continue
            if _v_parse((attrs or {}).get(VERSION_ATTR)) >= local_v:
                ok += 1
                if ok >= need:
                    return True
        return False

    def _spawn_repair_task(self, coro) -> None:
        t = asyncio.ensure_future(coro)
        hold = getattr(self, "_repair_tasks", None)
        if hold is None:
            hold = self._repair_tasks = set()
        hold.add(t)
        t.add_done_callback(hold.discard)

    def _escalate_disk_failure(self) -> None:
        """Too many distinct objects with medium errors: the disk is
        dying.  Self-report failure to the mon and stop — peering
        re-replicates onto healthy OSDs (the reference OSD aborts on
        repeated EIO and the mon's down/out machinery re-places it)."""
        if self._disk_escalated:
            return
        self._disk_escalated = True
        from ceph_tpu.common.fault_injector import disk_fault_counters

        self.perf.inc("disk_fault_escalations")
        disk_fault_counters().inc("escalations")
        log.error(
            "osd.%d: %d objects with medium errors >= "
            "osd_max_object_read_errors; marking self failed and "
            "shutting down", self.id, len(self._read_error_ledger),
        )
        # the self-markdown is an operator-visible cluster event AND a
        # fault-induced death: one line in the replicated cluster log,
        # one crash dump for `ceph crash ls` / RECENT_CRASH
        self.clog.cluster.error(
            f"osd.{self.id} marking self down: "
            f"{len(self._read_error_ledger)} objects with verified "
            "medium errors (read-error ledger escalation)")
        self.record_crash(
            reason="read-error ledger escalation: "
            f"{len(self._read_error_ledger)} damaged objects >= "
            "osd_max_object_read_errors; daemon self-terminated")

        async def _die() -> None:
            try:
                await self._mon_conn.send_message(MOSDFailure(
                    reporter=self.id, failed=self.id, epoch=self.epoch,
                ))
            except (ConnectionError, OSError, AttributeError):
                pass  # peers' connection resets will report us instead
            # last flush: the markdown log entry must beat the stop
            # (stop() cancels the flush loop)
            await self.clog.flush()
            await self.stop()

        # held OUTSIDE _repair_tasks: stop() cancels those, and the
        # death task must survive to run stop() itself
        self._death_task = asyncio.ensure_future(_die())

    async def _rep_degraded_read(
        self, pool, pg, acting, msg, snap: int
    ) -> "MOSDOpReply | None":
        """Serve a read-class vector from the first replica holding the
        object (primary-local copy quarantined away): READ/STAT/xattr
        ops answer from the replica's payload+attrs; vectors needing
        more (omap, class calls) fall back to the caller's ENOENT.
        Requeues the background repair that restores the local copy."""
        for osd in acting:
            if osd in (self.id, CRUSH_ITEM_NONE) or not self.osdmap.is_up(osd):
                continue
            payload, attrs, _e = await self._read_shard_quiet(
                pool, pg, NO_SHARD, osd, msg.oid, snap=snap)
            if payload is None or (attrs or {}).get(WHITEOUT_ATTR) == b"1":
                continue
            attrs = attrs or {}
            size = int(attrs.get(SIZE_ATTR, len(payload)) or len(payload))
            outs: list[tuple[int, bytes, dict[str, bytes]]] = []
            first_read: bytes | None = None
            for op in msg.ops:
                r, d, kv = 0, b"", {}
                if op.op == OP_READ:
                    end = size if not op.length else min(
                        op.off + op.length, size)
                    d = payload[op.off:end]
                    if first_read is None:
                        first_read = d
                elif op.op == OP_STAT:
                    pass
                elif op.op == OP_GETXATTR:
                    v = attrs.get(USER_XATTR_PREFIX + op.name)
                    if v is None:
                        r = -errno.ENODATA
                    else:
                        d = v
                elif op.op == OP_GETXATTRS:
                    kv = {
                        n[len(USER_XATTR_PREFIX):]: v
                        for n, v in attrs.items()
                        if n.startswith(USER_XATTR_PREFIX)
                    }
                else:
                    return None  # vector needs local state we lack
                outs.append((r, d, kv))
            self.perf.inc("rep_degraded_read")
            self._queue_object_repair(pool, pg, msg.oid)
            result = next((r for r, _d, _kv in outs if r != 0), 0)
            return MOSDOpReply(
                tid=msg.tid, result=result, epoch=self.epoch, size=size,
                data=first_read or b"", outs=outs,
            )
        return None

    async def _rep_read_failover(
        self, pool, pg, acting, o: ghobject_t, off: int, length: int
    ) -> bytes | None:
        """Primary-local medium error on a replicated read: serve the
        bytes from a healthy replica instead of bouncing EIO to the
        client (the reference primary reads a replica copy and repairs
        in the background on read errors)."""
        snap = o.snap if o.snap >= 0 else NOSNAP
        for osd in acting:
            if osd in (self.id, CRUSH_ITEM_NONE) or not self.osdmap.is_up(osd):
                continue
            payload, _attrs, _e = await self._read_shard_quiet(
                pool, pg, NO_SHARD, osd, o.name, off=off, length=length,
                snap=snap,
            )
            if payload is not None:
                self.perf.inc("rep_read_failover")
                from ceph_tpu.common.fault_injector import (
                    disk_fault_counters,
                )

                disk_fault_counters().inc("rep_read_failover")
                return payload
        return None

    # -- dispatch ------------------------------------------------------

    async def _dispatch(self, msg: Message) -> None:
        try:
            if isinstance(msg, MOSDMap):
                await self._handle_map(msg)
            elif isinstance(msg, MMgrMap):
                self.mgr_client.handle_mgr_map(msg)
            elif isinstance(msg, MMgrConfigure):
                self.mgr_client.handle_configure(msg)
            elif isinstance(msg, MLogAck):
                self.clog.handle_ack(msg)
            elif isinstance(msg, MConfig):
                self._apply_mon_config(msg)
            elif isinstance(msg, MOSDPing):
                await self._handle_ping(msg)
            elif isinstance(msg, MWatchNotifyAck):
                self._handle_notify_ack(msg)
            elif isinstance(msg, MOSDOp):
                asyncio.ensure_future(self._handle_client_op(msg))
            elif isinstance(msg, MOSDECSubOpWrite):
                t0 = time.monotonic()
                await self._handle_sub_write(msg)
                # shard apply latency — the `ceph osd perf`
                # apply_latency source (never a TrackedOp: sub-op
                # service must stay admission-free)
                self.op_tracker.record_latency(
                    "subop_w", time.monotonic() - t0)
            elif isinstance(msg, MOSDECSubOpRead):
                await self._handle_sub_read(msg)
            elif isinstance(msg, MOSDRepOp):
                t0 = time.monotonic()
                await self._handle_rep_op(msg)
                self.op_tracker.record_latency(
                    "subop_w", time.monotonic() - t0)
            elif isinstance(msg, MOSDPGPush):
                await self._handle_push(msg)
            elif isinstance(msg, MOSDPGQuery):
                # peering messages may wait for map catch-up
                # (_wait_for_epoch): run off the connection's dispatch
                # loop so in-flight client sub-ops on the same pipe
                # don't queue behind the wait (the reference parks
                # these on a waiting_for_map queue the same way)
                self._spawn_peering(self._handle_pg_query(msg))
            elif isinstance(msg, MOSDPGLog):
                self._spawn_peering(self._handle_pg_log(msg))
            elif isinstance(msg, MOSDScrub):
                asyncio.ensure_future(self._handle_scrub(msg))
            elif isinstance(msg, MBackfillReserve):
                await self._handle_backfill_reserve(msg)
            elif isinstance(
                msg,
                (
                    MOSDECSubOpWriteReply, MOSDECSubOpReadReply,
                    MOSDRepOpReply, MOSDPGInfo, MOSDPGLogAck,
                    MOSDOpReply,  # tiering: we client other pools
                ),
            ):
                fut = self._waiters.get(msg.tid)
                if fut and not fut.done():
                    fut.set_result(msg)
            elif isinstance(msg, MOSDPGPushReply):
                fut = self._push_waiters.get(msg.tid)
                if fut and not fut.done():
                    fut.set_result(msg)
        except Exception:
            log.exception("osd.%d: dispatch failed for %r", self.id, msg)

    async def _handle_map(self, msg: MOSDMap) -> None:
        # copy-on-write swap: code that captured self.osdmap mid-pass
        # keeps a stable snapshot (recovery, in-flight ops)
        old_map = self.osdmap
        new_map, gap = apply_map_message(self.osdmap, msg.maps, msg.incs)
        if new_map is not None:
            self.osdmap = new_map
            self._maybe_snap_trim(old_map, new_map)
            self._track_intervals(old_map, new_map)
            self._maybe_split_pgs(old_map, new_map)
            self._gc_removed_pools(old_map, new_map)
            self._warm_ec_profiles()
        if gap:
            # ask the mon for the missing range (or a full map)
            await self._request_map_fill()
        self._map_event.set()
        log.info("osd.%d: map epoch %d", self.id, self.epoch)
        if self.osdmap.max_osd > self.id and self.osdmap.is_up(self.id):
            self._seen_up = True
        if (
            not self.stopping
            and getattr(self, "_seen_up", False)
            and self.osdmap.max_osd > self.id
            and self.osdmap.exists(self.id)
            and not self.osdmap.is_up(self.id)
        ):
            # the map says we are down but we are alive (false failure
            # report, or a mon that hasn't seen our boot): re-assert
            # with a fresh incarnation (OSD::_committed_osd_maps ->
            # start_boot in the reference)
            log.warning("osd.%d: map says I'm down; re-booting", self.id)
            self.incarnation = time.time_ns()
            try:
                await self._mon_conn.send_message(MOSDBoot(
                    osd=self.id, host=self.addr[0], port=self.addr[1],
                    incarnation=self.incarnation,
                ))
            except (ConnectionError, OSError):
                pass  # mon hunt will re-boot us
        if self._recovery_task is None or self._recovery_task.done():
            self._recovery_task = asyncio.ensure_future(self._recover_all())

    def _apply_mon_config(self, msg: MConfig) -> None:
        """Centralized config distribution (MConfig/ConfigMonitor):
        apply the sections addressing this daemon at the 'mon' source —
        below env/cmdline overrides, above file/defaults."""
        for sec in ("global", "osd", f"osd.{self.id}"):
            for name, value in msg.sections.get(sec, {}).items():
                try:
                    # apply_changes (not bare set) so live observers —
                    # backfill reserver caps, mClock knobs — re-read
                    self.conf.apply_changes({name: value}, source="mon")
                except (KeyError, ValueError):
                    log.warning(
                        "osd.%d: ignoring mon config %s=%r", self.id,
                        name, value)

    def _track_intervals(self, old_map, new_map) -> None:
        """Record acting-set interval changes for PGs this OSD touches
        (the PastIntervals bookkeeping): the PREVIOUS map is in hand at
        map-change time, so even a member that just JOINED the acting
        set learns where the PG lived before — the prior set a full
        remap must pull from."""
        if old_map is None:
            return
        # placement-inputs precheck: epochs minted by non-placement
        # changes (pool create, profiles, config) can't move any pg —
        # skip the per-pg mapping work entirely.  CRUSH weights are a
        # placement input too (osd crush reweight!), compared via the
        # per-bucket item weights.
        if (
            old_map.osd_state == new_map.osd_state
            and old_map.osd_weight == new_map.osd_weight
            and old_map.osd_primary_affinity == new_map.osd_primary_affinity
            and old_map.pg_upmap == new_map.pg_upmap
            and old_map.pg_upmap_items == new_map.pg_upmap_items
            and old_map.pg_temp == new_map.pg_temp
            and len(old_map.crush.buckets) == len(new_map.crush.buckets)
            and all(
                bid in new_map.crush.buckets
                and b.items == new_map.crush.buckets[bid].items
                and b.item_weights == new_map.crush.buckets[bid].item_weights
                for bid, b in old_map.crush.buckets.items()
            )
            and old_map.crush.rules == new_map.crush.rules
            and old_map.crush.device_classes == new_map.crush.device_classes
            and all(
                p.pg_num == new_map.pools[pid].pg_num
                and p.crush_rule == new_map.pools[pid].crush_rule
                for pid, p in old_map.pools.items()
                if pid in new_map.pools
            )
        ):
            return
        changed = False
        if not self._past_acting_loaded:
            self._load_past_acting()
        for pid, pool in new_map.pools.items():
            old_pool = old_map.pools.get(pid)
            if old_pool is None:
                continue
            for ps in range(pool.pg_num):
                pg = pg_t(pid, ps)
                _u, _up, acting, _p = new_map.pg_to_up_acting_osds(
                    pg, folded=True)
                if ps >= old_pool.pg_num:
                    # a split child did not exist under the old map:
                    # its history starts at its ANCESTOR's home (the
                    # reference's pg_t::get_ancestor in
                    # PastIntervals::check_new_interval) — that's where
                    # the refiled objects physically sit
                    anc = old_pool.raw_pg_to_pg(pg_t(pid, ps))
                    _u2, _up2, acting_old, _p2 = (
                        old_map.pg_to_up_acting_osds(anc, folded=True))
                else:
                    _u2, _up2, acting_old, _p2 = (
                        old_map.pg_to_up_acting_osds(pg, folded=True))
                if old_pool.pg_num > pool.pg_num:
                    # merge: the dissolving children's members hold
                    # refiled target objects — their old homes are
                    # prior intervals of the TARGET (inverse of the
                    # split-ancestor rule above)
                    for cps in range(pool.pg_num, old_pool.pg_num):
                        if pool.raw_pg_to_pg(pg_t(pid, cps)).ps != ps:
                            continue
                        _u3, _up3, acting_child, _p3 = (
                            old_map.pg_to_up_acting_osds(
                                pg_t(pid, cps), folded=True))
                        if (
                            acting_child
                            and acting_child != acting
                            and (self.id in acting
                                 or self.id in acting_child)
                        ):
                            hist = self._past_acting.setdefault(
                                (pid, ps), [])
                            if acting_child not in hist:
                                hist.append(list(acting_child))
                                del hist[:-16]
                                changed = True
                if acting_old == acting:
                    continue
                if self.id not in acting and self.id not in acting_old:
                    continue
                hist = self._past_acting.setdefault((pid, ps), [])
                if not hist or hist[-1] != acting_old:
                    hist.append(list(acting_old))
                    del hist[:-16]  # bounded
                    changed = True
        if changed:
            self._save_past_acting()

    # the store layer's reserved meta collection (objectstore.py:37,
    # pool -1 can never collide with a real pool)
    from ceph_tpu.store.objectstore import META_COLL as _META_COLL
    _META_OID = "osd_past_intervals"

    def _load_past_acting(self) -> None:
        """Restart path: reload the recorded intervals so a primary
        that reboots across a remap still knows the prior homes (the
        reference persists PastIntervals in pg info the same way)."""
        self._past_acting_loaded = True
        import json as _json

        try:
            raw = self.store.read(
                self._META_COLL, ghobject_t(self._META_OID))
        except (FileNotFoundError, OSError):
            return
        try:
            data = _json.loads(raw)
        except ValueError:
            return
        for k, hist in data.items():
            pid, ps = k.split(".")
            self._past_acting[(int(pid), int(ps))] = hist

    def _save_past_acting(self) -> None:
        import json as _json

        t = Transaction()
        self._ensure_coll(t, self._META_COLL)
        blob = _json.dumps({
            f"{pid}.{ps}": hist
            for (pid, ps), hist in self._past_acting.items()
        }).encode()
        t.touch(self._META_COLL, ghobject_t(self._META_OID))
        t.truncate(self._META_COLL, ghobject_t(self._META_OID), len(blob))
        t.write(self._META_COLL, ghobject_t(self._META_OID), 0, blob)
        try:
            self.store.queue_transaction(t)
        except OSError:
            log.exception("osd.%d: persisting past intervals failed", self.id)

    def _prior_pairs(
        self, pool, pg: pg_t, pairs: list[tuple[int, int]]
    ) -> list[tuple[int, int]]:
        """(shard, osd) candidates from past intervals: members not in
        the current acting set — potential data sources (the prior_set
        role of PastIntervals).  DOWN members stay listed while the map
        still counts them in (not out, not removed): their store
        survives the kill and may hold the newest ACKED shard, so the
        reconcile pass must know they exist to defer destructive
        verdicts until they answer (the reference blocks peering on
        down_osds_we_would_probe the same way; chaos-fuzz-found:
        a write acked degraded on exactly k shards, one holder killed,
        and the rollback fired in the 400ms before it rebooted)."""
        if not self._past_acting_loaded:
            self._load_past_acting()
        key = (pg.pool, pg.ps)
        current = {(s, o) for s, o in pairs}
        om = self.osdmap
        out: list[tuple[int, int]] = []
        seen = set()
        for past in reversed(self._past_acting.get(key, [])):
            for s, o in self._pg_members(pool, past):
                if (s, o) in current or (s, o) in seen:
                    continue
                if o == CRUSH_ITEM_NONE:
                    continue
                if not om.is_up(o) and (
                        not (0 <= o < om.max_osd) or not om.exists(o)
                        or om.is_out(o)):
                    # written off: out (data forfeited to the remap)
                    # or removed — no veto, no probe
                    continue
                seen.add((s, o))
                out.append((s, o))
        return out

    def _maybe_split_pgs(self, old_map, new_map) -> None:
        """PG splitting AND merging, local half (the reference's
        PG::split_colls / OSD::split_pgs and PG::merge_from,
        src/osd/OSD.cc + PG.cc:563): when a pool's pg_num grows, every
        local object whose name now folds to a child ps moves into the
        child's collection via collection_move_rename; when it
        shrinks, dissolving children fold their objects AND pg log
        into the merge target.  The cluster half (children/targets
        placing onto new OSDs) is ordinary recovery: _track_intervals
        records the prior homes (the parent's for split children, the
        children's for merge targets), so the primary pulls from the
        members holding the refiled data.

        Runs on EVERY first map after boot too (old_map None): a crash
        mid-split/merge leaves misfolded objects behind, and the
        reconcile pass refiles them from persistent stores."""
        pools = new_map.pools.items()
        if old_map is not None:
            pools = [
                (pid, p) for pid, p in pools
                if pid in old_map.pools
                and p.pg_num != old_map.pools[pid].pg_num
            ]
        for _pid, pool in pools:
            try:
                merged = self._refile_merge_collections(pool)
                moved = self._refile_split_collections(pool)
            except Exception:
                log.exception("osd.%d: pg resize refile failed", self.id)
                continue
            if moved or merged:
                log.info(
                    "osd.%d: pg resize pool %d: refiled %d objects "
                    "(split) + %d (merge)",
                    self.id, pool.id, moved, merged)
                # resize invalidates the pool's clean verdicts
                for key in list(self._clean_epoch):
                    if key[0] == pool.id:
                        del self._clean_epoch[key]

    def _refile_merge_collections(self, pool) -> int:
        """Fold collections of dissolved PGs (ps >= pg_num) into their
        merge targets: objects move, the child's log merges
        (PGLog.merge_from), and the child collection dies — one
        transaction per child, so a crash leaves the child whole and
        the boot reconcile re-runs it."""
        from ceph_tpu.store.objectstore import META_COLL

        moved = 0
        for c in list(self.store.list_collections()):
            if c.pool != pool.id or c == META_COLL:
                continue
            if c.ps < pool.pg_num:
                continue  # survivor
            target_ps = pool.raw_pg_to_pg(pg_t(pool.id, c.ps)).ps
            dst = coll_t(pool.id, target_ps, c.shard)
            t = Transaction()
            if not self.store.collection_exists(dst):
                t.create_collection(dst)
            try:
                objs = list(self.store.collection_list(c))
            except FileNotFoundError:
                continue
            meta_objs = []
            for o in objs:
                if o.name == PGMETA_OID:
                    meta_objs.append(o)
                    continue
                t.collection_move_rename(c, o, dst, o)
                moved += 1
            child_lg = self._pg_log(c)
            target_lg = self._pg_log(dst)
            target_lg.merge_from(t, child_lg)
            # per-child version sequences are incomparable: the first
            # post-merge recovery pass must backfill-reconcile without
            # listing-based stray reaping (the mon only merges CLEAN
            # pools, so nothing legitimate is pending deletion) — the
            # marker rides the merge transaction and the primary
            # clears it after its first complete pass
            t.omap_setkeys(dst, target_lg.meta, {"merge_pending": b"1"})
            for o in meta_objs:
                t.remove(c, o)
            t.remove_collection(c)
            self.store.queue_transaction(t)
            self._pg_logs.pop(c, None)
            self._clean_epoch.pop((pool.id, c.ps), None)
        return moved

    def _refile_split_collections(self, pool) -> int:
        from ceph_tpu.store.objectstore import META_COLL

        moved = 0
        for c in list(self.store.list_collections()):
            if c.pool != pool.id or c == META_COLL:
                continue
            if c.ps >= pool.pg_num:
                continue  # stale collection beyond the map (merge-only)
            try:
                objs = list(self.store.collection_list(c))
            except FileNotFoundError:
                continue
            t = Transaction()
            made: set = set()
            children: set[int] = set()
            for o in objs:
                if o.name == PGMETA_OID:
                    continue
                newps = pool.raw_pg_to_pg(object_to_pg(pool, o.name)).ps
                if newps == c.ps:
                    continue
                dst = coll_t(pool.id, newps, c.shard)
                if dst not in made and not self.store.collection_exists(dst):
                    t.create_collection(dst)
                    made.add(dst)
                # clones (snap != head) ride along with the same id
                t.collection_move_rename(c, o, dst, o)
                children.add(newps)
                moved += 1
            # the log splits with the data (PGLog::split_into): each
            # child inherits the entries for its objects AND the
            # parent's version bounds, in the SAME transaction
            parent_lg = self._pg_log(c)
            for ps in sorted(children):
                dst = coll_t(pool.id, ps, c.shard)
                parent_lg.split_into(
                    t, self._pg_log(dst),
                    lambda oid, _ps=ps: pool.raw_pg_to_pg(
                        object_to_pg(pool, oid)).ps == _ps,
                )
            if not t.empty():
                self.store.queue_transaction(t)
        return moved

    def _gc_removed_pools(self, old_map, new_map) -> None:
        """Deleted pools leave orphan collections (the reference's
        pg-removal on pool deletion): drop them locally."""
        if old_map is None:
            gone = {
                c.pool for c in self.store.list_collections()
                if c.pool >= 0 and c.pool not in new_map.pools
            }
        else:
            gone = set(old_map.pools) - set(new_map.pools)
        if not gone:
            return
        try:
            t = Transaction()
            for c in list(self.store.list_collections()):
                if c.pool in gone:
                    try:
                        objs = list(self.store.collection_list(c))
                    except FileNotFoundError:
                        continue
                    for o in objs:
                        t.remove(c, o)
                    t.remove_collection(c)
                    self._pg_logs.pop(c, None)
            if not t.empty():
                self.store.queue_transaction(t)
                log.info("osd.%d: removed collections of deleted pools %s",
                         self.id, sorted(gone))
        except Exception:
            # gc must never abort map handling (the map swap already
            # happened; waiters and recovery still need their kicks)
            log.exception("osd.%d: pool gc failed", self.id)

    def _maybe_snap_trim(self, old_map, new_map) -> None:
        """Schedule the snap trimmer for pools whose removed_snaps grew
        (the reference's SnapTrimmer/SnapMapper worker role)."""
        for pid, pool in new_map.pools.items():
            old_pool = old_map.pools.get(pid) if old_map else None
            old_removed = old_pool.removed_snaps if old_pool else set()
            if pool.removed_snaps - old_removed:
                task = asyncio.ensure_future(self._snap_trim(pool))
                # the loop keeps only weak refs to tasks: hold one so a
                # half-finished trim can't be garbage-collected
                self._trim_tasks.add(task)
                task.add_done_callback(self._trim_tasks.discard)

    async def _snap_trim(self, pool) -> None:
        """Purge clones whose every covered snap is removed; update or
        drop the head SnapSet; reap whiteout heads with no clones left.
        Runs on every OSD against its local store — replicas hold the
        same objects, so local deterministic trimming converges."""
        import dataclasses

        removed = pool.removed_snaps
        try:
            colls = [
                c for c in self.store.list_collections() if c.pool == pool.id
            ]
        except Exception:
            return
        for c in colls:
            try:
                objs = self.store.collection_list(c)
            except FileNotFoundError:
                continue
            for o in objs:
                if o.snap < 0:  # head (ghobject default snap = -2)
                    continue
                async with self._obj_lock(pool.id, o.name):
                    try:
                        raw = self.store.getattr(c, o, SNAPS_ATTR)
                    except (KeyError, FileNotFoundError):
                        continue
                    snaps = decode_snaps(raw)
                    live = [sn for sn in snaps if sn not in removed]
                    if live == snaps:
                        continue
                    t = Transaction()
                    head = dataclasses.replace(o, snap=ghobject_t("").snap)
                    if live:
                        t.setattrs(c, o, {SNAPS_ATTR: encode_snaps(live)})
                        # keep the head SnapSet's covered list in step
                        ss = SnapSet.from_bytes(
                            self._getattr_quiet(c, head, SS_ATTR))
                        cl = ss.clone_by_id(o.snap)
                        if cl is not None and cl.snaps != live:
                            cl.snaps = list(live)
                            t.setattrs(c, head, {SS_ATTR: ss.to_bytes()})
                    else:
                        t.remove(c, o)
                        ss = SnapSet.from_bytes(
                            self._getattr_quiet(c, head, SS_ATTR))
                        ss.drop_clone(o.snap)
                        if self.store.exists(c, head):
                            if not ss.clones and self._is_whiteout(c, head):
                                t.remove(c, head)
                            else:
                                t.setattrs(c, head, {SS_ATTR: ss.to_bytes()})
                    try:
                        if getattr(self.store, "blocking_commit", False):
                            await asyncio.to_thread(
                                self.store.queue_transaction, t)
                        else:
                            self.store.queue_transaction(t)
                    except (FileNotFoundError, FileExistsError):
                        pass  # raced a concurrent op; next trim rescans
                await asyncio.sleep(0)

    def _getattr_quiet(self, c, o, name) -> bytes | None:
        try:
            return self.store.getattr(c, o, name)
        except (KeyError, FileNotFoundError):
            return None

    async def _request_map_fill(self) -> None:
        try:
            if self._mon_conn is not None:
                await self._mon_conn.send_message(MMonSubscribe(
                    start_epoch=self.osdmap.epoch if self.osdmap else 0
                ))
        except ConnectionError:
            pass  # mon hunt will re-subscribe

    # -- client ops (the PrimaryLogPG::do_op slice) --------------------

    async def _handle_client_op(self, msg: MOSDOp) -> None:
        tracked = self.op_tracker.create(
            f"osd_op({msg.oid} pool={msg.pool} "
            f"ops={[o.op for o in msg.ops]} tid={msg.tid})",
            op_class="write" if msg.is_write() else "read",
        )
        try:
            self.perf.inc("op")
            if msg.is_write():
                self.perf.inc("op_w")
                self.perf.inc(
                    "op_in_bytes", sum(len(o.data) for o in msg.ops)
                )
            else:
                self.perf.inc("op_r")
            self.dlog.dout(4, "osd.%d: op %s", self.id, tracked.description)
            tracked.mark_event("queued")
            # the queue leg of the cluster trace (stage=queue): joined
            # to the client's trace context when the op carries one, so
            # mClock admission wait is attributable per op
            # tenant tag -> dmclock class (untagged ops ride the
            # built-in client class); cost grows with payload so
            # byte-heavy tenants charge their dmclock tags — and the
            # qos_cost_* fairness counters — proportionally
            klass = msg.qos_class or "client"
            cost = 1.0 + sum(len(o.data) for o in msg.ops) / 65536.0
            q_sp = self.tracer.start_span(
                "op_queue", ctx=msg.trace, stage="queue", oid=msg.oid,
                klass=klass)
            async with self.op_gate.admit(klass, cost=cost):
                self.tracer.finish_span(q_sp)
                tracked.mark_event("executing")
                with self.tracer.span(
                    "do_op", ctx=msg.trace,
                    reqid=msg.reqid, oid=msg.oid, pool=msg.pool,
                    ops=len(msg.ops),
                ) as _sp:
                    token = self._op_span.set(_sp)
                    try:
                        reply = await self._execute_op(msg)
                    finally:
                        try:
                            self._op_span.reset(token)
                        except ValueError:
                            # a task garbage-collected at loop teardown
                            # runs this finally in a foreign Context;
                            # the var dies with the task either way
                            pass
                    _sp.tag(result=reply.result)
            tracked.mark_event("replying")
            if reply.result == 0 and reply.data:
                self.perf.inc("op_out_bytes", len(reply.data))
        except ECConnErrors as e:
            log.warning("osd.%d: op tid %d failed: %r", self.id, msg.tid, e)
            reply = MOSDOpReply(
                tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch
            )
        except Exception:
            log.exception("osd.%d: op tid %d crashed", self.id, msg.tid)
            reply = MOSDOpReply(tid=msg.tid, result=-errno.EIO, epoch=self.epoch)
        try:
            await msg.conn.send_message(reply)
        except ConnectionError:
            pass
        finally:
            tracked.finish()

    async def _execute_op(self, msg: MOSDOp) -> MOSDOpReply:
        """do_op/do_osd_ops dispatch: route the op vector to the pool's
        backend; write vectors serialize per object (the reference's
        ObjectContext write lock, PrimaryLogPG::find_object_context)."""
        pool = self.osdmap.get_pg_pool(msg.pool) if self.osdmap else None
        if pool is None:
            return MOSDOpReply(tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
        if not msg.ops:
            return MOSDOpReply(tid=msg.tid, result=-errno.EINVAL, epoch=self.epoch)
        caps = getattr(msg.conn, "peer_caps", None)
        if caps is not None:
            # OSDCap admission (PrimaryLogPG::do_op op_has_sufficient_caps):
            # the need is the UNION over sub-ops — a write-only cap
            # must not smuggle a read by bundling it with a write —
            # with class calls additionally requiring x; scoped to
            # this pool.  A denial is EPERM, not a retry.
            from ceph_tpu.common.caps import capable
            from ceph_tpu.msg.messages import OP_CALL

            need = set()
            for o in msg.ops:
                if o.op == OP_CALL:
                    need.add("x")
                    from ceph_tpu import cls as _cls

                    cname, _, mname = (o.name or "").partition(".")
                    need.add("w" if _cls.method_is_write(cname, mname)
                             else "r")
                elif o.is_write():
                    need.add("w")
                else:
                    need.add("r")
            pool_name = self.osdmap.pool_names.get(msg.pool, "")
            if not capable(caps, "osd", "".join(sorted(need)),
                           pool=pool_name):
                return MOSDOpReply(
                    tid=msg.tid, result=-errno.EPERM, epoch=self.epoch)
        pg = object_to_pg(pool, msg.oid)
        acting, primary = self._acting(pool, pg)
        if primary != self.id:
            # client raced a map change; tell it to retry on a newer map
            return MOSDOpReply(tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        # peering-before-active: a primary serving its first op of a
        # new interval must adopt the acting set's log state first —
        # else a revived primary mints versions from its STALE
        # last_update, re-basing the version stream over the
        # degraded-window writes its peers hold (counter collision:
        # undetectable as a gap, invisible to missing_from — the
        # stale-shard flake's deepest root).  Bounce until primed.
        if not await self._prime_interval(pool, pg, acting):
            return MOSDOpReply(
                tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        # versions mint under the epoch primacy was verified at, even
        # if the map advances mid-op (see _next_version)
        admit_epoch = self.epoch
        if msg.is_write():
            # fullness gate (reference OSD::_check_full, OSD.cc:890):
            # a write to a PG any of whose acting members the map marks
            # FULL — or whose primary's own store is past the local
            # failsafe — bounces with ENOSPC rather than corrupting a
            # store that has nowhere to put it.  Deletes must pass: they
            # are how an operator recovers from FULL.
            only_deletes = all(
                (not o.is_write()) or o.op in _DELETE_OPS
                for o in msg.ops)
            if not only_deletes:
                om = self.osdmap
                if (
                    self._full_ratio()
                    >= self.conf["osd_failsafe_full_ratio"]
                    or any(o != CRUSH_ITEM_NONE and om.is_full(o)
                           for o in acting)
                ):
                    return MOSDOpReply(
                        tid=msg.tid, result=-errno.ENOSPC,
                        epoch=self.epoch)
        if any(o.op in (OP_WATCH, OP_UNWATCH, OP_NOTIFY) for o in msg.ops):
            return await self._watch_notify_vector(pool, pg, msg)
        tiered = (
            pool.extra.get("tier_of")
            and pool.extra.get("cache_mode") == "writeback"
            and not getattr(msg, "_tier_internal", False)
        )
        # the object lock covers tier admission (present/dirty checks,
        # promote) AND the op itself, so the agent's flush/evict can't
        # interleave with a client op's check-then-act; internal tier
        # ops carry _have_obj_lock and skip re-acquisition
        if (tiered or msg.is_write()) and not getattr(
                msg, "_have_obj_lock", False):
            async with self._obj_lock(pool.id, msg.oid):
                return await self._execute_op_locked(
                    pool, pg, acting, msg, admit_epoch, tiered)
        return await self._execute_op_locked(
            pool, pg, acting, msg, admit_epoch, tiered)

    async def _execute_op_locked(
        self, pool, pg, acting, msg, admit_epoch, tiered,
    ) -> MOSDOpReply:
        if tiered:
            reply = await self._tier_prepare(pool, pg, msg)
            if reply is not None:
                return reply
        if msg.is_write():
            if msg.snapid != NOSNAP:
                return MOSDOpReply(
                    tid=msg.tid, result=-errno.EROFS, epoch=self.epoch)
            if pool.is_erasure():
                ec = self._ec_for(pool)
                return await self._ec_write_vector(
                    pool, pg, acting, msg, ec, self._sinfo(ec),
                    admit_epoch,
                )
            return await self._rep_write_vector(
                pool, pg, acting, msg, admit_epoch)
        if pool.is_erasure():
            ec = self._ec_for(pool)
            return await self._ec_read_vector(
                pool, pg, acting, msg, ec, self._sinfo(ec)
            )
        return await self._rep_read_vector(pool, pg, acting, msg)

    # -- watch/notify (PrimaryLogPG watch/notify + MWatchNotify) -------

    async def _watch_notify_vector(self, pool, pg, msg) -> MOSDOpReply:
        import base64
        import json

        outs = []
        for o in msg.ops:
            r, d, kv = 0, b"", {}
            key = (pool.id, msg.oid)
            if o.op not in (OP_WATCH, OP_UNWATCH, OP_NOTIFY):
                # watch vectors are control-only; silently "succeeding"
                # a data op here would drop it
                outs.append((-errno.EOPNOTSUPP, b"", {}))
                continue
            if o.op == OP_WATCH:
                self._watchers.setdefault(key, {})[
                    (msg.src, o.off)
                ] = msg.conn
            elif o.op == OP_UNWATCH:
                self._watchers.get(key, {}).pop((msg.src, o.off), None)
            elif o.op == OP_NOTIFY:
                notify_id = next(self._tids)
                timeout = (o.length or 5000) / 1000.0
                watchers = dict(self._watchers.get(key, {}))
                acks: list[tuple] = []
                missed: list[tuple] = []
                waits = []
                for (entity, cookie), conn in watchers.items():
                    fut = asyncio.get_running_loop().create_future()
                    self._notify_waiters[(notify_id, entity, cookie)] = fut
                    try:
                        await conn.send_message(MWatchNotify(
                            notify_id=notify_id, cookie=cookie,
                            oid=msg.oid, pool=pool.id, payload=o.data,
                        ))
                        waits.append((entity, cookie, fut))
                    except (ConnectionError, OSError):
                        # dead watcher: drop it (client linger would
                        # re-establish in the reference)
                        self._watchers.get(key, {}).pop((entity, cookie), None)
                        self._notify_waiters.pop((notify_id, entity, cookie), None)
                deadline = asyncio.get_running_loop().time() + timeout
                for entity, cookie, fut in waits:
                    remaining = deadline - asyncio.get_running_loop().time()
                    try:
                        ack = await asyncio.wait_for(
                            fut, max(0.001, remaining)
                        )
                        acks.append((entity, cookie, ack.reply))
                    except asyncio.TimeoutError:
                        missed.append((entity, cookie))
                    finally:
                        self._notify_waiters.pop((notify_id, entity, cookie), None)
                d = json.dumps({
                    "acks": [
                        [list(e), c, base64.b64encode(rep).decode()]
                        for e, c, rep in acks
                    ],
                    "timeouts": [[list(e), c] for e, c in missed],
                }).encode()
            outs.append((r, d, kv))
        data = next((d for _r, d, _kv in outs if d), b"")
        result = next((r for r, _d, _kv in outs if r != 0), 0)
        return MOSDOpReply(
            tid=msg.tid, result=result, epoch=self.epoch, data=data,
            outs=outs,
        )

    def _handle_notify_ack(self, msg: MWatchNotifyAck) -> None:
        fut = self._notify_waiters.get((msg.notify_id, msg.src, msg.cookie))
        if fut and not fut.done():
            fut.set_result(msg)

    # -- replicated backend -------------------------------------------

    # -- snapshots (make_writeable / find_object_context twins) --------

    def _load_snapset(self, c: coll_t, oid: str) -> SnapSet:
        try:
            return SnapSet.from_bytes(
                self.store.getattr(c, ghobject_t(oid), SS_ATTR))
        except (KeyError, FileNotFoundError):
            return SnapSet()

    def _is_whiteout(self, c: coll_t, o: ghobject_t) -> bool:
        try:
            return self.store.getattr(c, o, WHITEOUT_ATTR) == b"1"
        except (KeyError, FileNotFoundError):
            return False

    @staticmethod
    def _effective_snapc(pool, msg) -> SnapContext:
        """Client self-managed context, else the pool-snap context
        (pg_pool_t::get_snap_context fallback)."""
        if msg.snaps:
            return SnapContext(msg.snap_seq, list(msg.snaps))
        return pool.get_snap_context()

    def _resolve_read_object(
        self, c: coll_t, oid: str, snapid: int
    ) -> tuple[ghobject_t, int] | int:
        """find_object_context: map (oid, snapid) to the store object
        serving that snap.  Returns (ghobject, errno 0) or an errno."""
        head = ghobject_t(oid)
        if snapid == NOSNAP:
            if not self.store.exists(c, head) or self._is_whiteout(c, head):
                return errno.ENOENT
            return head, 0
        ss = self._load_snapset(c, oid)
        target = ss.resolve(snapid)
        if target is None:
            return errno.ENOENT  # no clone covers it: absent at that snap
        if target == NOSNAP:
            # no clone covers it: the head serves the read only if no
            # write happened since the snap (snapid > seq); otherwise
            # the snap's content is gone (trimmed or never existed)
            if snapid <= ss.seq:
                return errno.ENOENT
            if not self.store.exists(c, head) or self._is_whiteout(c, head):
                return errno.ENOENT
            return head, 0
        clone = ghobject_t(oid, snap=target)
        if not self.store.exists(c, clone):
            return errno.ENOENT
        return clone, 0

    async def _rep_read_vector(self, pool, pg, acting, msg) -> MOSDOpReply:
        c = self._shard_coll(pool, pg, NO_SHARD)
        if any(o.op == OP_LIST_SNAPS for o in msg.ops):
            ss = self._load_snapset(c, msg.oid)
            return MOSDOpReply(
                tid=msg.tid, result=0, epoch=self.epoch, data=ss.to_bytes())
        resolved = self._resolve_read_object(c, msg.oid, msg.snapid)
        if isinstance(resolved, int):
            if resolved == errno.ENOENT and msg.oid in self._read_error_ledger:
                # the hole is OURS: a medium-error quarantine removed
                # the local copy and its repair hasn't landed yet —
                # serve the read degraded from a replica instead of
                # returning ENOENT for an object the cluster still has
                snap = NOSNAP
                serve = msg.snapid == NOSNAP
                if not serve:
                    tgt = self._load_snapset(c, msg.oid).resolve(msg.snapid)
                    if tgt is not None and tgt != NOSNAP:
                        snap, serve = tgt, True
                if serve:
                    reply = await self._rep_degraded_read(
                        pool, pg, acting, msg, snap)
                    if reply is not None:
                        return reply
            return MOSDOpReply(
                tid=msg.tid, result=-resolved, epoch=self.epoch)
        o, _ = resolved
        size = self.store.stat(c, o)
        outs: list[tuple[int, bytes, dict[str, bytes]]] = []
        first_read: bytes | None = None
        for op in msg.ops:
            r, d, kv = 0, b"", {}
            if op.op == OP_READ:
                try:
                    d = self.store.read(c, o, op.off, op.length or None)
                except OSError as e:
                    if (e.errno or errno.EIO) != errno.EIO:
                        raise
                    # local medium error: fail over to a healthy
                    # replica instead of returning EIO to the client;
                    # the ledger/quarantine machinery repairs the local
                    # copy in the background
                    self._note_medium_error(
                        pool, pg, NO_SHARD, msg.oid,
                        snap=o.snap if o.snap >= 0 else NOSNAP)
                    d = await self._rep_read_failover(
                        pool, pg, acting, o, op.off, op.length or 0)
                    if d is None:
                        r, d = -errno.EIO, b""
                if first_read is None:
                    first_read = d
            elif op.op == OP_STAT:
                pass
            elif op.op == OP_GETXATTR:
                try:
                    d = self.store.getattr(c, o, USER_XATTR_PREFIX + op.name)
                except KeyError:
                    r = -errno.ENODATA
            elif op.op == OP_GETXATTRS:
                kv = {
                    name[len(USER_XATTR_PREFIX):]: v
                    for name, v in self.store.getattrs(c, o).items()
                    if name.startswith(USER_XATTR_PREFIX)
                }
            elif op.op == OP_OMAP_GETKEYS:
                kv = {k: b"" for k in self.store.omap_get(c, o)}
            elif op.op == OP_OMAP_GETVALS:
                kv = self.store.omap_get(c, o)
            elif op.op == OP_OMAP_GETVALSBYKEYS:
                kv = self.store.omap_get_values(c, o, op.keys)
            elif op.op == OP_CALL:
                from ceph_tpu import cls as _cls

                cname, _, meth = op.name.partition(".")
                ctx = _cls.MethodContext(self.store, c, o)
                r, d = _cls.call(cname, meth, ctx, op.data)
            else:
                r = -errno.EOPNOTSUPP
            outs.append((r, d, kv))
        result = next((r for r, _d, _kv in outs if r != 0), 0)
        return MOSDOpReply(
            tid=msg.tid, result=result, epoch=self.epoch, size=size,
            data=first_read or b"", outs=outs,
        )

    def _rep_effects(
        self, c: coll_t, o: ghobject_t, ops, ss: SnapSet | None = None
    ) -> tuple[list, int, bool] | int:
        """Resolve a client write vector into a deterministic effect
        vector + final size (the primary's role before MOSDRepOp ships
        the transaction in the reference).  Returns an errno on guard
        failure.  ``ss`` (the object's SnapSet) serves ROLLBACK."""
        from ceph_tpu.msg.messages import OSDOp

        exists = self.store.exists(c, o) and not self._is_whiteout(c, o)
        size = self.store.stat(c, o) if exists else 0
        effects: list[OSDOp] = []
        outs: list[tuple[int, bytes, dict]] = []
        expanded: list[OSDOp] = []
        for op in ops:
            if op.op == OP_CALL:
                # run the object-class method on the primary; its
                # recorded mutations splice into the effect vector so
                # class side effects replicate atomically (objclass
                # dispatch, src/osd/PrimaryLogPG.cc CEPH_OSD_OP_CALL)
                from ceph_tpu import cls as _cls

                cname, _, meth = op.name.partition(".")
                ctx = _cls.MethodContext(self.store, c, o)
                rc, outdata = _cls.call(cname, meth, ctx, op.data)
                outs.append((rc, outdata, {}))
                if rc < 0:
                    return -rc
                expanded.extend(ctx.effects)
            else:
                outs.append((0, b"", {}))
                expanded.append(op)
        for op in expanded:
            if op.op == OP_CREATE:
                if op.off and exists:
                    return errno.EEXIST
                exists = True
                effects.append(OSDOp(OP_CREATE))
            elif op.op == OP_WRITE_FULL:
                effects.append(OSDOp(OP_WRITE_FULL, data=op.data))
                size, exists = len(op.data), True
            elif op.op == OP_WRITE:
                effects.append(OSDOp(OP_WRITE, off=op.off, data=op.data))
                size, exists = max(size, op.off + len(op.data)), True
            elif op.op == OP_APPEND:
                effects.append(OSDOp(OP_WRITE, off=size, data=op.data))
                size, exists = size + len(op.data), True
            elif op.op == OP_ZERO:
                end = min(size, op.off + op.length)
                if op.off < end:
                    effects.append(OSDOp(OP_ZERO, off=op.off, length=end - op.off))
                exists = True
            elif op.op == OP_TRUNCATE:
                effects.append(OSDOp(OP_TRUNCATE, off=op.off))
                size, exists = op.off, True
            elif op.op == OP_SETXATTR:
                effects.append(OSDOp(OP_SETXATTR, name=op.name, data=op.data))
                exists = True
            elif op.op == OP_RMXATTR:
                effects.append(OSDOp(OP_RMXATTR, name=op.name))
                exists = True
            elif op.op == OP_OMAP_SETKEYS:
                effects.append(OSDOp(OP_OMAP_SETKEYS, kv=op.kv))
                exists = True
            elif op.op == OP_OMAP_RMKEYS:
                effects.append(OSDOp(OP_OMAP_RMKEYS, keys=op.keys))
                exists = True
            elif op.op == OP_OMAP_CLEAR:
                effects.append(OSDOp(OP_OMAP_CLEAR))
                exists = True
            elif op.op == OP_DELETE:
                if not exists:
                    # absent or whiteout head: nothing to delete (a
                    # second delete must not remove the snapdir anchor)
                    return errno.ENOENT
                effects.append(OSDOp(OP_DELETE))
                exists, size = False, 0
            elif op.op == OP_ROLLBACK:
                # CEPH_OSD_OP_ROLLBACK (PrimaryLogPG::_rollback_to):
                # restore head content from the clone serving op.off
                target = ss.resolve(op.off) if ss is not None else NOSNAP
                if target is None:
                    return errno.ENOENT
                if target == NOSNAP:
                    if not exists:
                        return errno.ENOENT
                    continue  # head already serves that snap: no-op
                clone = ghobject_t(o.name, snap=target)
                if not self.store.exists(c, clone):
                    return errno.ENOENT
                data = bytes(self.store.read(c, clone))
                effects.append(OSDOp(OP_WRITE_FULL, data=data))
                effects.append(OSDOp(OP_OMAP_CLEAR))
                kv = self.store.omap_get(c, clone)
                if kv:
                    effects.append(OSDOp(OP_OMAP_SETKEYS, kv=kv))
                for name, v in self.store.getattrs(c, clone).items():
                    if name.startswith(USER_XATTR_PREFIX):
                        effects.append(OSDOp(
                            OP_SETXATTR,
                            name=name[len(USER_XATTR_PREFIX):], data=v))
                size, exists = len(data), True
            else:
                return errno.EOPNOTSUPP
        # an object deleted mid-vector and rewritten afterwards is not a
        # delete; only the final state counts for the log entry
        return effects, size, not exists, outs

    def _rep_effect_txn(
        self, pool, pg, oid, effects, attrs, version: eversion_t,
        delete_final: bool, reqid: str = "",
    ) -> Transaction:
        """Build the store transaction for an effect vector + its
        pg-log entry (primary and replicas run the identical code)."""
        c = self._shard_coll(pool, pg, NO_SHARD)
        o = ghobject_t(oid)
        t = Transaction()
        self._ensure_coll(t, c)
        # track existence through the vector: an earlier op in this SAME
        # transaction may create the object, so a build-time store.exists
        # check alone would drop a later remove
        obj_exists = self.store.exists(c, o)
        for op in effects:
            if op.op in (OP_CREATE,):
                t.touch(c, o)
            elif op.op == OP_WRITE_FULL:
                t.touch(c, o).truncate(c, o, len(op.data)).write(c, o, 0, op.data)
            elif op.op == OP_WRITE:
                t.touch(c, o).write(c, o, op.off, op.data)
            elif op.op == OP_ZERO:
                t.zero(c, o, op.off, op.length)
            elif op.op == OP_TRUNCATE:
                t.touch(c, o).truncate(c, o, op.off)
            elif op.op == OP_SETXATTR:
                t.setattrs(c, o, {USER_XATTR_PREFIX + op.name: op.data})
            elif op.op == OP_RMXATTR:
                t.touch(c, o).rmattr(c, o, USER_XATTR_PREFIX + op.name)
            elif op.op == OP_OMAP_SETKEYS:
                t.omap_setkeys(c, o, op.kv)
            elif op.op == OP_OMAP_RMKEYS:
                t.omap_rmkeys(c, o, op.keys)
            elif op.op == OP_OMAP_CLEAR:
                t.omap_clear(c, o)
            elif op.op == OP_SNAP_CLONE:
                # make_writeable COW: snapshot the head into its clone
                # before the rest of the vector mutates it
                clone = ghobject_t(oid, snap=op.off)
                if obj_exists and not self.store.exists(c, clone):
                    t.clone(c, o, clone)
                    t.setattrs(c, clone, {SNAPS_ATTR: op.data})
                continue
            elif op.op == OP_DELETE:
                if obj_exists:
                    t.remove(c, o)
                obj_exists = False
                continue
            obj_exists = True
        if not delete_final:
            t.setattrs(c, o, attrs)
        if version > ZERO:
            lg = self._pg_log(c)
            if version > lg.info.last_update:
                prior = self._object_version(c, o)
                lg.append(t, pg_log_entry_t(
                    DELETE if delete_final else MODIFY, oid, version, prior,
                    reqid,
                ))
                self._pg_log_trim(t, lg)
        return t

    async def _rep_replicated_at(
        self, pool, pg, pairs, oid: str, logged_v, lg,
    ) -> bool:
        """True when every acting member verifiably serves ``oid`` at
        >= ``logged_v`` — or verifiably lacks it while the newest
        logged op for the oid is a DELETE (absence is then the
        replicated state, not a hole).  An unreachable member is
        UNVERIFIED, never vouched for: the dup reply's 0 is a commit
        claim, and claiming it for redundancy nobody can see is how
        acked writes end up one-copy on a size-2 pool."""
        latest_op = None
        for v in sorted(lg.entries, reverse=True):
            if lg.entries[v].oid == oid:
                latest_op = lg.entries[v].op
                break
        for s, o2 in pairs:
            if o2 == self.id:
                c = self._shard_coll(pool, pg, s)
                go = ghobject_t(oid)
                present = self.store.exists(c, go)
                ver = self._object_version(c, go) if present else ZERO
            else:
                try:
                    payload, attrs = await self._probe_shard(
                        pool, pg, s, o2, oid)
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    return False
                present = payload is not None
                ver = (_v_parse((attrs or {}).get(VERSION_ATTR))
                       if present else ZERO)
            if present:
                if ver < logged_v:
                    return False
            elif latest_op != DELETE:
                return False
        return True

    async def _rep_write_vector(self, pool, pg, acting, msg,
                                admit_epoch: int | None = None) -> MOSDOpReply:
        c = self._shard_coll(pool, pg, NO_SHARD)
        o = ghobject_t(msg.oid)
        lg = self._pg_log(c)
        if msg.reqid and msg.reqid in lg.reqids:
            # duplicate of an applied op — but the retry exists
            # BECAUSE something failed, and a fan-out that died
            # mid-replication may have left a replica stale.  Verify
            # every acting member actually serves the logged version
            # before vouching for the commit (the EC dup path's PR-3
            # discipline, now on the replicated path too: vouching
            # blind acked writes whose redundancy was still degraded
            # and left the stale-copy flake for scrub to find).
            logged_v = lg.reqids[msg.reqid]
            pairs = self._pg_members(pool, acting)
            if await self._rep_replicated_at(
                    pool, pg, pairs, msg.oid, logged_v, lg):
                return MOSDOpReply(
                    tid=msg.tid, result=0, epoch=self.epoch)
            try:
                await self._reconcile_object(
                    pool, pg, pairs, msg.oid, have_lock=True)
            except Exception:
                log.exception(
                    "osd.%d: dup-retry reconcile of %s failed",
                    self.id, msg.oid)
            if await self._rep_replicated_at(
                    pool, pg, pairs, msg.oid, logged_v, lg):
                return MOSDOpReply(
                    tid=msg.tid, result=0, epoch=self.epoch)
            self._queue_object_repair(pool, pg, msg.oid)
            return MOSDOpReply(
                tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        # make_writeable: clone-on-write under a newer SnapContext
        from ceph_tpu.msg.messages import OSDOp

        snapc = self._effective_snapc(pool, msg)
        if snapc.snaps and not snapc.valid():
            return MOSDOpReply(tid=msg.tid, result=-errno.EINVAL, epoch=self.epoch)
        ss = self._load_snapset(c, msg.oid)
        live_head = self.store.exists(c, o) and not self._is_whiteout(c, o)
        cow: list[OSDOp] = []
        if live_head and ss.needs_cow(snapc):
            clone = ss.make_clone(snapc, self.store.stat(c, o))
            cow.append(OSDOp(
                OP_SNAP_CLONE, off=clone.id, data=encode_snaps(clone.snaps)))
        else:
            ss.advance_seq(snapc)
        resolved = self._rep_effects(c, o, msg.ops, ss=ss)
        if isinstance(resolved, int):
            return MOSDOpReply(tid=msg.tid, result=-resolved, epoch=self.epoch)
        effects, size, delete, call_outs = resolved
        effects = cow + effects
        version = self._next_version(c, admit_epoch)
        if version is None:
            return MOSDOpReply(
                tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        attrs = {
            SIZE_ATTR: str(size).encode(),
            VERSION_ATTR: _v_bytes(version),
        }
        if ss.seq or ss.clones:
            attrs[SS_ATTR] = ss.to_bytes()
        attrs[WHITEOUT_ATTR] = b"0"
        if delete and ss.clones:
            # clones still anchor to this name: leave a whiteout head
            # (the reference's snapdir object role) instead of removing
            delete = False
            size = 0
            effects.append(OSDOp(OP_CREATE))
            attrs[SIZE_ATTR] = b"0"
            attrs[WHITEOUT_ATTR] = b"1"
        t = self._rep_effect_txn(
            pool, pg, msg.oid, effects, attrs, version, delete,
            reqid=msg.reqid,
        )
        parent_sp = self._op_span.get()
        await self._store_latency_gate()
        with self._maybe_span(
            "store_commit", parent=parent_sp, stage="store", oid=msg.oid,
        ):
            if getattr(self.store, "blocking_commit", False):
                await asyncio.to_thread(self.store.queue_transaction, t)
            else:
                self.store.queue_transaction(t)
        waits = []
        for osd in acting:
            if osd in (self.id, CRUSH_ITEM_NONE):
                continue
            tid = next(self._tids)
            waits.append(self._traced_sub_op(
                "rep_sub_op", parent_sp, NO_SHARD, osd, msg.reqid,
                MOSDRepOp(
                    tid=tid, pg=pg, from_osd=self.id, oid=msg.oid,
                    attrs=attrs, delete=delete, epoch=self.epoch,
                    version=version, ops=effects, reqid=msg.reqid,
                ), tid))
        if waits:
            replies = await asyncio.gather(*waits, return_exceptions=True)
            lost = False
            for rep in replies:
                if isinstance(rep, asyncio.CancelledError):
                    raise rep
                if isinstance(rep, ECConnErrors + (OSError,)):
                    lost = True
                elif isinstance(rep, BaseException):
                    raise rep
                elif rep.result != 0:
                    return MOSDOpReply(
                        tid=msg.tid, result=rep.result, epoch=self.epoch)
                elif getattr(rep, "floored", False):
                    # replica pinned its contiguity floor mid-traffic:
                    # queue a recovery pass (no map change will)
                    self._queue_pg_pass(pool, pg)
            if lost:
                # partial replication: the primary applied + logged but
                # a replica never confirmed.  Reconcile NOW under the
                # object lock (push the logged version over the stale
                # replica) so the client's dup-detected retry vouches
                # for a write that actually replicated — not one the
                # next scrub flags as a version mismatch
                repaired = False
                try:
                    repaired = await self._reconcile_object(
                        pool, pg, self._pg_members(pool, acting),
                        msg.oid, have_lock=True)
                except Exception:
                    log.exception(
                        "osd.%d: post-partial-repop reconcile of %s "
                        "failed", self.id, msg.oid)
                if not repaired:
                    self._queue_object_repair(pool, pg, msg.oid)
                return MOSDOpReply(
                    tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        first_out = next((d for _r, d, _kv in call_outs if d), b"")
        return MOSDOpReply(
            tid=msg.tid, result=0, epoch=self.epoch, outs=call_outs,
            data=first_out,
        )

    async def _apply_full_object(
        self, pool, pg, oid, data, attrs, delete=False,
        version: eversion_t = ZERO,
    ):
        await self._apply_shard_write_async(
            pool, pg, NO_SHARD, oid, data, attrs, delete=delete,
            version=version,
        )

    async def _handle_rep_op(self, msg: MOSDRepOp) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        result = 0
        try:
            if msg.ops:
                t = self._rep_effect_txn(
                    pool, msg.pg, msg.oid, msg.ops, msg.attrs, msg.version,
                    msg.delete, reqid=msg.reqid,
                )
                await self._store_latency_gate()
                with self._maybe_span(
                    "store_commit", ctx=msg.trace, stage="store",
                    oid=msg.oid,
                ):
                    if getattr(self.store, "blocking_commit", False):
                        await asyncio.to_thread(
                            self.store.queue_transaction, t)
                    else:
                        self.store.queue_transaction(t)
            else:
                # legacy full-object payload (recovery pushes reuse this)
                await self._apply_full_object(
                    pool, msg.pg, msg.oid, msg.data, msg.attrs, msg.delete,
                    msg.version,
                )
        except OSError as e:
            result = -(e.errno or errno.EIO)
        # report a pinned contiguity floor so the primary queues a
        # recovery pass (see MOSDECSubOpWriteReply.floored)
        floored = False
        if result == 0 and msg.version > ZERO:
            lg = self._pg_log(self._shard_coll(pool, msg.pg, NO_SHARD))
            floored = (lg.contig_floor is not None
                       and lg.info.last_update == msg.version)
        await msg.conn.send_message(MOSDRepOpReply(
            tid=msg.tid, pg=msg.pg, from_osd=self.id, result=result,
            epoch=self.epoch, floored=floored,
        ))

