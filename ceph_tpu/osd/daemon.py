"""OSD daemon: the object-service process of the mini-cluster.

The asyncio twin of the reference OSD's op path (src/osd/OSD.cc
dispatch -> PrimaryLogPG::do_op -> PGBackend submit, SURVEY.md §3.1):
boots into the mon (MOSDBoot), subscribes to maps, serves client ops as
primary, fans EC chunk writes/reads out to shard peers
(MOSDECSubOpWrite/Read — ECBackend::submit_transaction/handle_sub_*,
src/osd/ECBackend.cc:943,1022,1472), replicates full objects for
replicated pools (MOSDRepOp), and reconstructs missing shards after map
changes (RecoveryBackend::continue_recovery_op, ECBackend.cc:563 →
decode via ECUtil + MOSDPGPush).

Data layout matches the reference: one collection per PG shard
(coll_t(pool, ps, shard), ECTransaction.cc:80-88), chunk payloads at
chunk offsets, per-shard HashInfo crc chains in the ``hinfo`` xattr
(ECUtil.cc:164-248) and the logical size in ``_size`` (the object_info
analogue).

Consistency is log-based (ceph_tpu/osd/pglog.py): every write commits
a pg-log entry with the data; after a map change the primary runs
peering-lite (_recover_pg): pg_info exchange, log adoption from
newer members, per-peer missing sets from the log delta, and full
backfill with authoritative-list stray removal when trimmed past a
peer.  Reads verify object versions across chunks so revived members
with stale shards cannot corrupt results.

Deliberate simplifications vs the reference: the peering state machine
is a linear pass rather than boost::statechart, there is no
ObjectContext rw-locking (recovery races resolve by version guards and
the next pass), and sub-chunk (CLAY) recovery I/O goes through full
chunk reads.
"""

from __future__ import annotations

import asyncio
import errno
import itertools
import logging
import time

import numpy as np

from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.ec import registry as ec_registry
from ceph_tpu.msg.messages import (
    PING,
    PING_REPLY,
    MMonSubscribe,
    MConfig,
    MOSDBeacon,
    MOSDBoot,
    MOSDECSubOpRead,
    MOSDECSubOpReadReply,
    MOSDECSubOpWrite,
    MOSDECSubOpWriteReply,
    MOSDFailure,
    MOSDMap,
    MOSDPing,
    MWatchNotify,
    MWatchNotifyAck,
    MOSDOp,
    MOSDOpReply,
    MOSDPGPush,
    MOSDPGPushReply,
    MOSDRepOp,
    MOSDRepOpReply,
    MOSDPGInfo,
    MOSDPGLog,
    MOSDPGLogAck,
    MOSDPGQuery,
    MBackfillReserve,
    MOSDScrub,
    MOSDScrubReply,
    OP_APPEND,
    OP_CALL,
    OP_CREATE,
    OP_DELETE,
    OP_GETXATTR,
    OP_GETXATTRS,
    OP_OMAP_CLEAR,
    OP_OMAP_GETKEYS,
    OP_OMAP_GETVALS,
    OP_OMAP_GETVALSBYKEYS,
    OP_OMAP_RMKEYS,
    OP_OMAP_SETKEYS,
    OP_LIST_SNAPS,
    OP_READ,
    OP_RMXATTR,
    OP_ROLLBACK,
    OP_SNAP_CLONE,
    OP_SETXATTR,
    OP_STAT,
    OP_TRUNCATE,
    OP_NOTIFY,
    OP_UNWATCH,
    OP_WATCH,
    OP_WRITE,
    OP_WRITE_FULL,
    OP_ZERO,
)
from ceph_tpu.msg.messenger import Connection, Message, Messenger
from ceph_tpu.ops.hashing import ceph_str_hash_rjenkins
from ceph_tpu.osd import ecutil
from ceph_tpu.osd.mapenc import apply_map_message
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.pglog import (
    DELETE,
    MODIFY,
    PGMETA_OID,
    ZERO,
    PGLog,
    eversion_t,
    pg_log_entry_t,
)
from ceph_tpu.osd.snaps import (
    NOSNAP,
    SNAPS_ATTR,
    SS_ATTR,
    WHITEOUT_ATTR,
    SnapContext,
    SnapSet,
    decode_snaps,
    encode_snaps,
)
from ceph_tpu.osd.types import PgPool, pg_t
from ceph_tpu.store import MemStore, Transaction, coll_t, ghobject_t

log = logging.getLogger("ceph_tpu.osd")

NO_SHARD = -1
STRIPE_UNIT = 4096  # logical bytes per data chunk per stripe
SUBOP_TIMEOUT = 30.0

SIZE_ATTR = "_size"
HINFO_ATTR = "hinfo"
VERSION_ATTR = "_v"  # object_info version (oi attr analogue)
USER_XATTR_PREFIX = "u_"  # client xattrs, namespaced off internal attrs


def _read_extents(store, c, o, extents) -> bytes:
    """Serve a multi-run ranged read from ONE covering store read:
    checksummed engines (BlockStore) verify each blob once instead of
    once per run — CLAY sub-chunk repairs issue many runs per chunk."""
    lo = min(eo for eo, _ln in extents)
    hi = max(eo + ln for eo, ln in extents)
    span = bytes(store.read(c, o, lo, hi - lo))
    # per-run slices clamp at the object size exactly like the
    # individual reads they replace (no padding)
    return b"".join(span[eo - lo : eo - lo + ln] for eo, ln in extents)


class ECFetchError(Exception):
    """A version-consistent EC fetch could not complete."""

    def __init__(self, eno: int):
        super().__init__(errno.errorcode.get(eno, str(eno)))
        self.errno = eno


def _v_bytes(v: eversion_t) -> bytes:
    return v.key().encode()


def _v_parse(raw: bytes | None) -> eversion_t:
    if not raw:
        return ZERO
    e, v = raw.decode().split(".")
    return eversion_t(int(e), int(v))


def object_to_pg(pool: PgPool, oid: str) -> pg_t:
    """object_locator_to_pg (src/osd/osd_types.cc): name hash -> raw pg
    (the mapping pipeline folds it into pg_num)."""
    return pg_t(pool.id, int(ceph_str_hash_rjenkins(oid)))


class OSDDaemon:
    def __init__(
        self,
        osd_id: int,
        mon_addr: tuple[str, int],
        store: MemStore | None = None,
        beacon_interval: float | None = None,
        conf=None,
        auth=None,
        encode_service=None,
    ):
        from ceph_tpu.common import ConfigProxy, get_perf_counters

        self.id = osd_id
        # one address or a monmap; the daemon hunts for a live monitor
        self.mon_addrs: list[tuple[str, int]] = (
            list(mon_addr) if isinstance(mon_addr, list) else [mon_addr]
        )
        self.mon_addr = self.mon_addrs[0]
        self.conf = conf if conf is not None else ConfigProxy()
        self.store = store or MemStore()
        # multi-device encode farm (production ECSubWrite-fan-out seam,
        # SURVEY.md §2.9); resolved lazily so single-device processes
        # never touch jax at boot
        self._encode_service = encode_service
        self._encode_service_resolved = encode_service is not None
        self.messenger = Messenger(
            ("osd", osd_id), self._dispatch, on_reset=self._on_reset,
            auth=auth,
            compress_mode=self.conf["ms_compress_mode"],
            compress_algorithm=self.conf["ms_compress_algorithm"],
            compress_min_size=self.conf["ms_compress_min_size"],
        )
        self.messenger.inject_socket_failures = self.conf[
            "ms_inject_socket_failures"
        ]
        self.perf = get_perf_counters(f"osd.{osd_id}")
        from ceph_tpu.common import DoutLogger, OpTracker
        from ceph_tpu.common.tracing import Tracer

        # per-incarnation tracer: a restarted daemon must not inherit a
        # dead daemon's span ring
        self.tracer = Tracer(f"osd.{osd_id}")

        # slow-op forensics (TrackedOp.h:121) + per-subsystem dout
        self.op_tracker = OpTracker(
            history_size=self.conf["osd_op_history_size"],
            slow_threshold=self.conf["osd_op_complaint_time"],
        )
        self.dlog = DoutLogger("osd", self.conf, name_suffix=str(osd_id))
        self._admin: object | None = None
        self._log_keep = self.conf["osd_min_pg_log_entries"]
        self.osdmap: OSDMap | None = None
        self.beacon_interval = (
            beacon_interval
            if beacon_interval is not None
            else self.conf["osd_beacon_report_interval"]
        )
        self.addr: tuple[str, int] | None = None
        self._mon_conn: Connection | None = None
        self._tids = itertools.count(1)
        self._waiters: dict[int, asyncio.Future] = {}
        self._push_waiters: dict[int, asyncio.Future] = {}  # by push tid
        # per-object write serialization (the ObjectContext rw-lock
        # analogue): RMW read/encode/fan-out must not interleave with
        # another write to the same object
        self._obj_locks: dict[tuple[int, str], asyncio.Lock] = {}
        # watch/notify state (primary-local; the reference persists
        # watchers in object_info and re-establishes via client linger —
        # here clients re-watch after a primary change)
        self._watchers: dict[tuple[int, str], dict[tuple, object]] = {}
        self._notify_waiters: dict[tuple, asyncio.Future] = {}
        self._trim_tasks: set = set()
        import contextvars

        # root span of the client op executing in THIS task (ops run as
        # concurrent tasks, so a plain attribute would cross-parent)
        self._op_span = contextvars.ContextVar(
            f"osd{osd_id}_op_span", default=None)
        self._recovering_pgs: set[tuple[int, int]] = set()
        # (pool, ps) -> newest epoch whose recovery pass completed for
        # that pg: a pg is only reported clean once the pass has
        # verified it under the current map (completeness, not just
        # map up-ness)
        self._clean_epoch: dict[tuple[int, int], int] = {}
        # past_intervals-lite (reference src/osd/osd_types.h:3270
        # PastIntervals): per local PG, the acting sets of recent map
        # intervals since the pg was last clean — recovery consults
        # their still-up members as data SOURCES, so a fully-remapped
        # PG can pull from its previous home.  Bounded; trimmed when
        # the recovery pass completes clean.
        self._past_acting: dict[tuple[int, int], list[list[int]]] = {}
        self._past_acting_loaded = False
        # (pool, ps) -> (last shallow stamp, last deep stamp), monotonic
        self._scrub_stamps: dict[tuple[int, int], tuple[float, float]] = {}
        self._scrub_task: asyncio.Task | None = None
        # primary-side EC stripe cache: (pool, oid) -> (object version,
        # logical lo, bytes) of the most recent write — hot RMW
        # overwrites skip the shard read (ExtentCache role, reference
        # src/osd/ExtentCache.h; entries are version-guarded, so a
        # primary change or missed write can never serve stale bytes)
        from collections import OrderedDict as _OD

        self._extent_cache: "dict[tuple[int, str], tuple]" = _OD()
        self._extent_cache_bytes = 0
        self._ec_cache: dict[str, object] = {}
        self._pg_logs: dict[coll_t, PGLog] = {}
        self._beacon_task: asyncio.Task | None = None
        self._hb_task: asyncio.Task | None = None
        # peer heartbeat state (handle_osd_ping analogue)
        self._hb_last_reply: dict[int, float] = {}
        self._hb_first_ping: dict[int, float] = {}
        self._hb_reported: dict[int, float] = {}
        self.drop_pings = False  # test hook: simulate a silent partition
        self._recovery_task: asyncio.Task | None = None
        # backfill admission control (AsyncReserver twin, reference
        # src/common/AsyncReserver.h + MBackfillReserve handshake):
        # local slots gate PGs WE lead into recovery; remote slots gate
        # how many foreign primaries may backfill onto us at once
        from ceph_tpu.common.reserver import AsyncReserver

        _mb = self.conf["osd_max_backfills"]
        self.local_reserver = AsyncReserver(max_allowed=_mb)
        self.remote_reserver = AsyncReserver(max_allowed=_mb)
        self._remote_grants: dict[tuple[int, int, int], object] = {}
        # in-flight object-reconciliation budget within granted PGs
        # (osd_recovery_max_active role)
        self._recovery_budget = asyncio.Semaphore(
            self.conf["osd_recovery_max_active"])
        self.recovery_stats = {
            "reservation_rejects": 0, "pgs_recovered": 0,
            "peak_local": 0, "peak_remote": 0,
        }
        self.conf.add_observer(
            ("osd_max_backfills",),
            lambda ch: (
                self.local_reserver.set_max(ch["osd_max_backfills"]),
                self.remote_reserver.set_max(ch["osd_max_backfills"]),
            ),
        )
        # mClock admission gate (OpScheduler seam): top-level work —
        # client ops, recovery reconciliations, scrub chunks — admits
        # here; under saturation dequeue order follows dmclock tags so
        # clients outrank background work.  Sub-op service never
        # admits (see opqueue.py deadlock rule).
        from ceph_tpu.osd.opqueue import MClockGate
        from ceph_tpu.osd.scheduler import ClientProfile

        self.op_gate = MClockGate(
            max_inflight=self.conf["osd_op_queue_max_inflight"],
            profiles={
                "client": ClientProfile(
                    weight=self.conf["osd_mclock_scheduler_client_wgt"]),
                "recovery": ClientProfile(weight=self.conf[
                    "osd_mclock_scheduler_background_recovery_wgt"]),
                "best_effort": ClientProfile(weight=self.conf[
                    "osd_mclock_scheduler_background_best_effort_wgt"]),
            },
        )
        self.conf.add_observer(
            ("osd_op_queue_max_inflight",),
            lambda ch: self.op_gate.set_max_inflight(
                ch["osd_op_queue_max_inflight"]),
        )
        self._map_event = asyncio.Event()
        self.stopping = False
        # fresh per daemon start: lets the mon distinguish a fast
        # restart (new incarnation -> epoch bump, peers re-peer) from a
        # paxos replay of the same boot (no-op)
        self.incarnation = time.time_ns()

    # -- lifecycle -----------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.addr = await self.messenger.bind(host, port)
        sock_path = self.conf["admin_socket"]
        if sock_path:
            from ceph_tpu.common import AdminSocket

            self._admin = AdminSocket(sock_path.replace("$id", str(self.id)))
            self._register_admin_commands(self._admin)
            await self._admin.start()
        await self._mon_hunt()
        if self.beacon_interval > 0:
            self._beacon_task = asyncio.ensure_future(self._beacon())
        if self.conf["osd_heartbeat_interval"] > 0:
            self._hb_task = asyncio.ensure_future(self._heartbeat())
        if self.conf["osd_scrub_interval"] > 0:
            self._scrub_task = asyncio.ensure_future(self._scrub_scheduler())
        if self.conf["osd_tier_agent_interval"] > 0:
            self._tier_task = asyncio.ensure_future(self._tier_agent())
        # wait for the first map so ops can be served
        await asyncio.wait_for(self._map_event.wait(), 10)

    async def _mon_hunt(self) -> None:
        """Find a live monitor, (re)boot and (re)subscribe — the
        MonClient hunting behavior on monitor loss."""
        last: Exception | None = None
        for mhost, mport in self.mon_addrs:
            try:
                conn = await self.messenger.connect(mhost, mport)
                await conn.send_message(MOSDBoot(
                    osd=self.id, host=self.addr[0], port=self.addr[1],
                    incarnation=self.incarnation,
                ))
                await conn.send_message(MMonSubscribe(
                    start_epoch=self.osdmap.epoch if self.osdmap else 0
                ))
                self._mon_conn = conn
                return
            except (ConnectionError, OSError) as e:
                last = e
        raise ConnectionError(f"osd.{self.id}: no monitor reachable: {last}")

    def _register_admin_commands(self, sock) -> None:
        """The reference OSD's admin-socket surface
        (src/osd/OSD.cc::asok_command slice)."""
        sock.register(
            "perf dump", "dump perf counters",
            lambda cmd: self.perf.dump(),
        )
        sock.register(
            "dump_ops_in_flight", "in-flight client ops",
            lambda cmd: self.op_tracker.dump_ops_in_flight(),
        )
        sock.register(
            "dump_historic_ops", "recently completed ops",
            lambda cmd: self.op_tracker.dump_historic_ops(),
        )
        sock.register(
            "dump_historic_slow_ops", "ops over the complaint threshold",
            lambda cmd: self.op_tracker.dump_historic_slow_ops(),
        )
        sock.register(
            "dump_traces", "recent spans (blkin/otel role)",
            lambda cmd: self.tracer.dump(),
        )
        sock.register(
            "config show", "effective configuration",
            lambda cmd: self.conf.show(),
        )
        sock.register(
            "config set", "set a config option at runtime",
            lambda cmd: (
                self.conf.apply_changes({cmd["var"]: cmd["val"]}),
                {"success": cmd["var"]},
            )[1],
        )
        sock.register(
            "status", "daemon status",
            lambda cmd: {
                "osd": self.id,
                "epoch": self.epoch,
                "up": not self.stopping,
                "num_pgs": len(self._pg_logs),
            },
        )

    async def stop(self) -> None:
        self.stopping = True
        if self._admin is not None:
            await self._admin.stop()
        for t in (
            self._beacon_task, self._hb_task, self._recovery_task,
            self._scrub_task, getattr(self, "_rehome_task", None),
            getattr(self, "_tier_task", None),
        ):
            if t:
                t.cancel()
        await self.messenger.shutdown()

    async def _beacon(self) -> None:
        while not self.stopping:
            await asyncio.sleep(self.beacon_interval)
            try:
                stats = b""
                try:
                    stats = self._collect_pg_stats()
                except Exception:
                    log.exception("osd.%d: pg-stat collection failed", self.id)
                await self._mon_conn.send_message(
                    MOSDBeacon(osd=self.id, epoch=self.epoch,
                               pg_stats=stats)
                )
            except ConnectionError:
                continue  # mon died; the rehome task is hunting

    def _collect_pg_stats(self) -> bytes:
        """Per-PG state for the PGs this OSD leads — the MPGStats
        report (reference src/mgr/DaemonServer.cc aggregation source).
        States mirror the reference's pg_state_t vocabulary at the
        granularity this OSD can see: active+clean, active+degraded
        (acting set has holes or down members), active+recovering."""
        import json as _json

        om = self.osdmap
        if om is None:
            return b""
        out = {}
        for pid, pool in om.pools.items():
            for ps in range(pool.pg_num):
                pg = pg_t(pid, ps)
                _u, _up, acting, primary = om.pg_to_up_acting_osds(
                    pg, folded=True)
                if primary != self.id:
                    continue
                degraded = any(
                    o == CRUSH_ITEM_NONE or not om.is_up(o) for o in acting
                )
                state = "active"
                if (pid, ps) in self._recovering_pgs:
                    state += "+recovering"
                elif degraded:
                    state += "+degraded"
                elif self._clean_epoch.get((pid, ps), -1) < om.epoch:
                    # the recovery pass has not verified this pg under
                    # the current map yet: data completeness unknown
                    state += "+peering"
                else:
                    state += "+clean"
                my_shard = next(
                    (s for s, o in enumerate(acting) if o == self.id),
                    None,
                )
                n_obj = 0
                if my_shard is not None:
                    shard = my_shard if pool.is_erasure() else NO_SHARD
                    n_obj = len(self._local_objects(pool, pg, shard))
                out[f"{pid}.{ps}"] = {"state": state, "objects": n_obj}
        return _json.dumps(out).encode()

    @property
    def epoch(self) -> int:
        return self.osdmap.epoch if self.osdmap else 0

    # -- peer heartbeats (OSD::handle_osd_ping, src/osd/OSD.cc:5735) ---

    async def _heartbeat(self) -> None:
        """Ping every up peer; report peers whose replies stop to the
        mon.  This catches OSD<->OSD partitions that mon beacons cannot
        see (the peer's beacon keeps flowing while its data path is
        dead) — the reference's front/back heartbeat role."""
        interval = self.conf["osd_heartbeat_interval"]
        grace = self.conf["osd_heartbeat_grace"]
        while not self.stopping:
            await asyncio.sleep(interval)
            om = self.osdmap
            if om is None:
                continue
            now = time.monotonic()
            peers = [
                o for o in range(om.max_osd)
                if o != self.id and om.is_up(o) and o in om.osd_addrs
            ]
            for gone in set(self._hb_first_ping) - set(peers):
                self._hb_first_ping.pop(gone, None)
                self._hb_last_reply.pop(gone, None)
                self._hb_reported.pop(gone, None)
            for peer in peers:
                self._hb_first_ping.setdefault(peer, now)
                try:
                    conn = await self._osd_conn(peer)
                    await conn.send_message(MOSDPing(
                        op=PING, from_osd=self.id, epoch=self.epoch,
                        stamp=time.monotonic_ns(),
                    ))
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    pass  # counts as silence; grace logic judges below
                last_ok = max(
                    self._hb_last_reply.get(peer, 0.0),
                    self._hb_first_ping[peer],
                )
                if (
                    now - last_ok > grace
                    and now - self._hb_reported.get(peer, 0.0) > grace
                ):
                    self._hb_reported[peer] = now
                    log.warning(
                        "osd.%d: peer osd.%d silent for %.1fs; reporting",
                        self.id, peer, now - last_ok,
                    )
                    try:
                        await self._mon_conn.send_message(MOSDFailure(
                            reporter=self.id, failed=peer, epoch=self.epoch,
                        ))
                    except (ConnectionError, OSError):
                        pass

    async def _handle_ping(self, msg: MOSDPing) -> None:
        if msg.op == PING:
            if self.drop_pings:
                # test hook: peers cannot reach us (we still hear their
                # replies to OUR pings, like a one-way-dead link)
                return
            await msg.conn.send_message(MOSDPing(
                op=PING_REPLY, from_osd=self.id, epoch=self.epoch,
                stamp=msg.stamp,
            ))
        elif msg.op == PING_REPLY:
            self._hb_last_reply[msg.from_osd] = time.monotonic()

    # -- plumbing ------------------------------------------------------

    async def _on_reset(self, conn: Connection) -> None:
        """Connection to a peer died: fail pending sub-ops and report
        the peer (the OSD::ms_handle_reset + failure-report path)."""
        if self.stopping or conn.peer is None:
            return
        kind, peer_id = conn.peer
        if kind == "mon" and conn is self._mon_conn:
            async def _rehome():
                for _ in range(20):
                    await asyncio.sleep(0.2)
                    if self.stopping:
                        return
                    try:
                        await self._mon_hunt()
                        return
                    except (ConnectionError, OSError):
                        continue
            self._rehome_task = asyncio.ensure_future(_rehome())
            return
        for tid, fut in list(self._waiters.items()):
            if getattr(fut, "peer", None) == conn.peer and not fut.done():
                fut.set_exception(ConnectionError(f"peer {conn.peer} reset"))
        if kind == "osd" and self.osdmap and self.osdmap.is_up(peer_id):
            try:
                await self._mon_conn.send_message(
                    MOSDFailure(
                        reporter=self.id, failed=peer_id, epoch=self.epoch
                    )
                )
            except ConnectionError:
                pass

    async def _osd_conn(self, osd: int) -> Connection:
        addr = self.osdmap.osd_addrs.get(osd)
        if addr is None:
            raise ConnectionError(f"no address for osd.{osd}")
        return await self.messenger.connect_to(("osd", osd), *addr)

    async def _sub_op(self, osd: int, msg: Message, tid: int):
        """Send a sub-op and await its reply future."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        fut.peer = ("osd", osd)
        self._waiters[tid] = fut
        try:
            conn = await self._osd_conn(osd)
            await conn.send_message(msg)
            return await asyncio.wait_for(fut, SUBOP_TIMEOUT)
        finally:
            self._waiters.pop(tid, None)

    def _ec_for(self, pool: PgPool):
        prof_name = pool.erasure_code_profile
        if prof_name not in self._ec_cache:
            profile = dict(self.osdmap.erasure_code_profiles[prof_name])
            ec = ec_registry.factory(profile.get("plugin", "jax"), profile)
            self._ec_cache[prof_name] = ec
        return self._ec_cache[prof_name]

    def _sinfo(self, ec) -> ecutil.StripeInfo:
        k = ec.get_data_chunk_count()
        chunk = ec.get_chunk_size(STRIPE_UNIT * k)
        return ecutil.StripeInfo(k, chunk * k)

    def _acting(self, pool: PgPool, pg: pg_t) -> tuple[list[int], int]:
        _, _, acting, primary = self.osdmap.pg_to_up_acting_osds(pg)
        return acting, primary

    @property
    def encode_service(self):
        """The process encode farm, per osd_ec_encode_farm config:
        'auto' = farm when >1 local jax device, 'on' = always attach the
        shared service, 'off' = never.  Resolved once, lazily."""
        if not self._encode_service_resolved:
            self._encode_service_resolved = True
            mode = self.conf["osd_ec_encode_farm"]
            if mode != "off":
                from ceph_tpu.parallel import encode_service as es

                svc = es.shared()
                if svc.active() or mode == "on":
                    svc.min_bytes = self.conf["osd_ec_farm_min_bytes"]
                    self._encode_service = svc
        return self._encode_service

    def _extent_cache_get(self, pool_id, oid, version, lo, hi):
        ent = self._extent_cache.get((pool_id, oid))
        if ent is None:
            return None
        v, elo, arr = ent
        if v != version or elo > lo or elo + len(arr) < hi:
            return None
        self._extent_cache.move_to_end((pool_id, oid))
        self.perf.inc("ec_extent_cache_hit")
        return arr[lo - elo : hi - elo]

    def _extent_cache_put(self, pool_id, oid, version, lo, arr) -> None:
        limit = self.conf["osd_ec_extent_cache_bytes"]
        if limit <= 0 or len(arr) > limit:
            return
        old = self._extent_cache.pop((pool_id, oid), None)
        if old is not None:
            self._extent_cache_bytes -= len(old[2])
        self._extent_cache[(pool_id, oid)] = (version, lo, arr)
        self._extent_cache_bytes += len(arr)
        while self._extent_cache_bytes > limit and self._extent_cache:
            _k, ent = self._extent_cache.popitem(last=False)
            self._extent_cache_bytes -= len(ent[2])

    def _extent_cache_drop(self, pool_id, oid) -> None:
        old = self._extent_cache.pop((pool_id, oid), None)
        if old is not None:
            self._extent_cache_bytes -= len(old[2])

    async def _ecu_encode(self, sinfo, ec, logical):
        """ecutil.encode via the farm (falls back inside)."""
        return await ecutil.encode_async(
            sinfo, ec, logical, service=self.encode_service)

    async def _ecu_decode_concat(self, sinfo, ec, chunks):
        return await ecutil.decode_concat_async(
            sinfo, ec, chunks, service=self.encode_service)

    def _pg_log(self, c: coll_t) -> PGLog:
        lg = self._pg_logs.get(c)
        if lg is None:
            lg = PGLog(c)
            lg.load(self.store)
            self._pg_logs[c] = lg
        return lg

    def _next_version(
        self, c: coll_t, epoch: int | None = None
    ) -> eversion_t | None:
        """``epoch`` must be the op's ADMISSION epoch (captured when the
        primary check passed): maps can advance mid-op, and minting with
        the then-current epoch would let two daemons that were each
        primary under different maps stamp the SAME eversion onto
        different payloads — an undetectable mixed-content write.

        Returns None when the pg log already holds an entry from a
        NEWER epoch (e.g. adopted from the next interval's primary):
        this op must be re-admitted under the newer map (caller replies
        EAGAIN) — minting into a foreign epoch could collide with that
        primary's versions."""
        lu = self._pg_log(c).info.last_update
        e = self.epoch if epoch is None else epoch
        if lu.epoch > e:
            return None
        return eversion_t(e, lu.version + 1)

    def _object_version(self, c: coll_t, o: ghobject_t) -> eversion_t:
        try:
            return _v_parse(self.store.getattr(c, o, VERSION_ATTR))
        except (FileNotFoundError, KeyError):
            return ZERO

    def _obj_lock(self, pool_id: int, oid: str) -> asyncio.Lock:
        key = (pool_id, oid)
        lk = self._obj_locks.get(key)
        if lk is None:
            if len(self._obj_locks) > 4096:  # prune idle locks
                # a lock is only disposable when nothing holds it AND
                # nothing waits on it: between release and a waiter's
                # wakeup, locked() is False while the waiter still
                # references the old Lock object — pruning then would
                # hand the next writer a fresh lock and break mutual
                # exclusion
                for k in [
                    k for k, v in self._obj_locks.items()
                    if not v.locked() and not getattr(v, "_waiters", None)
                ]:
                    del self._obj_locks[k]
            lk = self._obj_locks[key] = asyncio.Lock()
        return lk

    # -- dispatch ------------------------------------------------------

    async def _dispatch(self, msg: Message) -> None:
        try:
            if isinstance(msg, MOSDMap):
                await self._handle_map(msg)
            elif isinstance(msg, MConfig):
                self._apply_mon_config(msg)
            elif isinstance(msg, MOSDPing):
                await self._handle_ping(msg)
            elif isinstance(msg, MWatchNotifyAck):
                self._handle_notify_ack(msg)
            elif isinstance(msg, MOSDOp):
                asyncio.ensure_future(self._handle_client_op(msg))
            elif isinstance(msg, MOSDECSubOpWrite):
                await self._handle_sub_write(msg)
            elif isinstance(msg, MOSDECSubOpRead):
                await self._handle_sub_read(msg)
            elif isinstance(msg, MOSDRepOp):
                await self._handle_rep_op(msg)
            elif isinstance(msg, MOSDPGPush):
                await self._handle_push(msg)
            elif isinstance(msg, MOSDPGQuery):
                # peering messages may wait for map catch-up
                # (_wait_for_epoch): run off the connection's dispatch
                # loop so in-flight client sub-ops on the same pipe
                # don't queue behind the wait (the reference parks
                # these on a waiting_for_map queue the same way)
                self._spawn_peering(self._handle_pg_query(msg))
            elif isinstance(msg, MOSDPGLog):
                self._spawn_peering(self._handle_pg_log(msg))
            elif isinstance(msg, MOSDScrub):
                asyncio.ensure_future(self._handle_scrub(msg))
            elif isinstance(msg, MBackfillReserve):
                await self._handle_backfill_reserve(msg)
            elif isinstance(
                msg,
                (
                    MOSDECSubOpWriteReply, MOSDECSubOpReadReply,
                    MOSDRepOpReply, MOSDPGInfo, MOSDPGLogAck,
                    MOSDOpReply,  # tiering: we client other pools
                ),
            ):
                fut = self._waiters.get(msg.tid)
                if fut and not fut.done():
                    fut.set_result(msg)
            elif isinstance(msg, MOSDPGPushReply):
                fut = self._push_waiters.get(msg.tid)
                if fut and not fut.done():
                    fut.set_result(msg)
        except Exception:
            log.exception("osd.%d: dispatch failed for %r", self.id, msg)

    async def _handle_map(self, msg: MOSDMap) -> None:
        # copy-on-write swap: code that captured self.osdmap mid-pass
        # keeps a stable snapshot (recovery, in-flight ops)
        old_map = self.osdmap
        new_map, gap = apply_map_message(self.osdmap, msg.maps, msg.incs)
        if new_map is not None:
            self.osdmap = new_map
            self._maybe_snap_trim(old_map, new_map)
            self._track_intervals(old_map, new_map)
            self._maybe_split_pgs(old_map, new_map)
            self._gc_removed_pools(old_map, new_map)
        if gap:
            # ask the mon for the missing range (or a full map)
            await self._request_map_fill()
        self._map_event.set()
        log.info("osd.%d: map epoch %d", self.id, self.epoch)
        if self.osdmap.max_osd > self.id and self.osdmap.is_up(self.id):
            self._seen_up = True
        if (
            not self.stopping
            and getattr(self, "_seen_up", False)
            and self.osdmap.max_osd > self.id
            and self.osdmap.exists(self.id)
            and not self.osdmap.is_up(self.id)
        ):
            # the map says we are down but we are alive (false failure
            # report, or a mon that hasn't seen our boot): re-assert
            # with a fresh incarnation (OSD::_committed_osd_maps ->
            # start_boot in the reference)
            log.warning("osd.%d: map says I'm down; re-booting", self.id)
            self.incarnation = time.time_ns()
            try:
                await self._mon_conn.send_message(MOSDBoot(
                    osd=self.id, host=self.addr[0], port=self.addr[1],
                    incarnation=self.incarnation,
                ))
            except (ConnectionError, OSError):
                pass  # mon hunt will re-boot us
        if self._recovery_task is None or self._recovery_task.done():
            self._recovery_task = asyncio.ensure_future(self._recover_all())

    def _apply_mon_config(self, msg: MConfig) -> None:
        """Centralized config distribution (MConfig/ConfigMonitor):
        apply the sections addressing this daemon at the 'mon' source —
        below env/cmdline overrides, above file/defaults."""
        for sec in ("global", "osd", f"osd.{self.id}"):
            for name, value in msg.sections.get(sec, {}).items():
                try:
                    # apply_changes (not bare set) so live observers —
                    # backfill reserver caps, mClock knobs — re-read
                    self.conf.apply_changes({name: value}, source="mon")
                except (KeyError, ValueError):
                    log.warning(
                        "osd.%d: ignoring mon config %s=%r", self.id,
                        name, value)

    def _track_intervals(self, old_map, new_map) -> None:
        """Record acting-set interval changes for PGs this OSD touches
        (the PastIntervals bookkeeping): the PREVIOUS map is in hand at
        map-change time, so even a member that just JOINED the acting
        set learns where the PG lived before — the prior set a full
        remap must pull from."""
        if old_map is None:
            return
        # placement-inputs precheck: epochs minted by non-placement
        # changes (pool create, profiles, config) can't move any pg —
        # skip the per-pg mapping work entirely.  CRUSH weights are a
        # placement input too (osd crush reweight!), compared via the
        # per-bucket item weights.
        if (
            old_map.osd_state == new_map.osd_state
            and old_map.osd_weight == new_map.osd_weight
            and old_map.osd_primary_affinity == new_map.osd_primary_affinity
            and old_map.pg_upmap == new_map.pg_upmap
            and old_map.pg_upmap_items == new_map.pg_upmap_items
            and old_map.pg_temp == new_map.pg_temp
            and len(old_map.crush.buckets) == len(new_map.crush.buckets)
            and all(
                bid in new_map.crush.buckets
                and b.items == new_map.crush.buckets[bid].items
                and b.item_weights == new_map.crush.buckets[bid].item_weights
                for bid, b in old_map.crush.buckets.items()
            )
            and old_map.crush.rules == new_map.crush.rules
            and old_map.crush.device_classes == new_map.crush.device_classes
            and all(
                p.pg_num == new_map.pools[pid].pg_num
                and p.crush_rule == new_map.pools[pid].crush_rule
                for pid, p in old_map.pools.items()
                if pid in new_map.pools
            )
        ):
            return
        changed = False
        if not self._past_acting_loaded:
            self._load_past_acting()
        for pid, pool in new_map.pools.items():
            old_pool = old_map.pools.get(pid)
            if old_pool is None:
                continue
            for ps in range(pool.pg_num):
                pg = pg_t(pid, ps)
                _u, _up, acting, _p = new_map.pg_to_up_acting_osds(
                    pg, folded=True)
                if ps >= old_pool.pg_num:
                    # a split child did not exist under the old map:
                    # its history starts at its ANCESTOR's home (the
                    # reference's pg_t::get_ancestor in
                    # PastIntervals::check_new_interval) — that's where
                    # the refiled objects physically sit
                    anc = old_pool.raw_pg_to_pg(pg_t(pid, ps))
                    _u2, _up2, acting_old, _p2 = (
                        old_map.pg_to_up_acting_osds(anc, folded=True))
                else:
                    _u2, _up2, acting_old, _p2 = (
                        old_map.pg_to_up_acting_osds(pg, folded=True))
                if acting_old == acting:
                    continue
                if self.id not in acting and self.id not in acting_old:
                    continue
                hist = self._past_acting.setdefault((pid, ps), [])
                if not hist or hist[-1] != acting_old:
                    hist.append(list(acting_old))
                    del hist[:-16]  # bounded
                    changed = True
        if changed:
            self._save_past_acting()

    # the store layer's reserved meta collection (objectstore.py:37,
    # pool -1 can never collide with a real pool)
    from ceph_tpu.store.objectstore import META_COLL as _META_COLL
    _META_OID = "osd_past_intervals"

    def _load_past_acting(self) -> None:
        """Restart path: reload the recorded intervals so a primary
        that reboots across a remap still knows the prior homes (the
        reference persists PastIntervals in pg info the same way)."""
        self._past_acting_loaded = True
        import json as _json

        try:
            raw = self.store.read(
                self._META_COLL, ghobject_t(self._META_OID))
        except (FileNotFoundError, OSError):
            return
        try:
            data = _json.loads(raw)
        except ValueError:
            return
        for k, hist in data.items():
            pid, ps = k.split(".")
            self._past_acting[(int(pid), int(ps))] = hist

    def _save_past_acting(self) -> None:
        import json as _json

        t = Transaction()
        self._ensure_coll(t, self._META_COLL)
        blob = _json.dumps({
            f"{pid}.{ps}": hist
            for (pid, ps), hist in self._past_acting.items()
        }).encode()
        t.touch(self._META_COLL, ghobject_t(self._META_OID))
        t.truncate(self._META_COLL, ghobject_t(self._META_OID), len(blob))
        t.write(self._META_COLL, ghobject_t(self._META_OID), 0, blob)
        try:
            self.store.queue_transaction(t)
        except OSError:
            log.exception("osd.%d: persisting past intervals failed", self.id)

    def _prior_pairs(
        self, pool, pg: pg_t, pairs: list[tuple[int, int]]
    ) -> list[tuple[int, int]]:
        """(shard, osd) candidates from past intervals: still-up
        members not in the current acting set — potential data sources
        (the prior_set role of PastIntervals)."""
        if not self._past_acting_loaded:
            self._load_past_acting()
        key = (pg.pool, pg.ps)
        current = {(s, o) for s, o in pairs}
        out: list[tuple[int, int]] = []
        seen = set()
        for past in reversed(self._past_acting.get(key, [])):
            for s, o in self._pg_members(pool, past):
                if (s, o) in current or (s, o) in seen:
                    continue
                if o == CRUSH_ITEM_NONE or not self.osdmap.is_up(o):
                    continue
                seen.add((s, o))
                out.append((s, o))
        return out

    def _maybe_split_pgs(self, old_map, new_map) -> None:
        """PG splitting, local half (the reference's PG::split_colls /
        OSD::split_pgs, src/osd/OSD.cc + PG.cc): when a pool's pg_num
        grows, every local object whose name now folds to a child ps
        moves into the child's collection via collection_move_rename —
        the same primitive the reference's split uses.  The cluster
        half (children placing onto new OSDs) is ordinary recovery:
        _track_intervals records the parent's old acting set as the
        child's prior interval, so the child's primary pulls from the
        members holding the refiled data.

        Runs on EVERY first map after boot too (old_map None): a crash
        mid-split leaves misfolded objects behind, and the reconcile
        pass refiles them from persistent stores."""
        pools = new_map.pools.items()
        if old_map is not None:
            pools = [
                (pid, p) for pid, p in pools
                if pid in old_map.pools
                and p.pg_num > old_map.pools[pid].pg_num
            ]
        for _pid, pool in pools:
            try:
                moved = self._refile_split_collections(pool)
            except Exception:
                log.exception("osd.%d: pg split refile failed", self.id)
                continue
            if moved:
                log.info("osd.%d: pg split pool %d: refiled %d objects",
                         self.id, pool.id, moved)
                # split invalidates the parent PGs' clean verdicts
                for key in list(self._clean_epoch):
                    if key[0] == pool.id:
                        del self._clean_epoch[key]

    def _refile_split_collections(self, pool) -> int:
        from ceph_tpu.store.objectstore import META_COLL

        moved = 0
        for c in list(self.store.list_collections()):
            if c.pool != pool.id or c == META_COLL:
                continue
            if c.ps >= pool.pg_num:
                continue  # stale collection beyond the map (merge-only)
            try:
                objs = list(self.store.collection_list(c))
            except FileNotFoundError:
                continue
            t = Transaction()
            made: set = set()
            children: set[int] = set()
            for o in objs:
                if o.name == PGMETA_OID:
                    continue
                newps = pool.raw_pg_to_pg(object_to_pg(pool, o.name)).ps
                if newps == c.ps:
                    continue
                dst = coll_t(pool.id, newps, c.shard)
                if dst not in made and not self.store.collection_exists(dst):
                    t.create_collection(dst)
                    made.add(dst)
                # clones (snap != head) ride along with the same id
                t.collection_move_rename(c, o, dst, o)
                children.add(newps)
                moved += 1
            # the log splits with the data (PGLog::split_into): each
            # child inherits the entries for its objects AND the
            # parent's version bounds, in the SAME transaction
            parent_lg = self._pg_log(c)
            for ps in sorted(children):
                dst = coll_t(pool.id, ps, c.shard)
                parent_lg.split_into(
                    t, self._pg_log(dst),
                    lambda oid, _ps=ps: pool.raw_pg_to_pg(
                        object_to_pg(pool, oid)).ps == _ps,
                )
            if not t.empty():
                self.store.queue_transaction(t)
        return moved

    def _gc_removed_pools(self, old_map, new_map) -> None:
        """Deleted pools leave orphan collections (the reference's
        pg-removal on pool deletion): drop them locally."""
        if old_map is None:
            gone = {
                c.pool for c in self.store.list_collections()
                if c.pool >= 0 and c.pool not in new_map.pools
            }
        else:
            gone = set(old_map.pools) - set(new_map.pools)
        if not gone:
            return
        try:
            t = Transaction()
            for c in list(self.store.list_collections()):
                if c.pool in gone:
                    try:
                        objs = list(self.store.collection_list(c))
                    except FileNotFoundError:
                        continue
                    for o in objs:
                        t.remove(c, o)
                    t.remove_collection(c)
                    self._pg_logs.pop(c, None)
            if not t.empty():
                self.store.queue_transaction(t)
                log.info("osd.%d: removed collections of deleted pools %s",
                         self.id, sorted(gone))
        except Exception:
            # gc must never abort map handling (the map swap already
            # happened; waiters and recovery still need their kicks)
            log.exception("osd.%d: pool gc failed", self.id)

    def _maybe_snap_trim(self, old_map, new_map) -> None:
        """Schedule the snap trimmer for pools whose removed_snaps grew
        (the reference's SnapTrimmer/SnapMapper worker role)."""
        for pid, pool in new_map.pools.items():
            old_pool = old_map.pools.get(pid) if old_map else None
            old_removed = old_pool.removed_snaps if old_pool else set()
            if pool.removed_snaps - old_removed:
                task = asyncio.ensure_future(self._snap_trim(pool))
                # the loop keeps only weak refs to tasks: hold one so a
                # half-finished trim can't be garbage-collected
                self._trim_tasks.add(task)
                task.add_done_callback(self._trim_tasks.discard)

    async def _snap_trim(self, pool) -> None:
        """Purge clones whose every covered snap is removed; update or
        drop the head SnapSet; reap whiteout heads with no clones left.
        Runs on every OSD against its local store — replicas hold the
        same objects, so local deterministic trimming converges."""
        import dataclasses

        removed = pool.removed_snaps
        try:
            colls = [
                c for c in self.store.list_collections() if c.pool == pool.id
            ]
        except Exception:
            return
        for c in colls:
            try:
                objs = self.store.collection_list(c)
            except FileNotFoundError:
                continue
            for o in objs:
                if o.snap < 0:  # head (ghobject default snap = -2)
                    continue
                async with self._obj_lock(pool.id, o.name):
                    try:
                        raw = self.store.getattr(c, o, SNAPS_ATTR)
                    except (KeyError, FileNotFoundError):
                        continue
                    snaps = decode_snaps(raw)
                    live = [sn for sn in snaps if sn not in removed]
                    if live == snaps:
                        continue
                    t = Transaction()
                    head = dataclasses.replace(o, snap=ghobject_t("").snap)
                    if live:
                        t.setattrs(c, o, {SNAPS_ATTR: encode_snaps(live)})
                        # keep the head SnapSet's covered list in step
                        ss = SnapSet.from_bytes(
                            self._getattr_quiet(c, head, SS_ATTR))
                        cl = ss.clone_by_id(o.snap)
                        if cl is not None and cl.snaps != live:
                            cl.snaps = list(live)
                            t.setattrs(c, head, {SS_ATTR: ss.to_bytes()})
                    else:
                        t.remove(c, o)
                        ss = SnapSet.from_bytes(
                            self._getattr_quiet(c, head, SS_ATTR))
                        ss.drop_clone(o.snap)
                        if self.store.exists(c, head):
                            if not ss.clones and self._is_whiteout(c, head):
                                t.remove(c, head)
                            else:
                                t.setattrs(c, head, {SS_ATTR: ss.to_bytes()})
                    try:
                        if getattr(self.store, "blocking_commit", False):
                            await asyncio.to_thread(
                                self.store.queue_transaction, t)
                        else:
                            self.store.queue_transaction(t)
                    except (FileNotFoundError, FileExistsError):
                        pass  # raced a concurrent op; next trim rescans
                await asyncio.sleep(0)

    def _getattr_quiet(self, c, o, name) -> bytes | None:
        try:
            return self.store.getattr(c, o, name)
        except (KeyError, FileNotFoundError):
            return None

    async def _request_map_fill(self) -> None:
        try:
            if self._mon_conn is not None:
                await self._mon_conn.send_message(MMonSubscribe(
                    start_epoch=self.osdmap.epoch if self.osdmap else 0
                ))
        except ConnectionError:
            pass  # mon hunt will re-subscribe

    # -- client ops (the PrimaryLogPG::do_op slice) --------------------

    async def _handle_client_op(self, msg: MOSDOp) -> None:
        tracked = self.op_tracker.create(
            f"osd_op({msg.oid} pool={msg.pool} "
            f"ops={[o.op for o in msg.ops]} tid={msg.tid})"
        )
        try:
            self.perf.inc("op")
            if msg.is_write():
                self.perf.inc("op_w")
                self.perf.inc(
                    "op_in_bytes", sum(len(o.data) for o in msg.ops)
                )
            else:
                self.perf.inc("op_r")
            self.dlog.dout(4, "osd.%d: op %s", self.id, tracked.description)
            tracked.mark_event("queued")
            async with self.op_gate.admit("client"):
                tracked.mark_event("executing")
                with self.tracer.span(
                    "do_op", reqid=msg.reqid, oid=msg.oid, pool=msg.pool,
                    ops=len(msg.ops),
                ) as _sp:
                    token = self._op_span.set(_sp)
                    try:
                        reply = await self._execute_op(msg)
                    finally:
                        try:
                            self._op_span.reset(token)
                        except ValueError:
                            # a task garbage-collected at loop teardown
                            # runs this finally in a foreign Context;
                            # the var dies with the task either way
                            pass
                    _sp.tag(result=reply.result)
            tracked.mark_event("replying")
            if reply.result == 0 and reply.data:
                self.perf.inc("op_out_bytes", len(reply.data))
        except ECConnErrors as e:
            log.warning("osd.%d: op tid %d failed: %r", self.id, msg.tid, e)
            reply = MOSDOpReply(
                tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch
            )
        except Exception:
            log.exception("osd.%d: op tid %d crashed", self.id, msg.tid)
            reply = MOSDOpReply(tid=msg.tid, result=-errno.EIO, epoch=self.epoch)
        try:
            await msg.conn.send_message(reply)
        except ConnectionError:
            pass
        finally:
            tracked.finish()

    async def _execute_op(self, msg: MOSDOp) -> MOSDOpReply:
        """do_op/do_osd_ops dispatch: route the op vector to the pool's
        backend; write vectors serialize per object (the reference's
        ObjectContext write lock, PrimaryLogPG::find_object_context)."""
        pool = self.osdmap.get_pg_pool(msg.pool) if self.osdmap else None
        if pool is None:
            return MOSDOpReply(tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
        if not msg.ops:
            return MOSDOpReply(tid=msg.tid, result=-errno.EINVAL, epoch=self.epoch)
        caps = getattr(msg.conn, "peer_caps", None)
        if caps is not None:
            # OSDCap admission (PrimaryLogPG::do_op op_has_sufficient_caps):
            # the need is the UNION over sub-ops — a write-only cap
            # must not smuggle a read by bundling it with a write —
            # with class calls additionally requiring x; scoped to
            # this pool.  A denial is EPERM, not a retry.
            from ceph_tpu.common.caps import capable
            from ceph_tpu.msg.messages import OP_CALL

            need = set()
            for o in msg.ops:
                if o.op == OP_CALL:
                    need.add("x")
                    from ceph_tpu import cls as _cls

                    cname, _, mname = (o.name or "").partition(".")
                    need.add("w" if _cls.method_is_write(cname, mname)
                             else "r")
                elif o.is_write():
                    need.add("w")
                else:
                    need.add("r")
            pool_name = self.osdmap.pool_names.get(msg.pool, "")
            if not capable(caps, "osd", "".join(sorted(need)),
                           pool=pool_name):
                return MOSDOpReply(
                    tid=msg.tid, result=-errno.EPERM, epoch=self.epoch)
        pg = object_to_pg(pool, msg.oid)
        acting, primary = self._acting(pool, pg)
        if primary != self.id:
            # client raced a map change; tell it to retry on a newer map
            return MOSDOpReply(tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        # versions mint under the epoch primacy was verified at, even
        # if the map advances mid-op (see _next_version)
        admit_epoch = self.epoch
        if any(o.op in (OP_WATCH, OP_UNWATCH, OP_NOTIFY) for o in msg.ops):
            return await self._watch_notify_vector(pool, pg, msg)
        tiered = (
            pool.extra.get("tier_of")
            and pool.extra.get("cache_mode") == "writeback"
            and not getattr(msg, "_tier_internal", False)
        )
        # the object lock covers tier admission (present/dirty checks,
        # promote) AND the op itself, so the agent's flush/evict can't
        # interleave with a client op's check-then-act; internal tier
        # ops carry _have_obj_lock and skip re-acquisition
        if (tiered or msg.is_write()) and not getattr(
                msg, "_have_obj_lock", False):
            async with self._obj_lock(pool.id, msg.oid):
                return await self._execute_op_locked(
                    pool, pg, acting, msg, admit_epoch, tiered)
        return await self._execute_op_locked(
            pool, pg, acting, msg, admit_epoch, tiered)

    async def _execute_op_locked(
        self, pool, pg, acting, msg, admit_epoch, tiered,
    ) -> MOSDOpReply:
        if tiered:
            reply = await self._tier_prepare(pool, pg, msg)
            if reply is not None:
                return reply
        if msg.is_write():
            if msg.snapid != NOSNAP:
                return MOSDOpReply(
                    tid=msg.tid, result=-errno.EROFS, epoch=self.epoch)
            if pool.is_erasure():
                ec = self._ec_for(pool)
                return await self._ec_write_vector(
                    pool, pg, acting, msg, ec, self._sinfo(ec),
                    admit_epoch,
                )
            return await self._rep_write_vector(
                pool, pg, acting, msg, admit_epoch)
        if pool.is_erasure():
            ec = self._ec_for(pool)
            return await self._ec_read_vector(
                pool, pg, acting, msg, ec, self._sinfo(ec)
            )
        return await self._rep_read_vector(pool, pg, acting, msg)

    # -- cache tiering (PrimaryLogPG HitSet/TierAgent, src/osd/HitSet.h)

    def _hitset(self, pool_id: int) -> "OrderedDict":
        from collections import OrderedDict as _OD

        hs = getattr(self, "_hitsets", None)
        if hs is None:
            hs = self._hitsets = {}
        if pool_id not in hs:
            hs[pool_id] = _OD()
        return hs[pool_id]

    def _hitset_touch(self, pool_id: int, oid: str) -> None:
        """Approximate recency (the reference's HitSet stack reduced to
        one explicit-object window, src/osd/HitSet.h ExplicitHashHitSet):
        most-recent at the end, bounded."""
        hs = self._hitset(pool_id)
        hs[oid] = time.monotonic()
        hs.move_to_end(oid)
        while len(hs) > 4096:
            hs.popitem(last=False)

    async def _pool_op(self, pool_id: int, oid: str, ops: list) -> "MOSDOpReply":
        """The daemon as a CLIENT of another pool (the tiering
        flush/promote I/O, PrimaryLogPG::start_copy using the
        objecter).  Minimal resend-on-EAGAIN."""
        import errno as _errno

        for _try in range(8):
            om = self.osdmap
            pool = om.get_pg_pool(pool_id)
            if pool is None:
                return MOSDOpReply(result=-_errno.ENOENT, epoch=self.epoch)
            pg = object_to_pg(pool, oid)
            _, primary = self._acting(pool, pg)
            addr = om.osd_addrs.get(primary)
            if primary < 0 or addr is None:
                await asyncio.sleep(0.2)
                continue
            tid = next(self._tids)
            m = MOSDOp(pool=pool_id, oid=oid, ops=list(ops), tid=tid,
                       epoch=om.epoch)
            if m.is_write():
                m.reqid = f"osd.{self.id}:{tid}"
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._waiters[tid] = fut
            try:
                conn = await self.messenger.connect_to(
                    ("osd", primary), *addr)
                await conn.send_message(m)
                reply = await asyncio.wait_for(fut, 30.0)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                await asyncio.sleep(0.2)
                continue
            finally:
                self._waiters.pop(tid, None)
            if reply.result == -_errno.EAGAIN:
                await asyncio.sleep(0.1 * (_try + 1))
                continue
            return reply
        return MOSDOpReply(result=-_errno.ETIMEDOUT, epoch=self.epoch)

    async def _tier_internal_op(
        self, pool, oid: str, ops: list, *, have_lock: bool = False,
    ) -> int:
        """Run a replicated write vector on OUR pool as an internal op
        (agent flush/evict, promote): full primary pipeline, replicas
        included, marked so the tier hook doesn't recurse.
        ``have_lock``: the caller already holds the object lock."""
        m = MOSDOp(pool=pool.id, oid=oid, ops=list(ops),
                   tid=next(self._tids), epoch=self.epoch)
        m._tier_internal = True
        m._have_obj_lock = have_lock
        m.reqid = f"osd.{self.id}:{m.tid}"
        reply = await self._execute_op(m)
        return reply.result

    async def _tier_prepare(self, pool, pg, msg) -> "MOSDOpReply | None":
        """The cache-pool op admission (PrimaryLogPG::maybe_handle_cache
        + do_cache_redirect/promote_object, writeback mode):

        - CACHE_FLUSH / CACHE_EVICT / COPY_FROM vectors are handled
          here entirely;
        - an op whose object misses the cache promotes it from the
          base pool first (whole-object, data only — documented lite
          scope vs the reference's omap/xattr copy);
        - deletes propagate to the base synchronously (the reference
          whiteouts + flushes; same visible result);
        - writes mark the object dirty (xattr), reads/writes record
          hits.  Returns a reply to short-circuit, or None to continue
          with the (possibly rewritten) vector."""
        import errno as _errno

        from ceph_tpu.msg.messages import (
            OP_CACHE_EVICT,
            OP_CACHE_FLUSH,
            OP_COPY_FROM,
            OSDOp,
        )

        base_pid = int(pool.extra["tier_of"])
        c = self._shard_coll(pool, pg, NO_SHARD)
        o = ghobject_t(msg.oid)
        present = self.store.exists(c, o) and not self._is_whiteout(c, o)

        kinds = {op.op for op in msg.ops}
        if OP_CACHE_FLUSH in kinds:
            if not present:
                return MOSDOpReply(tid=msg.tid, result=-_errno.ENOENT,
                                   epoch=self.epoch)
            rc = await self._tier_flush(pool, base_pid, c, o, msg.oid,
                                        have_lock=True)
            return MOSDOpReply(tid=msg.tid, result=rc, epoch=self.epoch)
        if OP_CACHE_EVICT in kinds:
            if not present:
                return MOSDOpReply(tid=msg.tid, result=-_errno.ENOENT,
                                   epoch=self.epoch)
            if self._tier_dirty(c, o):
                return MOSDOpReply(tid=msg.tid, result=-_errno.EBUSY,
                                   epoch=self.epoch)
            rc = await self._tier_internal_op(
                pool, msg.oid, [OSDOp(OP_DELETE)], have_lock=True)
            self._hitset(pool.id).pop(msg.oid, None)
            self.perf.inc("tier_evict")
            return MOSDOpReply(tid=msg.tid, result=rc, epoch=self.epoch)
        if OP_COPY_FROM in kinds:
            op = next(op for op in msg.ops if op.op == OP_COPY_FROM)
            spool, _, soid = (op.name or "").partition(":")
            reply = await self._pool_op(
                int(spool), soid, [OSDOp(OP_READ)])
            if reply.result != 0:
                return MOSDOpReply(tid=msg.tid, result=reply.result,
                                   epoch=self.epoch)
            # the copy is DIRTY (writeback: it exists only here until
            # flushed — an unflushed-evictable copy would be lost)
            msg.ops = [
                OSDOp(OP_WRITE_FULL, data=reply.data),
                OSDOp(OP_SETXATTR, name="cache.dirty", data=b"1"),
            ]
            return None  # continue as a normal replicated write

        self._hitset_touch(pool.id, msg.oid)
        if present:
            self.perf.inc("tier_hit")
        else:
            self.perf.inc("tier_miss")
            # promote-on-miss (reads AND writes: writeback promotes
            # before mutating, PrimaryLogPG::promote_object)
            reply = await self._pool_op(base_pid, msg.oid, [OSDOp(OP_READ)])
            if reply.result == 0:
                rc = await self._tier_internal_op(pool, msg.oid, [
                    OSDOp(OP_WRITE_FULL, data=reply.data),
                ], have_lock=True)
                if rc != 0:
                    return MOSDOpReply(tid=msg.tid, result=rc,
                                       epoch=self.epoch)
                self.perf.inc("tier_promote")
            elif reply.result != -_errno.ENOENT:
                return MOSDOpReply(tid=msg.tid, result=reply.result,
                                   epoch=self.epoch)

        if msg.is_write():
            if any(op.op == OP_DELETE for op in msg.ops):
                # propagate the delete to the base FIRST (lite
                # stand-in for whiteout + flush): if the base refuses,
                # the op fails — a cache-only delete would resurrect
                # on the next promote
                reply = await self._pool_op(
                    base_pid, msg.oid, [OSDOp(OP_DELETE)])
                if reply.result not in (0, -_errno.ENOENT):
                    return MOSDOpReply(tid=msg.tid, result=reply.result,
                                       epoch=self.epoch)
            else:
                msg.ops = list(msg.ops) + [
                    OSDOp(OP_SETXATTR, name="cache.dirty", data=b"1")]
        return None

    def _tier_dirty(self, c: coll_t, o: ghobject_t) -> bool:
        try:
            return self.store.getattr(c, o, "u_cache.dirty") == b"1"
        except (KeyError, FileNotFoundError, OSError):
            return False

    async def _tier_flush(self, pool, base_pid: int, c, o, oid: str,
                          *, have_lock: bool = False) -> int:
        """Write a dirty cache object back to the base pool, then mark
        it clean (CEPH_OSD_OP_CACHE_FLUSH, PrimaryLogPG::start_flush)."""
        from ceph_tpu.msg.messages import OP_RMXATTR, OSDOp

        try:
            data = self.store.read(c, o)
        except (FileNotFoundError, OSError):
            return -errno.ENOENT
        if self._tier_dirty(c, o):
            reply = await self._pool_op(
                base_pid, oid, [OSDOp(OP_WRITE_FULL, data=bytes(data))])
            if reply.result != 0:
                return reply.result
            rc = await self._tier_internal_op(
                pool, oid, [OSDOp(OP_RMXATTR, name="cache.dirty")],
                have_lock=have_lock)
            if rc != 0:
                return rc
        self.perf.inc("tier_flush")
        return 0

    async def _tier_agent(self) -> None:
        """The TierAgent loop (PrimaryLogPG::agent_work): under
        target_max_bytes pressure, flush dirty objects then evict cold
        clean ones, per cache pool, for the PGs this OSD leads."""
        interval = self.conf["osd_tier_agent_interval"]
        while not self.stopping:
            await asyncio.sleep(interval)
            om = self.osdmap
            if om is None:
                continue
            for pool in list(om.pools.values()):
                try:
                    target = int(pool.extra.get("target_max_bytes", "0"))
                except (TypeError, ValueError):
                    continue
                if (
                    not target
                    or not pool.extra.get("tier_of")
                    or pool.extra.get("cache_mode") != "writeback"
                ):
                    continue
                try:
                    await self._tier_agent_pool(pool, target)
                except Exception:
                    log.exception("osd.%d: tier agent failed", self.id)

    async def _tier_agent_pool(self, pool, target: int) -> None:
        from ceph_tpu.msg.messages import OSDOp

        base_pid = int(pool.extra["tier_of"])
        mine: list[tuple[str, int, coll_t, ghobject_t]] = []
        total = 0
        for ps in range(pool.pg_num):
            pg = pg_t(pool.id, ps)
            _a, primary = self._acting(pool, pg)
            if primary != self.id:
                continue
            c = coll_t(pool.id, ps, NO_SHARD)
            if not self.store.collection_exists(c):
                continue
            for o in self.store.collection_list(c):
                if o.name == PGMETA_OID or o.snap >= 0:
                    continue
                if self._is_whiteout(c, o):
                    continue
                try:
                    size = self.store.stat(c, o)
                except (FileNotFoundError, OSError):
                    continue
                mine.append((o.name, size, c, o))
                total += size
        if total <= target:
            return
        # coldest first: hitset order is recency (absent = coldest)
        hs = self._hitset(pool.id)
        rank = {oid: i for i, oid in enumerate(hs)}
        mine.sort(key=lambda t: rank.get(t[0], -1))
        for oid, size, c, o in mine:
            if total <= target * 0.8:
                break
            # flush-then-evict is ATOMIC vs client ops on this object:
            # the object lock spans both, so a write can't land between
            # the flush and the delete and be silently dropped
            async with self._obj_lock(pool.id, oid):
                if self._tier_dirty(c, o):
                    if await self._tier_flush(pool, base_pid, c, o, oid,
                                              have_lock=True) != 0:
                        continue
                if await self._tier_internal_op(
                        pool, oid, [OSDOp(OP_DELETE)],
                        have_lock=True) == 0:
                    self.perf.inc("tier_evict")
                    hs.pop(oid, None)
                    total -= size

    # -- EC backend ----------------------------------------------------

    def _shard_coll(self, pool: PgPool, pg: pg_t, shard: int) -> coll_t:
        return coll_t(pool.id, pool.raw_pg_to_pg(pg).ps, shard)

    def _ensure_coll(self, t: Transaction, c: coll_t) -> None:
        if not self.store.collection_exists(c):
            t.create_collection(c)

    def _ec_live(self, pool, acting) -> tuple[list, int | None] | None:
        """(live shard pairs, my_shard) or None when the op must bounce."""
        live = [
            (shard, osd)
            for shard, osd in enumerate(acting)
            if osd != CRUSH_ITEM_NONE
        ]
        if len(live) < pool.min_size:
            return None
        my_shard = next((s for s, o in live if o == self.id), None)
        if my_shard is None:
            # a primary that holds no shard of the live set would mint
            # versions from a PG log it never writes, defeating the
            # stale-shard guards — bounce the op instead
            return None
        return live, my_shard

    async def _ec_fan_out_write(
        self, pool, pg, live, oid, shard_payloads, attrs, version,
        *, off: int = 0, truncate: int = -1, rmattrs: list[str] | None = None,
        reqid: str = "", prev_version=None, _retried: bool = False,
        clone_snap: int = 0, clone_snaps: bytes = b"",
    ) -> int:
        """Fan one versioned shard write out to the live set; returns 0
        or the first failing shard's errno (the ECBackend ECSubWrite
        fan-out, src/osd/ECBackend.cc:943).

        ``prev_version`` (None = unguarded) is the base version this
        write was computed against: every shard must be AT that version
        or the write is refused with ESTALE — a shard that missed
        earlier writes is reconciled (recovery roll-forward) and the
        fan-out retried once, mirroring the reference's write-blocks-on-
        missing-object rule (PrimaryLogPG::is_missing_object wait)."""
        from ceph_tpu.common.fault_injector import FAULTS

        await FAULTS.check("osd.ec_fan_out")
        guarded = prev_version is not None
        parent_sp = self._op_span.get()
        waits = []
        local: list[tuple[int, bytes]] = []
        estale = False
        for shard, osd in live:
            payload = shard_payloads.get(shard, b"")
            if not isinstance(payload, bytes):
                payload = payload.tobytes()
            if osd == self.id:
                c = self._shard_coll(pool, pg, shard)
                o = ghobject_t(oid, shard=shard)
                if guarded and self._object_version(c, o) != prev_version:
                    estale = True
                    continue
                local.append((shard, payload))
            else:
                tid = next(self._tids)
                waits.append(self._traced_sub_op(
                    "ec_sub_write", parent_sp, shard, osd, reqid,
                    self._sub_op(osd, MOSDECSubOpWrite(
                        tid=tid, pg=pg, shard=shard, from_osd=self.id,
                        oid=oid, off=off, data=payload, attrs=attrs,
                        epoch=self.epoch, truncate=truncate,
                        version=version,
                        rmattrs=rmattrs or [], reqid=reqid,
                        prev_version=prev_version, guarded=guarded,
                        clone_snap=clone_snap, clone_snaps=clone_snaps,
                    ), tid)))
        first_err = 0
        if waits:
            for rep in await asyncio.gather(*waits):
                if rep.result == -errno.ESTALE:
                    estale = True
                elif rep.result != 0 and first_err == 0:
                    first_err = rep.result
        if first_err:
            return first_err
        if not estale:
            # the primary's OWN shard applies only after every remote
            # accepted: a demoted primary whose fan-out the cluster
            # rejects must not poison its local shard with a write
            # nobody else has (that one divergent shard would cost the
            # pg its availability margin)
            for shard, payload in local:
                await self._apply_shard_write_async(
                    pool, pg, shard, oid, payload, attrs, version=version,
                    off=off, truncate=truncate, rmattrs=rmattrs,
                    reqid=reqid, clone_snap=clone_snap,
                    clone_snaps=clone_snaps,
                )
        if estale:
            if _retried:
                return -errno.EAGAIN
            # roll the lagging shard(s) forward, then retry once; if the
            # object state moved past our base meanwhile, the client
            # must redo the RMW from the new base
            pairs = [(s, o) for s, o in live]
            try:
                await self._reconcile_object(
                    pool, pg, pairs, oid, have_lock=True)
            except Exception:
                log.exception(
                    "osd.%d: pre-write reconcile of %s failed", self.id, oid)
                return -errno.EAGAIN
            acting_like = [CRUSH_ITEM_NONE] * pool.size
            for s, o in live:
                acting_like[s] = o
            served = await self._ec_served_version(
                pool, pg, acting_like, oid)
            if served != prev_version:
                return -errno.EAGAIN
            return await self._ec_fan_out_write(
                pool, pg, live, oid, shard_payloads, attrs, version,
                off=off, truncate=truncate, rmattrs=rmattrs, reqid=reqid,
                prev_version=prev_version, _retried=True,
                clone_snap=clone_snap, clone_snaps=clone_snaps,
            )
        return 0

    async def _ec_write_vector(
        self, pool, pg, acting, msg, ec, sinfo, admit_epoch: int | None = None
    ) -> MOSDOpReply:
        """EC write-class op vector: full writes encode directly; partial
        writes (write/append/zero/truncate) run the read-modify-write
        pipeline over the dirty stripe range — the ECCommon RMW pipeline
        (reference src/osd/ECCommon.cc:623-707 start_rmw/try_state_to_reads
        + ExtentCache) re-designed as a single batched read → mutate →
        re-encode → fan-out pass."""
        ops = msg.ops
        snapc = self._effective_snapc(pool, msg)
        if snapc.snaps and not snapc.valid():
            return MOSDOpReply(tid=msg.tid, result=-errno.EINVAL, epoch=self.epoch)
        if any(o.op == OP_DELETE for o in ops):
            if len(ops) != 1:
                return MOSDOpReply(tid=msg.tid, result=-errno.EINVAL, epoch=self.epoch)
            return await self._ec_delete(
                pool, pg, acting, msg, snapc, admit_epoch)
        lv = self._ec_live(pool, acting)
        if lv is None:
            return MOSDOpReply(tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        live, my_shard = lv
        # duplicate-op detection: a resend of an already-applied
        # non-idempotent vector is answered, not re-applied (reference:
        # pg-log reqid dup lookup in PrimaryLogPG::do_op)
        lg = self._pg_log(self._shard_coll(pool, pg, my_shard))
        if msg.reqid and msg.reqid in lg.reqids:
            # the log claims this op already applied — but a fan-out
            # that died mid-write may have reached fewer than k shards
            # (the retry exists BECAUSE something failed).  Verify the
            # logged version is actually served before vouching for it;
            # if not, reconcile (roll forward if >= k shards carry it,
            # else divergent-rollback) and re-apply when rolled back.
            logged_v = lg.reqids[msg.reqid]
            served = await self._ec_served_version(
                pool, pg, acting, msg.oid, lg)
            if served is not None and served >= logged_v:
                return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)
            pairs = self._pg_members(pool, acting)
            try:
                await self._reconcile_object(
                    pool, pg, pairs, msg.oid, have_lock=True)
            except Exception:
                log.exception(
                    "osd.%d: dup-retry reconcile of %s failed", self.id,
                    msg.oid)
            served = await self._ec_served_version(
                pool, pg, acting, msg.oid, lg)
            if served is not None and served >= logged_v:
                return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)
            if msg.reqid in lg.reqids:
                # reconcile did not strip it (e.g. zombie entry adopted
                # from a peer log): drop it here so the op re-applies
                t0 = Transaction()
                self._ensure_coll(t0, self._shard_coll(pool, pg, my_shard))
                lg.rollback_divergent(t0, msg.oid, served or ZERO)
                if t0.ops:
                    if getattr(self.store, "blocking_commit", False):
                        await asyncio.to_thread(
                            self.store.queue_transaction, t0)
                    else:
                        self.store.queue_transaction(t0)
            # fall through: apply the vector afresh
        for o in ops:
            if o.op in (OP_OMAP_SETKEYS, OP_OMAP_RMKEYS, OP_OMAP_CLEAR):
                # EC pools have no omap (reference restriction:
                # pool_requires_alignment / MODE_EC forbids omap ops)
                return MOSDOpReply(tid=msg.tid, result=-errno.EOPNOTSUPP, epoch=self.epoch)

        # -- current object state (skipped for a leading WRITE_FULL
        # when no snapshots are in play) ----
        exists, cur_size = False, 0
        cur_v = ZERO  # stale-shard write guard base (see _ec_fan_out_write)
        ss = SnapSet()
        local_ss_raw = self._getattr_quiet(
            self._shard_coll(pool, pg, my_shard),
            ghobject_t(msg.oid, shard=my_shard), SS_ATTR)
        if ops[0].op != OP_WRITE_FULL or snapc.snaps or local_ss_raw:
            try:
                exists, _wo, cur_size, cur_v, ss, _attrs = \
                    await self._ec_head_state(pool, pg, acting, msg.oid)
            except ECFetchError as e:
                return MOSDOpReply(
                    tid=msg.tid, result=-e.errno, epoch=self.epoch)
        else:
            # whole-object replace: the primary's own shard version is
            # the guard base; a mismatch on any shard reconciles first
            cur_v = self._object_version(
                self._shard_coll(pool, pg, my_shard),
                ghobject_t(msg.oid, shard=my_shard))

        # make_writeable: clone-on-write under a newer SnapContext
        clone_snap_arg, clone_snaps_arg = 0, b""
        if exists and ss.needs_cow(snapc):
            cl = ss.make_clone(snapc, cur_size)
            clone_snap_arg = cl.id
            clone_snaps_arg = encode_snaps(cl.snaps)
        else:
            ss.advance_seq(snapc)

        # -- fold the vector into (full | edits) + size + attr deltas ---
        full: np.ndarray | None = None
        edits: list[tuple] = []   # (off, np.ndarray) | ("zfill", off)
        size = cur_size
        attr_sets: dict[str, bytes] = {}
        attr_rms: list[str] = []
        touched = False
        for o in ops:
            if o.op == OP_CREATE:
                if o.off and exists:  # off=1 -> exclusive
                    return MOSDOpReply(tid=msg.tid, result=-errno.EEXIST, epoch=self.epoch)
                touched = True
            elif o.op == OP_WRITE_FULL:
                full = np.frombuffer(o.data, np.uint8)
                edits, size = [], len(o.data)
                touched = exists = True
            elif o.op == OP_WRITE:
                edits.append((o.off, np.frombuffer(o.data, np.uint8)))
                size = max(size, o.off + len(o.data))
                touched = exists = True
            elif o.op == OP_APPEND:
                edits.append((size, np.frombuffer(o.data, np.uint8)))
                size += len(o.data)
                touched = exists = True
            elif o.op == OP_ZERO:
                end = min(size, o.off + o.length)
                if o.off < end:
                    edits.append((o.off, np.zeros(end - o.off, np.uint8)))
                touched = exists = True
            elif o.op == OP_TRUNCATE:
                if o.off < size:
                    # bytes past the cut must read as zero if the object
                    # regrows later in this vector
                    edits.append(("zfill", o.off))
                size = o.off
                touched = exists = True
            elif o.op == OP_SETXATTR:
                attr_sets[USER_XATTR_PREFIX + o.name] = bytes(o.data)
            elif o.op == OP_RMXATTR:
                attr_rms.append(USER_XATTR_PREFIX + o.name)
            elif o.op == OP_ROLLBACK:
                # restore head from the clone serving o.off
                # (PrimaryLogPG::_rollback_to, EC flavor)
                target = ss.resolve(o.off)
                if target is None or (target == NOSNAP and not exists):
                    return MOSDOpReply(
                        tid=msg.tid, result=-errno.ENOENT,
                        epoch=self.epoch)
                if target == NOSNAP:
                    continue  # head already serves that snap
                try:
                    csz, cattrs, cchunks = await self._ec_fetch(
                        pool, pg, acting, msg.oid, ec, snap=target)
                except ECFetchError as e:
                    return MOSDOpReply(
                        tid=msg.tid, result=-e.errno, epoch=self.epoch)
                logical = await self._ecu_decode_concat(sinfo, ec, cchunks)
                full = np.asarray(logical[:csz], np.uint8)
                edits, size = [], csz
                for name, v in (cattrs or {}).items():
                    if name.startswith(USER_XATTR_PREFIX):
                        attr_sets[name] = v
                touched = exists = True
            else:
                return MOSDOpReply(tid=msg.tid, result=-errno.EOPNOTSUPP, epoch=self.epoch)

        version = self._next_version(
            self._shard_coll(pool, pg, my_shard), admit_epoch)
        if version is None:
            return MOSDOpReply(
                tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        base_attrs = {
            SIZE_ATTR: str(size).encode(),
            VERSION_ATTR: _v_bytes(version),
            **attr_sets,
        }
        if ss.seq or ss.clones:
            base_attrs[SS_ATTR] = ss.to_bytes()
        base_attrs[WHITEOUT_ATTR] = b"0"

        # -- xattr-only vector: metadata write, no data churn -----------
        if not touched and full is None and not edits:
            if not exists:
                base_attrs[SIZE_ATTR] = b"0"
            r = await self._ec_fan_out_write(
                pool, pg, live, msg.oid, {}, base_attrs, version,
                rmattrs=attr_rms, reqid=msg.reqid, prev_version=cur_v,
                clone_snap=clone_snap_arg, clone_snaps=clone_snaps_arg,
            )
            return MOSDOpReply(tid=msg.tid, result=r, epoch=self.epoch)

        cs, sw = sinfo.chunk_size, sinfo.stripe_width
        new_shard_len = sinfo.logical_to_next_chunk_offset(size)

        if full is not None:
            # whole-object replace: no read needed; edits (if any) land
            # on the known content
            padded = np.zeros(sinfo.logical_to_next_stripe_offset(size), np.uint8)
            padded[: len(full)] = full
            for e in edits:
                if e[0] == "zfill":
                    padded[e[1]:] = 0
                else:
                    off, buf = e
                    padded[off : off + len(buf)] = buf
            if len(padded):
                shards = await self._ecu_encode(sinfo, ec, padded)
            else:
                shards = {s: np.zeros(0, np.uint8) for s in range(ec.get_chunk_count())}
            hinfo = ecutil.HashInfo(ec.get_chunk_count())
            hinfo.append(0, shards)
            base_attrs[HINFO_ATTR] = hinfo.to_bytes()
            r = await self._ec_fan_out_write(
                pool, pg, live, msg.oid, shards, base_attrs, version,
                off=0, truncate=new_shard_len, rmattrs=attr_rms,
                reqid=msg.reqid, prev_version=cur_v,
                clone_snap=clone_snap_arg, clone_snaps=clone_snaps_arg,
            )
            if r == 0:
                self._extent_cache_put(pool.id, msg.oid, version, 0, padded)
            else:
                self._extent_cache_drop(pool.id, msg.oid)
            return MOSDOpReply(tid=msg.tid, result=r, epoch=self.epoch)

        # -- RMW over the dirty stripe range ----------------------------
        real_edits: list[tuple[int, np.ndarray]] = []
        for e in edits:
            if e[0] == "zfill":
                # zero through the stripe boundary, not just to the
                # final size: a truncate-down must scrub the stale tail
                # of its last stripe, or a later extension (which relies
                # on the "bytes past size are zero" invariant) would
                # resurrect old bytes
                hi = max(size, sinfo.logical_to_next_stripe_offset(e[1]))
                if e[1] < hi:
                    real_edits.append((e[1], np.zeros(hi - e[1], np.uint8)))
            else:
                real_edits.append(e)
        # truncate/create never dirty stripes by themselves: shard-level
        # truncate keeps whole stripes, and store gap/extend writes
        # zero-fill — the parity of all-zero data is all zeros, so holes
        # stay consistent without re-encoding
        dirty = [
            (sinfo.logical_to_prev_stripe_offset(off),
             sinfo.logical_to_next_stripe_offset(off + len(buf)))
            for off, buf in real_edits if len(buf)
        ]
        if not dirty:
            # pure truncate / create / zero-beyond-end
            r = await self._ec_fan_out_write(
                pool, pg, live, msg.oid, {}, base_attrs, version,
                truncate=new_shard_len,
                rmattrs=attr_rms + (
                    [HINFO_ATTR] if exists and size != cur_size else []
                ),
                reqid=msg.reqid, prev_version=cur_v,
                clone_snap=clone_snap_arg, clone_snaps=clone_snaps_arg,
            )
            return MOSDOpReply(tid=msg.tid, result=r, epoch=self.epoch)
        d_lo = min(d[0] for d in dirty)
        d_hi = max(d[1] for d in dirty)
        old_end = sinfo.logical_to_next_stripe_offset(cur_size) if exists else 0
        buf = np.zeros(d_hi - d_lo, np.uint8)
        read_hi = min(d_hi, old_end)
        if exists and d_lo < read_hi:
            cached = self._extent_cache_get(
                pool.id, msg.oid, cur_v, d_lo, read_hi)
            if cached is not None:
                # hot stripe: the bytes we last wrote at cur_v ARE the
                # on-disk content — skip the shard read entirely
                buf[: read_hi - d_lo] = cached
            else:
                c_lo = sinfo.logical_to_prev_chunk_offset(d_lo)
                c_len = sinfo.logical_to_prev_chunk_offset(read_hi) - c_lo
                try:
                    _sz, _a, chunks = await self._ec_fetch(
                        pool, pg, acting, msg.oid, ec,
                        chunk_off=c_lo, chunk_len=c_len,
                        fast_read=pool.fast_read,
                    )
                except ECFetchError as e:
                    return MOSDOpReply(tid=msg.tid, result=-e.errno, epoch=self.epoch)
                old_logical = await self._ecu_decode_concat(sinfo, ec, chunks)
                buf[: len(old_logical)] = old_logical
        for off, data in real_edits:
            lo = max(off, d_lo)
            hi = min(off + len(data), d_hi)
            if lo < hi:
                buf[lo - d_lo : hi - d_lo] = data[lo - off : hi - off]
        shards = await self._ecu_encode(sinfo, ec, buf)
        # the cumulative-append crc chain cannot survive an overwrite;
        # deep scrub falls back to the parity-equation check (the
        # reference's ec_overwrites pools drop hinfo the same way)
        r = await self._ec_fan_out_write(
            pool, pg, live, msg.oid, shards, base_attrs, version,
            off=sinfo.logical_to_prev_chunk_offset(d_lo),
            truncate=new_shard_len,
            rmattrs=attr_rms + [HINFO_ATTR], reqid=msg.reqid,
            prev_version=cur_v,
            clone_snap=clone_snap_arg, clone_snaps=clone_snaps_arg,
        )
        if r == 0:
            self._extent_cache_put(pool.id, msg.oid, version, d_lo, buf)
        else:
            self._extent_cache_drop(pool.id, msg.oid)
        return MOSDOpReply(tid=msg.tid, result=r, epoch=self.epoch)

    def _apply_shard_write(
        self, pool, pg, shard, oid, payload: bytes, attrs,
        delete=False, version: eversion_t = ZERO,
        off: int = 0, truncate: int | None = None,
        rmattrs: list[str] | None = None, reqid: str = "",
    ) -> None:
        """Apply a shard write + (when versioned) its pg-log entry in
        ONE transaction — the reference couples data and log the same
        way (ECTransaction appends log entries to the shard txn)."""
        self.store.queue_transaction(
            self._shard_write_txn(pool, pg, shard, oid, payload, attrs,
                                  delete, version, off, truncate, rmattrs,
                                  reqid)
        )

    async def _apply_shard_write_async(
        self, pool, pg, shard, oid, payload: bytes, attrs,
        delete=False, version: eversion_t = ZERO,
        off: int = 0, truncate: int | None = None,
        rmattrs: list[str] | None = None, reqid: str = "",
        clone_snap: int = 0, clone_snaps: bytes = b"",
    ) -> None:
        """Same, but journaling stores fsync: run their commit on a
        worker thread so one OSD's disk flush never stalls the whole
        event loop (the reference's journaling happens on dedicated
        finisher threads for the same reason)."""
        t = self._shard_write_txn(
            pool, pg, shard, oid, payload, attrs, delete, version,
            off, truncate, rmattrs, reqid, clone_snap, clone_snaps,
        )
        if getattr(self.store, "blocking_commit", False):
            await asyncio.to_thread(self.store.queue_transaction, t)
        else:
            self.store.queue_transaction(t)

    def _shard_write_txn(
        self, pool, pg, shard, oid, payload, attrs, delete, version,
        off: int = 0, truncate: int | None = None,
        rmattrs: list[str] | None = None, reqid: str = "",
        clone_snap: int = 0, clone_snaps: bytes = b"",
    ) -> Transaction:
        """``truncate`` semantics: None keeps legacy whole-replace
        (truncate to len(payload)); -1 leaves the length alone (ranged
        RMW writes and metadata-only writes); >= 0 sets the exact shard
        length after the write (store truncate zero-fills on extend).
        ``clone_snap`` != 0 snapshots the local head shard into
        (oid, snap=clone_snap) before applying (make_writeable COW)."""
        c = self._shard_coll(pool, pg, shard)
        o = ghobject_t(oid, shard=shard)
        t = Transaction()
        self._ensure_coll(t, c)
        if clone_snap:
            cl = ghobject_t(oid, snap=clone_snap, shard=shard)
            if self.store.exists(c, o) and not self.store.exists(c, cl):
                t.clone(c, o, cl)
                t.setattrs(c, cl, {SNAPS_ATTR: clone_snaps})
        if delete:
            if self.store.exists(c, o):
                t.remove(c, o)
        else:
            t.touch(c, o)
            if payload:
                t.write(c, o, off, payload)
            if truncate is None:
                if off == 0:
                    t.truncate(c, o, len(payload))
            elif truncate >= 0:
                t.truncate(c, o, truncate)
            if attrs:
                t.setattrs(c, o, attrs)
            for name in rmattrs or ():
                t.rmattr(c, o, name)
        if version > ZERO:
            lg = self._pg_log(c)
            if version > lg.info.last_update:
                prior = self._object_version(c, o)
                lg.append(t, pg_log_entry_t(
                    DELETE if delete else MODIFY, oid, version, prior,
                    reqid,
                ))
                lg.trim(t, self._log_keep)
        return t

    async def _ec_head_state(self, pool, pg, acting, oid):
        """Probe the EC head object: (exists, whiteout, size, version,
        SnapSet, attrs).  exists is False for a whiteout head (data-
        plane absent) but the SnapSet still anchors its clones."""
        ec = self._ec_for(pool)
        try:
            sz, attrs, _ = await self._ec_fetch(
                pool, pg, acting, oid, ec, want_data=False)
        except ECFetchError as e:
            if e.errno != errno.ENOENT:
                raise  # degraded, not absent: callers surface the errno
            return False, False, 0, ZERO, SnapSet(), {}
        ss = SnapSet.from_bytes(attrs.get(SS_ATTR))
        wo = attrs.get(WHITEOUT_ATTR) == b"1"
        v = _v_parse(attrs.get(VERSION_ATTR))
        return (not wo), wo, (0 if wo else sz), v, ss, attrs

    async def _ec_served_version(
        self, pool, pg, acting, oid, lg=None
    ) -> "eversion_t | None":
        """The object version a consistent k-shard subset currently
        serves (None = nothing decodable right now).  An absent object
        whose newest log entry is a DELETE counts as served at the
        delete's version (the write wasn't lost — it was superseded)."""
        ec = self._ec_for(pool)
        try:
            _sz, attrs, _ = await self._ec_fetch(
                pool, pg, acting, oid, ec, want_data=False)
        except ECFetchError as e:
            if e.errno != errno.ENOENT:
                return None
            if lg is not None:
                for v in sorted(lg.entries, reverse=True):
                    if lg.entries[v].oid == oid:
                        if lg.entries[v].op == DELETE:
                            return v
                        break
            return ZERO
        return _v_parse(attrs.get(VERSION_ATTR))

    async def _traced_sub_op(self, name, parent, shard, osd, reqid, coro):
        """Child span per shard sub-op (the reference opens jaeger
        child spans per ECSubRead/Write, ECCommon.cc:440-445)."""
        with self.tracer.span(
            name, parent=parent, shard=shard, osd=osd, reqid=reqid,
        ):
            return await coro

    def _ec_avail(self, acting) -> dict[int, int]:
        """shard -> osd for the currently usable members of an acting
        set (shared by the normal and fast_read fetch paths)."""
        return {
            shard: osd for shard, osd in enumerate(acting)
            if osd != CRUSH_ITEM_NONE and self.osdmap.is_up(osd)
        }

    async def _ec_fetch_fast(
        self, pool, pg, acting, oid, ec, *,
        chunk_off: int = 0, chunk_len: int = 0, snap: int = NOSNAP,
    ):
        """fast_read flavor (reference ECCommon.cc:531 + the fast_read
        pool option): fan the ranged read to EVERY available shard at
        once and complete from the first k version-consistent replies —
        latency is the fastest k of n shards instead of a fixed-k read
        plus retry rounds."""
        import numpy as np

        k = ec.get_data_chunk_count()
        avail = {
            shard: osd for shard, osd in enumerate(acting)
            if osd != CRUSH_ITEM_NONE and self.osdmap.is_up(osd)
        }
        if len(avail) < k:
            raise ECFetchError(errno.EIO)
        async def read_one(s, o):
            return s, await self._read_shard_quiet(
                pool, pg, s, o, oid, off=chunk_off, length=chunk_len,
                snap=snap,
            )

        tasks = [
            asyncio.ensure_future(read_one(s, o)) for s, o in avail.items()
        ]
        got: dict[int, tuple] = {}
        enoent = 0
        try:
            for fut in asyncio.as_completed(tasks):
                shard, (payload, attrs, eno) = await fut
                if payload is None:
                    if eno == errno.ENOENT:
                        enoent += 1
                    continue
                got[shard] = (payload, attrs or {})
                # complete as soon as k shards agree on the newest
                # version seen so far
                versions = {
                    s2: _v_parse(a.get(VERSION_ATTR))
                    for s2, (_p, a) in got.items()
                }
                vmax = max(versions.values())
                fresh = [s2 for s2, v in versions.items() if v == vmax]
                if len(fresh) >= k:
                    self.perf.inc("ec_fast_read")
                    attrs = got[fresh[0]][1]
                    chunks = {
                        s2: np.frombuffer(got[s2][0], np.uint8)
                        for s2 in fresh[:k]
                    }
                    if SIZE_ATTR not in attrs:
                        raise ECFetchError(errno.ENOENT)
                    return int(attrs[SIZE_ATTR]), attrs, chunks
        finally:
            for t in tasks:
                if not t.done():
                    t.cancel()
        if enoent and enoent == len(tasks) - len(got):
            raise ECFetchError(errno.ENOENT)
        raise ECFetchError(errno.EIO)

    async def _ec_fetch(
        self, pool, pg, acting, oid, ec, *,
        chunk_off: int = 0, chunk_len: int = 0, want_data: bool = True,
        snap: int = NOSNAP, fast_read: bool = False,
    ):
        """Version-consistent EC shard fetch — the ECCommon read
        pipeline (reference src/osd/ECCommon.cc:440-445 fans ECSubRead
        to all shards concurrently; stale shards are excluded and the
        read retried with a different shard set).

        Returns ``(size, attrs, chunks)``; ``chunks`` maps shard id to
        the requested chunk byte range (empty when ``want_data`` is
        False — a probe).  ``chunk_len == 0`` reads to the shard end.
        Raises :class:`ECFetchError` with ENOENT for a fully-absent
        object, EIO otherwise.
        """
        if (
            fast_read and want_data
            and getattr(ec, "mds_any_k", False)
            and ec.get_sub_chunk_count() == 1
        ):
            # decode-from-any-k is only sound for MDS codes; non-MDS
            # plugins (shec/lrc) and sub-chunk codes take the
            # minimum_to_decode-driven path below
            try:
                return await self._ec_fetch_fast(
                    pool, pg, acting, oid, ec,
                    chunk_off=chunk_off, chunk_len=chunk_len, snap=snap,
                )
            except ECFetchError:
                raise
            except Exception:
                log.exception(
                    "osd.%d: fast_read fetch failed; normal path", self.id)
        k = ec.get_data_chunk_count()
        avail = self._ec_avail(acting)
        excluded: dict[int, int] = {}  # shard -> errno seen
        for _attempt in range(len(acting) + 1):
            usable = {s: o for s, o in avail.items() if s not in excluded}
            want = set(range(k))
            try:
                minimum = ec.minimum_to_decode(want, set(usable))
            except Exception:
                break  # not enough shards left to decode
            need_shards = sorted(set(minimum))
            if want_data:
                reads = (
                    self._read_shard_quiet(
                        pool, pg, s, usable[s], oid,
                        off=chunk_off, length=chunk_len, snap=snap,
                    )
                    for s in need_shards
                )
            else:
                reads = (
                    self._read_shard_quiet(
                        pool, pg, s, usable[s], oid, off=0, length=1,
                        snap=snap,
                    )
                    for s in need_shards
                )
            results = await asyncio.gather(*reads)
            chunks: dict[int, np.ndarray] = {}
            shard_attrs: dict[int, dict[str, bytes]] = {}
            failed = False
            for shard, (payload, a, eno) in zip(need_shards, results):
                if payload is None:
                    excluded[shard] = eno
                    failed = True
                else:
                    chunks[shard] = np.frombuffer(payload, np.uint8)
                    shard_attrs[shard] = a or {}
            if failed:
                continue
            # a revived OSD may hold a STALE chunk from before it went
            # down: all chunks used in one decode must carry the same
            # object version (object_info consistency; the reference
            # reaches this via peering/recovery before serving)
            versions = {
                s: _v_parse(a.get(VERSION_ATTR)) for s, a in shard_attrs.items()
            }
            vmax = max(versions.values(), default=ZERO)
            stale = [s for s, v in versions.items() if v < vmax]
            if stale:
                for s in stale:
                    excluded[s] = errno.ESTALE
                continue
            attrs = next(iter(shard_attrs.values()), {})
            if not attrs or SIZE_ATTR not in attrs:
                raise ECFetchError(errno.ENOENT)
            return int(attrs[SIZE_ATTR]), attrs, (chunks if want_data else {})
        if excluded and all(e == errno.ENOENT for e in excluded.values()):
            raise ECFetchError(errno.ENOENT)
        raise ECFetchError(errno.EIO)

    async def _ec_read_vector(
        self, pool, pg, acting, msg, ec, sinfo
    ) -> MOSDOpReply:
        """EC read-class op vector served from ONE version-consistent
        shard snapshot: ranged reads fetch only the covering stripes
        (objecter-style extent math) and xattrs ride the same attrs."""
        ops = msg.ops
        try:
            if any(o.op == OP_LIST_SNAPS for o in ops):
                _ex, _wo, _sz, _v, ss, _a = await self._ec_head_state(
                    pool, pg, acting, msg.oid)
                return MOSDOpReply(
                    tid=msg.tid, result=0, epoch=self.epoch,
                    data=ss.to_bytes())
            read_snap = NOSNAP
            if msg.snapid != NOSNAP:
                # find_object_context: route the read at a clone
                _ex, _wo, _sz, _v, ss, _a = await self._ec_head_state(
                    pool, pg, acting, msg.oid)
                target = ss.resolve(msg.snapid)
                if target is None or (target == NOSNAP and (
                        msg.snapid <= ss.seq or not _ex)):
                    return MOSDOpReply(
                        tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
                if target != NOSNAP:
                    read_snap = target
        except ECFetchError as e:
            return MOSDOpReply(
                tid=msg.tid, result=-e.errno, epoch=self.epoch)
        reads = [o for o in ops if o.op == OP_READ]
        chunk_off = chunk_len = 0
        if reads:
            lo = min(o.off for o in reads)
            chunk_off = sinfo.logical_to_prev_chunk_offset(lo)
            if not any(o.length == 0 for o in reads):
                hi = max(o.off + o.length for o in reads)
                chunk_len = sinfo.logical_to_next_chunk_offset(hi) - chunk_off
        try:
            size, attrs, chunks = await self._ec_fetch(
                pool, pg, acting, msg.oid, ec,
                chunk_off=chunk_off, chunk_len=chunk_len,
                want_data=bool(reads), snap=read_snap,
                fast_read=pool.fast_read,
            )
        except ECFetchError as e:
            return MOSDOpReply(tid=msg.tid, result=-e.errno, epoch=self.epoch)
        if read_snap == NOSNAP and attrs.get(WHITEOUT_ATTR) == b"1":
            return MOSDOpReply(
                tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
        logical = None
        base = 0
        if reads and chunks and any(len(v) for v in chunks.values()):
            logical = await self._ecu_decode_concat(sinfo, ec, chunks)
            base = sinfo.aligned_chunk_offset_to_logical_offset(chunk_off)
        outs: list[tuple[int, bytes, dict[str, bytes]]] = []
        first_read: bytes | None = None
        for o in ops:
            r, d, kv = 0, b"", {}
            if o.op == OP_READ:
                end = size if o.length == 0 else min(o.off + o.length, size)
                if logical is not None and o.off < end:
                    d = logical[o.off - base : end - base].tobytes()
                if first_read is None:  # summarize the FIRST read op,
                    first_read = d      # even when it returned 0 bytes
            elif o.op == OP_STAT:
                pass
            elif o.op == OP_GETXATTR:
                v = attrs.get(USER_XATTR_PREFIX + o.name)
                if v is None:
                    r = -errno.ENODATA
                else:
                    d = v
            elif o.op == OP_GETXATTRS:
                kv = {
                    name[len(USER_XATTR_PREFIX):]: v
                    for name, v in attrs.items()
                    if name.startswith(USER_XATTR_PREFIX)
                }
            else:
                # omap reads: EC pools have no omap (reference restriction)
                r = -errno.EOPNOTSUPP
            outs.append((r, d, kv))
        result = next((r for r, _d, _kv in outs if r != 0), 0)
        return MOSDOpReply(
            tid=msg.tid, result=result, epoch=self.epoch, size=size,
            data=first_read or b"", outs=outs,
        )

    async def _read_shard_quiet(
        self, pool, pg, shard, osd, oid, *, off: int = 0, length: int = 0,
        extents: list[tuple[int, int]] | None = None, snap: int = NOSNAP,
    ):
        """_read_shard with transport failures mapped to EIO."""
        try:
            return await self._read_shard(
                pool, pg, shard, osd, oid, off=off, length=length,
                extents=extents, snap=snap,
            )
        except (OSError, asyncio.TimeoutError, ConnectionError):
            return None, None, errno.EIO

    async def _read_shard(
        self, pool, pg, shard, osd, oid, *, off: int = 0, length: int = 0,
        extents: list[tuple[int, int]] | None = None, snap: int = NOSNAP,
    ):
        """Ranged chunk read of one shard: (payload, attrs, errno).
        ``length == 0`` reads to the shard end.  ``extents`` returns
        the concatenation of multiple byte runs (sub-chunk repair).
        ``snap`` != NOSNAP reads the clone shard object instead."""
        if osd == self.id:
            c = self._shard_coll(pool, pg, shard)
            o = (ghobject_t(oid, shard=shard) if snap == NOSNAP
                 else ghobject_t(oid, snap=snap, shard=shard))
            if not self.store.exists(c, o):
                return None, None, errno.ENOENT
            if extents:
                data = _read_extents(self.store, c, o, extents)
            else:
                data = self.store.read(
                    c, o, off, None if length == 0 else length
                )
            return data, self.store.getattrs(c, o), 0
        tid = next(self._tids)
        rep = await self._traced_sub_op(
            "ec_sub_read", self._op_span.get(), shard, osd,
            "", self._sub_op(osd, MOSDECSubOpRead(
                tid=tid, pg=pg, shard=shard, from_osd=self.id, oid=oid,
                off=off, length=length, want_attrs=True, epoch=self.epoch,
                extents=extents or [], snap=snap,
            ), tid))
        if rep.result != 0:
            return None, None, -rep.result
        return rep.data, rep.attrs, 0

    async def _ec_delete(self, pool, pg, acting, msg, snapc=None,
                         admit_epoch: int | None = None) -> MOSDOpReply:
        my_shard = next(
            (s for s, o in enumerate(acting) if o == self.id), None
        )
        if my_shard is None:
            # same guard as _ec_write_full: never mint versions from a
            # shard log this OSD doesn't own
            return MOSDOpReply(tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        lg = self._pg_log(self._shard_coll(pool, pg, my_shard))
        if msg.reqid and msg.reqid in lg.reqids:
            return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)
        # snapshots: a delete under a newer SnapContext clones first;
        # if clones anchor to this name, leave a whiteout head (the
        # snapdir role) instead of removing the shard objects
        if snapc is not None and (snapc.snaps or self._getattr_quiet(
                self._shard_coll(pool, pg, my_shard),
                ghobject_t(msg.oid, shard=my_shard), SS_ATTR)):
            try:
                exists, _wo, cur_size, cur_v, ss, _ = \
                    await self._ec_head_state(pool, pg, acting, msg.oid)
            except ECFetchError as e:
                return MOSDOpReply(
                    tid=msg.tid, result=-e.errno, epoch=self.epoch)
            if not exists and ss.clones:
                # already a whiteout (or absent) but clones anchor here:
                # a second DELETE must not remove the snapdir head
                return MOSDOpReply(
                    tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
            clone_snap_arg, clone_snaps_arg = 0, b""
            if exists and ss.needs_cow(snapc):
                cl = ss.make_clone(snapc, cur_size)
                clone_snap_arg = cl.id
                clone_snaps_arg = encode_snaps(cl.snaps)
            else:
                ss.advance_seq(snapc)
            if ss.clones and exists:
                lv = self._ec_live(pool, acting)
                if lv is None:
                    return MOSDOpReply(
                        tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
                live, _ = lv
                version = self._next_version(
                    self._shard_coll(pool, pg, my_shard), admit_epoch)
                if version is None:
                    return MOSDOpReply(
                        tid=msg.tid, result=-errno.EAGAIN,
                        epoch=self.epoch)
                wo_attrs = {
                    SIZE_ATTR: b"0",
                    VERSION_ATTR: _v_bytes(version),
                    WHITEOUT_ATTR: b"1",
                    SS_ATTR: ss.to_bytes(),
                }
                r = await self._ec_fan_out_write(
                    pool, pg, live, msg.oid, {}, wo_attrs, version,
                    truncate=0, reqid=msg.reqid, prev_version=cur_v,
                    clone_snap=clone_snap_arg, clone_snaps=clone_snaps_arg,
                )
                return MOSDOpReply(tid=msg.tid, result=r, epoch=self.epoch)
        self._extent_cache_drop(pool.id, msg.oid)
        version = self._next_version(
            self._shard_coll(pool, pg, my_shard), admit_epoch)
        if version is None:
            return MOSDOpReply(
                tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        waits = []
        for shard, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            if osd == self.id:
                await self._apply_shard_write_async(
                    pool, pg, shard, msg.oid, b"", {}, delete=True,
                    version=version, reqid=msg.reqid,
                )
            else:
                tid = next(self._tids)
                waits.append(self._sub_op(osd, MOSDECSubOpWrite(
                    tid=tid, pg=pg, shard=shard, from_osd=self.id,
                    oid=msg.oid, off=0, data=b"", attrs={},
                    epoch=self.epoch, delete=True, version=version,
                    reqid=msg.reqid,
                ), tid))
        if waits:
            await asyncio.gather(*waits)
        return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)

    async def _handle_sub_write(self, msg: MOSDECSubOpWrite) -> None:
        from ceph_tpu.common.fault_injector import FAULTS

        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        result = 0
        try:
            await FAULTS.check("osd.ec_sub_write_apply")
            if msg.version > ZERO and msg.version.epoch < self.epoch:
                # a sub-write minted under an older map (the version
                # carries the sender's ADMISSION epoch): accept it only
                # if the sender still leads this pg in OUR map — a
                # demoted primary's in-flight fan-out must not land
                # (the reference's require_same_or_newer_map gate)
                _u, _up, _a, cur_primary = self.osdmap.pg_to_up_acting_osds(
                    pg_t(msg.pg.pool, msg.pg.ps), folded=True)
                if msg.from_osd != cur_primary:
                    result = -errno.ESTALE
            skip = False
            if msg.guard > ZERO:
                c = self._shard_coll(pool, msg.pg, msg.shard)
                o = ghobject_t(msg.oid, shard=msg.shard)
                skip = self._object_version(c, o) > msg.guard
            if msg.guarded and not skip and result == 0:
                c = self._shard_coll(pool, msg.pg, msg.shard)
                o = ghobject_t(msg.oid, shard=msg.shard)
                if self._object_version(c, o) != msg.prev_version:
                    # this shard missed earlier writes (or holds a
                    # divergent newer one): recovery must reconcile it
                    # before it may accept new versions, or a partial
                    # write would stamp stale data current
                    result = -errno.ESTALE
            if not skip and result == 0:
                await self._apply_shard_write_async(
                    pool, msg.pg, msg.shard, msg.oid, msg.data, msg.attrs,
                    delete=msg.delete, version=msg.version,
                    off=msg.off, truncate=msg.truncate,
                    rmattrs=msg.rmattrs, reqid=msg.reqid,
                    clone_snap=msg.clone_snap, clone_snaps=msg.clone_snaps,
                )
        except OSError as e:
            result = -(e.errno or errno.EIO)
        await msg.conn.send_message(MOSDECSubOpWriteReply(
            tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
            result=result, epoch=self.epoch,
        ))

    async def _handle_sub_read(self, msg: MOSDECSubOpRead) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        c = self._shard_coll(pool, msg.pg, msg.shard)
        o = (ghobject_t(msg.oid, shard=msg.shard) if msg.snap == NOSNAP
             else ghobject_t(msg.oid, snap=msg.snap, shard=msg.shard))
        if not self.store.exists(c, o):
            rep = MOSDECSubOpReadReply(
                tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
                result=-errno.ENOENT, epoch=self.epoch,
            )
        else:
            try:
                if msg.extents:
                    data = _read_extents(self.store, c, o, msg.extents)
                else:
                    data = self.store.read(
                        c, o, msg.off, None if msg.length == 0 else msg.length
                    )
                self.perf.inc("subop_read_bytes", len(data))
                attrs = self.store.getattrs(c, o) if msg.want_attrs else {}
                rep = MOSDECSubOpReadReply(
                    tid=msg.tid, pg=msg.pg, shard=msg.shard,
                    from_osd=self.id, result=0, data=data, attrs=attrs,
                    epoch=self.epoch,
                )
            except OSError as e:
                # e.g. a checksum-at-rest failure (BlockStore EIO): the
                # primary excludes this shard and reconstructs from the
                # others (the reference's shard-EIO path,
                # ECBackend::handle_sub_read error handling)
                rep = MOSDECSubOpReadReply(
                    tid=msg.tid, pg=msg.pg, shard=msg.shard,
                    from_osd=self.id, result=-(e.errno or 5),
                    epoch=self.epoch,
                )
        await msg.conn.send_message(rep)

    # -- watch/notify (PrimaryLogPG watch/notify + MWatchNotify) -------

    async def _watch_notify_vector(self, pool, pg, msg) -> MOSDOpReply:
        import base64
        import json

        outs = []
        for o in msg.ops:
            r, d, kv = 0, b"", {}
            key = (pool.id, msg.oid)
            if o.op not in (OP_WATCH, OP_UNWATCH, OP_NOTIFY):
                # watch vectors are control-only; silently "succeeding"
                # a data op here would drop it
                outs.append((-errno.EOPNOTSUPP, b"", {}))
                continue
            if o.op == OP_WATCH:
                self._watchers.setdefault(key, {})[
                    (msg.src, o.off)
                ] = msg.conn
            elif o.op == OP_UNWATCH:
                self._watchers.get(key, {}).pop((msg.src, o.off), None)
            elif o.op == OP_NOTIFY:
                notify_id = next(self._tids)
                timeout = (o.length or 5000) / 1000.0
                watchers = dict(self._watchers.get(key, {}))
                acks: list[tuple] = []
                missed: list[tuple] = []
                waits = []
                for (entity, cookie), conn in watchers.items():
                    fut = asyncio.get_running_loop().create_future()
                    self._notify_waiters[(notify_id, entity, cookie)] = fut
                    try:
                        await conn.send_message(MWatchNotify(
                            notify_id=notify_id, cookie=cookie,
                            oid=msg.oid, pool=pool.id, payload=o.data,
                        ))
                        waits.append((entity, cookie, fut))
                    except (ConnectionError, OSError):
                        # dead watcher: drop it (client linger would
                        # re-establish in the reference)
                        self._watchers.get(key, {}).pop((entity, cookie), None)
                        self._notify_waiters.pop((notify_id, entity, cookie), None)
                deadline = asyncio.get_running_loop().time() + timeout
                for entity, cookie, fut in waits:
                    remaining = deadline - asyncio.get_running_loop().time()
                    try:
                        ack = await asyncio.wait_for(
                            fut, max(0.001, remaining)
                        )
                        acks.append((entity, cookie, ack.reply))
                    except asyncio.TimeoutError:
                        missed.append((entity, cookie))
                    finally:
                        self._notify_waiters.pop((notify_id, entity, cookie), None)
                d = json.dumps({
                    "acks": [
                        [list(e), c, base64.b64encode(rep).decode()]
                        for e, c, rep in acks
                    ],
                    "timeouts": [[list(e), c] for e, c in missed],
                }).encode()
            outs.append((r, d, kv))
        data = next((d for _r, d, _kv in outs if d), b"")
        result = next((r for r, _d, _kv in outs if r != 0), 0)
        return MOSDOpReply(
            tid=msg.tid, result=result, epoch=self.epoch, data=data,
            outs=outs,
        )

    def _handle_notify_ack(self, msg: MWatchNotifyAck) -> None:
        fut = self._notify_waiters.get((msg.notify_id, msg.src, msg.cookie))
        if fut and not fut.done():
            fut.set_result(msg)

    # -- replicated backend -------------------------------------------

    # -- snapshots (make_writeable / find_object_context twins) --------

    def _load_snapset(self, c: coll_t, oid: str) -> SnapSet:
        try:
            return SnapSet.from_bytes(
                self.store.getattr(c, ghobject_t(oid), SS_ATTR))
        except (KeyError, FileNotFoundError):
            return SnapSet()

    def _is_whiteout(self, c: coll_t, o: ghobject_t) -> bool:
        try:
            return self.store.getattr(c, o, WHITEOUT_ATTR) == b"1"
        except (KeyError, FileNotFoundError):
            return False

    @staticmethod
    def _effective_snapc(pool, msg) -> SnapContext:
        """Client self-managed context, else the pool-snap context
        (pg_pool_t::get_snap_context fallback)."""
        if msg.snaps:
            return SnapContext(msg.snap_seq, list(msg.snaps))
        return pool.get_snap_context()

    def _resolve_read_object(
        self, c: coll_t, oid: str, snapid: int
    ) -> tuple[ghobject_t, int] | int:
        """find_object_context: map (oid, snapid) to the store object
        serving that snap.  Returns (ghobject, errno 0) or an errno."""
        head = ghobject_t(oid)
        if snapid == NOSNAP:
            if not self.store.exists(c, head) or self._is_whiteout(c, head):
                return errno.ENOENT
            return head, 0
        ss = self._load_snapset(c, oid)
        target = ss.resolve(snapid)
        if target is None:
            return errno.ENOENT  # no clone covers it: absent at that snap
        if target == NOSNAP:
            # no clone covers it: the head serves the read only if no
            # write happened since the snap (snapid > seq); otherwise
            # the snap's content is gone (trimmed or never existed)
            if snapid <= ss.seq:
                return errno.ENOENT
            if not self.store.exists(c, head) or self._is_whiteout(c, head):
                return errno.ENOENT
            return head, 0
        clone = ghobject_t(oid, snap=target)
        if not self.store.exists(c, clone):
            return errno.ENOENT
        return clone, 0

    async def _rep_read_vector(self, pool, pg, acting, msg) -> MOSDOpReply:
        c = self._shard_coll(pool, pg, NO_SHARD)
        if any(o.op == OP_LIST_SNAPS for o in msg.ops):
            ss = self._load_snapset(c, msg.oid)
            return MOSDOpReply(
                tid=msg.tid, result=0, epoch=self.epoch, data=ss.to_bytes())
        resolved = self._resolve_read_object(c, msg.oid, msg.snapid)
        if isinstance(resolved, int):
            return MOSDOpReply(
                tid=msg.tid, result=-resolved, epoch=self.epoch)
        o, _ = resolved
        size = self.store.stat(c, o)
        outs: list[tuple[int, bytes, dict[str, bytes]]] = []
        first_read: bytes | None = None
        for op in msg.ops:
            r, d, kv = 0, b"", {}
            if op.op == OP_READ:
                d = self.store.read(c, o, op.off, op.length or None)
                if first_read is None:
                    first_read = d
            elif op.op == OP_STAT:
                pass
            elif op.op == OP_GETXATTR:
                try:
                    d = self.store.getattr(c, o, USER_XATTR_PREFIX + op.name)
                except KeyError:
                    r = -errno.ENODATA
            elif op.op == OP_GETXATTRS:
                kv = {
                    name[len(USER_XATTR_PREFIX):]: v
                    for name, v in self.store.getattrs(c, o).items()
                    if name.startswith(USER_XATTR_PREFIX)
                }
            elif op.op == OP_OMAP_GETKEYS:
                kv = {k: b"" for k in self.store.omap_get(c, o)}
            elif op.op == OP_OMAP_GETVALS:
                kv = self.store.omap_get(c, o)
            elif op.op == OP_OMAP_GETVALSBYKEYS:
                kv = self.store.omap_get_values(c, o, op.keys)
            elif op.op == OP_CALL:
                from ceph_tpu import cls as _cls

                cname, _, meth = op.name.partition(".")
                ctx = _cls.MethodContext(self.store, c, o)
                r, d = _cls.call(cname, meth, ctx, op.data)
            else:
                r = -errno.EOPNOTSUPP
            outs.append((r, d, kv))
        result = next((r for r, _d, _kv in outs if r != 0), 0)
        return MOSDOpReply(
            tid=msg.tid, result=result, epoch=self.epoch, size=size,
            data=first_read or b"", outs=outs,
        )

    def _rep_effects(
        self, c: coll_t, o: ghobject_t, ops, ss: SnapSet | None = None
    ) -> tuple[list, int, bool] | int:
        """Resolve a client write vector into a deterministic effect
        vector + final size (the primary's role before MOSDRepOp ships
        the transaction in the reference).  Returns an errno on guard
        failure.  ``ss`` (the object's SnapSet) serves ROLLBACK."""
        from ceph_tpu.msg.messages import OSDOp

        exists = self.store.exists(c, o) and not self._is_whiteout(c, o)
        size = self.store.stat(c, o) if exists else 0
        effects: list[OSDOp] = []
        outs: list[tuple[int, bytes, dict]] = []
        expanded: list[OSDOp] = []
        for op in ops:
            if op.op == OP_CALL:
                # run the object-class method on the primary; its
                # recorded mutations splice into the effect vector so
                # class side effects replicate atomically (objclass
                # dispatch, src/osd/PrimaryLogPG.cc CEPH_OSD_OP_CALL)
                from ceph_tpu import cls as _cls

                cname, _, meth = op.name.partition(".")
                ctx = _cls.MethodContext(self.store, c, o)
                rc, outdata = _cls.call(cname, meth, ctx, op.data)
                outs.append((rc, outdata, {}))
                if rc < 0:
                    return -rc
                expanded.extend(ctx.effects)
            else:
                outs.append((0, b"", {}))
                expanded.append(op)
        for op in expanded:
            if op.op == OP_CREATE:
                if op.off and exists:
                    return errno.EEXIST
                exists = True
                effects.append(OSDOp(OP_CREATE))
            elif op.op == OP_WRITE_FULL:
                effects.append(OSDOp(OP_WRITE_FULL, data=op.data))
                size, exists = len(op.data), True
            elif op.op == OP_WRITE:
                effects.append(OSDOp(OP_WRITE, off=op.off, data=op.data))
                size, exists = max(size, op.off + len(op.data)), True
            elif op.op == OP_APPEND:
                effects.append(OSDOp(OP_WRITE, off=size, data=op.data))
                size, exists = size + len(op.data), True
            elif op.op == OP_ZERO:
                end = min(size, op.off + op.length)
                if op.off < end:
                    effects.append(OSDOp(OP_ZERO, off=op.off, length=end - op.off))
                exists = True
            elif op.op == OP_TRUNCATE:
                effects.append(OSDOp(OP_TRUNCATE, off=op.off))
                size, exists = op.off, True
            elif op.op == OP_SETXATTR:
                effects.append(OSDOp(OP_SETXATTR, name=op.name, data=op.data))
                exists = True
            elif op.op == OP_RMXATTR:
                effects.append(OSDOp(OP_RMXATTR, name=op.name))
                exists = True
            elif op.op == OP_OMAP_SETKEYS:
                effects.append(OSDOp(OP_OMAP_SETKEYS, kv=op.kv))
                exists = True
            elif op.op == OP_OMAP_RMKEYS:
                effects.append(OSDOp(OP_OMAP_RMKEYS, keys=op.keys))
                exists = True
            elif op.op == OP_OMAP_CLEAR:
                effects.append(OSDOp(OP_OMAP_CLEAR))
                exists = True
            elif op.op == OP_DELETE:
                if not exists:
                    # absent or whiteout head: nothing to delete (a
                    # second delete must not remove the snapdir anchor)
                    return errno.ENOENT
                effects.append(OSDOp(OP_DELETE))
                exists, size = False, 0
            elif op.op == OP_ROLLBACK:
                # CEPH_OSD_OP_ROLLBACK (PrimaryLogPG::_rollback_to):
                # restore head content from the clone serving op.off
                target = ss.resolve(op.off) if ss is not None else NOSNAP
                if target is None:
                    return errno.ENOENT
                if target == NOSNAP:
                    if not exists:
                        return errno.ENOENT
                    continue  # head already serves that snap: no-op
                clone = ghobject_t(o.name, snap=target)
                if not self.store.exists(c, clone):
                    return errno.ENOENT
                data = bytes(self.store.read(c, clone))
                effects.append(OSDOp(OP_WRITE_FULL, data=data))
                effects.append(OSDOp(OP_OMAP_CLEAR))
                kv = self.store.omap_get(c, clone)
                if kv:
                    effects.append(OSDOp(OP_OMAP_SETKEYS, kv=kv))
                for name, v in self.store.getattrs(c, clone).items():
                    if name.startswith(USER_XATTR_PREFIX):
                        effects.append(OSDOp(
                            OP_SETXATTR,
                            name=name[len(USER_XATTR_PREFIX):], data=v))
                size, exists = len(data), True
            else:
                return errno.EOPNOTSUPP
        # an object deleted mid-vector and rewritten afterwards is not a
        # delete; only the final state counts for the log entry
        return effects, size, not exists, outs

    def _rep_effect_txn(
        self, pool, pg, oid, effects, attrs, version: eversion_t,
        delete_final: bool, reqid: str = "",
    ) -> Transaction:
        """Build the store transaction for an effect vector + its
        pg-log entry (primary and replicas run the identical code)."""
        c = self._shard_coll(pool, pg, NO_SHARD)
        o = ghobject_t(oid)
        t = Transaction()
        self._ensure_coll(t, c)
        # track existence through the vector: an earlier op in this SAME
        # transaction may create the object, so a build-time store.exists
        # check alone would drop a later remove
        obj_exists = self.store.exists(c, o)
        for op in effects:
            if op.op in (OP_CREATE,):
                t.touch(c, o)
            elif op.op == OP_WRITE_FULL:
                t.touch(c, o).truncate(c, o, len(op.data)).write(c, o, 0, op.data)
            elif op.op == OP_WRITE:
                t.touch(c, o).write(c, o, op.off, op.data)
            elif op.op == OP_ZERO:
                t.zero(c, o, op.off, op.length)
            elif op.op == OP_TRUNCATE:
                t.touch(c, o).truncate(c, o, op.off)
            elif op.op == OP_SETXATTR:
                t.setattrs(c, o, {USER_XATTR_PREFIX + op.name: op.data})
            elif op.op == OP_RMXATTR:
                t.touch(c, o).rmattr(c, o, USER_XATTR_PREFIX + op.name)
            elif op.op == OP_OMAP_SETKEYS:
                t.omap_setkeys(c, o, op.kv)
            elif op.op == OP_OMAP_RMKEYS:
                t.omap_rmkeys(c, o, op.keys)
            elif op.op == OP_OMAP_CLEAR:
                t.omap_clear(c, o)
            elif op.op == OP_SNAP_CLONE:
                # make_writeable COW: snapshot the head into its clone
                # before the rest of the vector mutates it
                clone = ghobject_t(oid, snap=op.off)
                if obj_exists and not self.store.exists(c, clone):
                    t.clone(c, o, clone)
                    t.setattrs(c, clone, {SNAPS_ATTR: op.data})
                continue
            elif op.op == OP_DELETE:
                if obj_exists:
                    t.remove(c, o)
                obj_exists = False
                continue
            obj_exists = True
        if not delete_final:
            t.setattrs(c, o, attrs)
        if version > ZERO:
            lg = self._pg_log(c)
            if version > lg.info.last_update:
                prior = self._object_version(c, o)
                lg.append(t, pg_log_entry_t(
                    DELETE if delete_final else MODIFY, oid, version, prior,
                    reqid,
                ))
                lg.trim(t, self._log_keep)
        return t

    async def _rep_write_vector(self, pool, pg, acting, msg,
                                admit_epoch: int | None = None) -> MOSDOpReply:
        c = self._shard_coll(pool, pg, NO_SHARD)
        o = ghobject_t(msg.oid)
        lg = self._pg_log(c)
        if msg.reqid and msg.reqid in lg.reqids:
            # duplicate of an applied op: answer without re-applying
            return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)
        # make_writeable: clone-on-write under a newer SnapContext
        from ceph_tpu.msg.messages import OSDOp

        snapc = self._effective_snapc(pool, msg)
        if snapc.snaps and not snapc.valid():
            return MOSDOpReply(tid=msg.tid, result=-errno.EINVAL, epoch=self.epoch)
        ss = self._load_snapset(c, msg.oid)
        live_head = self.store.exists(c, o) and not self._is_whiteout(c, o)
        cow: list[OSDOp] = []
        if live_head and ss.needs_cow(snapc):
            clone = ss.make_clone(snapc, self.store.stat(c, o))
            cow.append(OSDOp(
                OP_SNAP_CLONE, off=clone.id, data=encode_snaps(clone.snaps)))
        else:
            ss.advance_seq(snapc)
        resolved = self._rep_effects(c, o, msg.ops, ss=ss)
        if isinstance(resolved, int):
            return MOSDOpReply(tid=msg.tid, result=-resolved, epoch=self.epoch)
        effects, size, delete, call_outs = resolved
        effects = cow + effects
        version = self._next_version(c, admit_epoch)
        if version is None:
            return MOSDOpReply(
                tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        attrs = {
            SIZE_ATTR: str(size).encode(),
            VERSION_ATTR: _v_bytes(version),
        }
        if ss.seq or ss.clones:
            attrs[SS_ATTR] = ss.to_bytes()
        attrs[WHITEOUT_ATTR] = b"0"
        if delete and ss.clones:
            # clones still anchor to this name: leave a whiteout head
            # (the reference's snapdir object role) instead of removing
            delete = False
            size = 0
            effects.append(OSDOp(OP_CREATE))
            attrs[SIZE_ATTR] = b"0"
            attrs[WHITEOUT_ATTR] = b"1"
        t = self._rep_effect_txn(
            pool, pg, msg.oid, effects, attrs, version, delete,
            reqid=msg.reqid,
        )
        if getattr(self.store, "blocking_commit", False):
            await asyncio.to_thread(self.store.queue_transaction, t)
        else:
            self.store.queue_transaction(t)
        waits = []
        for osd in acting:
            if osd in (self.id, CRUSH_ITEM_NONE):
                continue
            tid = next(self._tids)
            waits.append(self._sub_op(osd, MOSDRepOp(
                tid=tid, pg=pg, from_osd=self.id, oid=msg.oid,
                attrs=attrs, delete=delete, epoch=self.epoch,
                version=version, ops=effects, reqid=msg.reqid,
            ), tid))
        if waits:
            replies = await asyncio.gather(*waits)
            for rep in replies:
                if rep.result != 0:
                    return MOSDOpReply(tid=msg.tid, result=rep.result, epoch=self.epoch)
        first_out = next((d for _r, d, _kv in call_outs if d), b"")
        return MOSDOpReply(
            tid=msg.tid, result=0, epoch=self.epoch, outs=call_outs,
            data=first_out,
        )

    async def _apply_full_object(
        self, pool, pg, oid, data, attrs, delete=False,
        version: eversion_t = ZERO,
    ):
        await self._apply_shard_write_async(
            pool, pg, NO_SHARD, oid, data, attrs, delete=delete,
            version=version,
        )

    async def _handle_rep_op(self, msg: MOSDRepOp) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        result = 0
        try:
            if msg.ops:
                t = self._rep_effect_txn(
                    pool, msg.pg, msg.oid, msg.ops, msg.attrs, msg.version,
                    msg.delete, reqid=msg.reqid,
                )
                if getattr(self.store, "blocking_commit", False):
                    await asyncio.to_thread(self.store.queue_transaction, t)
                else:
                    self.store.queue_transaction(t)
            else:
                # legacy full-object payload (recovery pushes reuse this)
                await self._apply_full_object(
                    pool, msg.pg, msg.oid, msg.data, msg.attrs, msg.delete,
                    msg.version,
                )
        except OSError as e:
            result = -(e.errno or errno.EIO)
        await msg.conn.send_message(MOSDRepOpReply(
            tid=msg.tid, pg=msg.pg, from_osd=self.id, result=result,
            epoch=self.epoch,
        ))

    # -- recovery ------------------------------------------------------

    async def _recover_all(self) -> None:
        """After a map change: for every PG this OSD leads, reconstruct
        missing shards/objects on the current acting set (the
        do_recovery -> recover_object path, §3.3).  Re-runs until a
        full pass has seen the newest map (epochs can land mid-pass).

        PGs run concurrently, but admission is reservation-gated
        (backfill_reservation.rst): each PG takes one of OUR
        osd_max_backfills local slots, then one remote slot on every
        acting peer (MBackfillReserve REQUEST/GRANT); a REJECT_TOOFULL
        releases everything and retries after
        osd_backfill_retry_interval, so cluster-wide concurrent
        backfill load per OSD stays bounded.

        A pass that leaves PGs unclean (a peer mid-restart, a dropped
        connection) re-runs after osd_backfill_retry_interval even if
        no new map arrives — the reference's recovery_request_timer
        retry role.  Without it a transient error at the wrong moment
        parks the PG in peering forever (found by the interleaving
        fuzzer, tests/test_interleave_fuzz.py)."""
        while not self.stopping:
            done_epoch = self.epoch
            # GC remote grants whose requesting primary is gone — a
            # primary that died after GRANT can never send RELEASE
            for key in list(self._remote_grants):
                if not self.osdmap.is_up(key[2]):
                    res = self._remote_grants.pop(key)
                    res.release()
            try:
                om = self.osdmap
                work: list[tuple[PgPool, pg_t, list[int]]] = []
                for pid, pool in list(om.pools.items()):
                    for ps in range(pool.pg_num):
                        pg = pg_t(pid, ps)
                        _, _, acting, primary = om.pg_to_up_acting_osds(
                            pg, folded=True
                        )
                        if primary != self.id:
                            continue
                        work.append((pool, pg, acting))
                if work:
                    # return_exceptions: one PG's crash must neither
                    # abort the pass (siblings would keep running
                    # DETACHED with reservations held) nor mask the
                    # others' completion
                    results = await asyncio.gather(*[
                        self._recover_pg_reserved(pool, pg, acting,
                                                  done_epoch)
                        for pool, pg, acting in work
                    ], return_exceptions=True)
                    for (_p, pg, _a), r in zip(work, results):
                        if isinstance(r, asyncio.CancelledError):
                            raise r
                        if isinstance(r, BaseException):
                            log.exception(
                                "osd.%d: recovery of %s crashed",
                                self.id, pg, exc_info=r)
                if self.epoch != done_epoch:
                    continue  # a map landed mid-pass: re-run now
                incomplete = [
                    pg for _pool, pg, _a in work
                    if self._clean_epoch.get((pg.pool, pg.ps), -1)
                    < done_epoch
                ]
                if not incomplete:
                    return
                log.info(
                    "osd.%d: %d pgs unclean after pass; retrying",
                    self.id, len(incomplete))
                await asyncio.sleep(
                    max(self.conf["osd_backfill_retry_interval"], 0.05))
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("osd.%d: recovery pass failed", self.id)
                return

    async def _recover_pg_reserved(
        self, pool: PgPool, pg: pg_t, acting: list[int], pass_epoch: int,
    ) -> None:
        key = (pg.pool, pg.ps)
        peers = sorted({
            o for o in acting
            if o != CRUSH_ITEM_NONE and o != self.id
        })
        retry = self.conf["osd_backfill_retry_interval"]
        async with self.local_reserver.request(key, priority=1):
            self.recovery_stats["peak_local"] = max(
                self.recovery_stats["peak_local"],
                self.local_reserver.in_use)
            granted: list[int] = []
            try:
                while not self.stopping and self.epoch == pass_epoch:
                    if await self._reserve_remotes(pg, peers, granted):
                        break
                    # partial holds across the retry sleep invite
                    # cluster-wide deadlock (two primaries each camped
                    # on one of the other's replicas): drop everything
                    self.recovery_stats["reservation_rejects"] += 1
                    await self._release_remotes(pg, granted)
                    granted.clear()
                    await asyncio.sleep(retry)
                else:
                    return
                self._recovering_pgs.add(key)
                try:
                    ok = await self._recover_pg(pool, pg, acting)
                    if ok:
                        self._clean_epoch[key] = pass_epoch
                        self.recovery_stats["pgs_recovered"] += 1
                finally:
                    self._recovering_pgs.discard(key)
            finally:
                await self._release_remotes(pg, granted)

    async def _reserve_remotes(
        self, pg: pg_t, peers: list[int], granted: list[int],
    ) -> bool:
        """GRANT from every acting peer, or False on REJECT_TOOFULL.

        A peer the MAP says is down is skipped — it can take no
        recovery load and no pushes will reach it.  A peer that is up
        but unreachable counts as a REJECT: it may come back mid-
        recovery and start absorbing pushes, so proceeding without its
        slot would unbound its inbound backfill load; the retry loop
        re-asks (either it answers, or it gets marked down — a new
        epoch — and the pass restarts without it).  Either way a
        best-effort RELEASE covers the race where the peer GRANTed but
        the reply missed our timeout — without it the replica's slot
        leaks until we restart."""
        for o in peers:
            tid = next(self._tids)
            try:
                rep = await self._sub_op(o, MBackfillReserve(
                    tid=tid, op=MBackfillReserve.REQUEST, pool=pg.pool,
                    ps=pg.ps, from_osd=self.id, priority=1,
                ), tid)
            except (OSError, asyncio.TimeoutError, ConnectionError):
                if not self.osdmap.is_up(o):
                    continue
                await self._release_remotes(pg, [o])
                return False
            if rep.op == MBackfillReserve.GRANT:
                granted.append(o)
            else:
                return False
        return True

    async def _release_remotes(self, pg: pg_t, granted: list[int]) -> None:
        for o in granted:
            try:
                conn = await self._osd_conn(o)
                await conn.send_message(MBackfillReserve(
                    tid=next(self._tids), op=MBackfillReserve.RELEASE,
                    pool=pg.pool, ps=pg.ps, from_osd=self.id,
                ))
            except (OSError, asyncio.TimeoutError, ConnectionError):
                continue

    async def _handle_backfill_reserve(self, msg: MBackfillReserve) -> None:
        if msg.op == MBackfillReserve.REQUEST:
            key = (msg.pool, msg.ps, msg.from_osd)
            res = self.remote_reserver.try_request(key, msg.priority)
            if res is not None:
                self._remote_grants[key] = res
                self.recovery_stats["peak_remote"] = max(
                    self.recovery_stats["peak_remote"],
                    self.remote_reserver.in_use)
                op = MBackfillReserve.GRANT
            else:
                op = MBackfillReserve.REJECT_TOOFULL
            await msg.conn.send_message(MBackfillReserve(
                tid=msg.tid, op=op, pool=msg.pool, ps=msg.ps,
                from_osd=self.id,
            ))
        elif msg.op == MBackfillReserve.RELEASE:
            res = self._remote_grants.pop(
                (msg.pool, msg.ps, msg.from_osd), None)
            if res is not None:
                res.release()
        else:  # GRANT / REJECT_TOOFULL reply to our REQUEST
            fut = self._waiters.get(msg.tid)
            if fut and not fut.done():
                fut.set_result(msg)

    def _local_objects(self, pool, pg, shard) -> list[str]:
        c = self._shard_coll(pool, pg, shard)
        if not self.store.collection_exists(c):
            return []
        return sorted(
            {o.name for o in self.store.collection_list(c)} - {PGMETA_OID}
        )

    def _pg_members(
        self, pool: PgPool, acting: list[int]
    ) -> list[tuple[int, int]]:
        """(shard, osd) pairs of the acting set; replicated members all
        use NO_SHARD collections."""
        if pool.is_erasure():
            return [
                (s, o) for s, o in enumerate(acting) if o != CRUSH_ITEM_NONE
            ]
        return [(NO_SHARD, o) for o in acting if o != CRUSH_ITEM_NONE]

    async def _recover_pg(self, pool: PgPool, pg: pg_t, acting: list[int]) -> bool:
        """Peering-lite + recovery for one PG this OSD leads.

        1. collect pg_info from every acting member (MOSDPGQuery);
        2. adopt log entries from any member ahead of us (we may have
           been the one that was down);
        3. scope the object set: exact per-peer missing sets when the
           log covers everyone (PGLog::proc_replica_log), full
           backfill over the union of object lists otherwise;
        4. reconcile each object to its newest version (reconstruct +
           MOSDPGPush / replayed delete);
        5. bring lagging members' logs current (MOSDPGLog).
        """
        pairs = self._pg_members(pool, acting)
        if self.id not in [o for _, o in pairs]:
            return True
        # prior-set (PastIntervals role): still-up members of previous
        # acting sets serve as extra data SOURCES — a fully-remapped PG
        # pulls from its old home
        prior = self._prior_pairs(pool, pg, pairs)
        my_shard = next(s for s, o in pairs if o == self.id)
        myc = self._shard_coll(pool, pg, my_shard)
        lg = self._pg_log(myc)

        peer_infos: dict[tuple[int, int], MOSDPGInfo] = {}
        for s, o in pairs:
            if o == self.id:
                continue
            try:
                peer_infos[(s, o)] = await self._pg_query(
                    pool, pg, s, o, since=lg.info.last_update
                )
            except (OSError, asyncio.TimeoutError, ConnectionError):
                continue  # unreachable; next map change retries

        # merge peers' witnessed interval chains into ours
        # (PastIntervals sharing via pg info): a member that joined in
        # a later interval learns the older homes it never saw
        import json as _json

        def _merge_chain(raw: bytes) -> bool:
            if not raw:
                return False
            try:
                chain = _json.loads(raw)
            except ValueError:
                return False
            hist = self._past_acting.setdefault((pg.pool, pg.ps), [])
            changed = False
            for a in chain:
                if a != acting and a not in hist:
                    hist.append(a)
                    del hist[:-16]
                    changed = True
            return changed

        merged = False
        for info in peer_infos.values():
            merged |= _merge_chain(getattr(info, "past_acting", b""))
        if merged:
            self._save_past_acting()
            prior = self._prior_pairs(pool, pg, pairs)

        pre_adopt_lu = lg.info.last_update
        ahead = [
            i for i in peer_infos.values()
            if i.last_update > lg.info.last_update
        ]
        gapped = False
        if ahead:
            best = max(ahead, key=lambda i: i.last_update)
            # a peer whose log_tail moved past our state means its
            # entries_after(our lu) delta has a hole: everything in the
            # trimmed range must come from backfill, and our own log
            # must admit the gap (set_tail) so covers() stays truthful
            gapped = best.log_tail > pre_adopt_lu
            t = Transaction()
            self._ensure_coll(t, myc)
            if gapped:
                lg.set_tail(t, best.log_tail)
            for raw in best.entries:
                e = pg_log_entry_t.decode(raw)
                if e.version > lg.info.last_update:
                    lg.append(t, e)
            lg.trim(t, self._log_keep)
            if not t.empty():
                self.store.queue_transaction(t)

        # scope; prior intervals force the backfill enumeration — the
        # data may live entirely on members our log knows nothing about
        scope: set[str] | None = None if (gapped or prior) else set()
        if scope is not None:
            for info in peer_infos.values():
                miss = lg.missing_from(info.last_update)
                if miss is None:
                    scope = None
                    break
                scope |= set(miss.items)
        if ahead and scope is not None:
            # entries adopted above may name objects my own shard lacks
            for raw in max(ahead, key=lambda i: i.last_update).entries:
                e = pg_log_entry_t.decode(raw)
                scope.add(e.oid)
        strays: set[str] = set()
        if scope is None:
            # backfill: reconcile the union of object lists, but the
            # member with the newest pre-recovery state is authoritative
            # for WHICH objects exist — an object only held by stale
            # members is a stray (deleted while they were down), never
            # resurrected (reference backfill removes strays the same
            # way)
            objs = set(self._local_objects(pool, pg, my_shard))
            lists: dict[tuple[int, int], set[str]] = {
                (my_shard, self.id): set(objs)
            }
            lus = {(my_shard, self.id): pre_adopt_lu}
            worklist = [
                ((s, o), None) for s, o in prior
            ] + [(k, i) for k, i in peer_infos.items()]
            chain_grew = False
            queried: set[tuple[int, int]] = {(my_shard, self.id)}
            qi = 0
            while qi < len(worklist):
                (s, o), info = worklist[qi]
                qi += 1
                if (s, o) in queried:
                    continue
                queried.add((s, o))
                if o == self.id:
                    # a past interval where WE held a different shard:
                    # serve the listing locally (querying self raises)
                    try:
                        lists[(s, o)] = set(
                            self._local_objects(pool, pg, s))
                    except FileNotFoundError:
                        continue
                    lus[(s, o)] = self._pg_log(
                        self._shard_coll(pool, pg, s)).info.last_update
                    objs |= lists[(s, o)]
                    continue
                try:
                    full = await self._pg_query(
                        pool, pg, s, o, since=lg.info.last_update,
                        want_objects=True,
                    )
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    continue
                lists[(s, o)] = {oid for oid, _v in full.objects}
                lus[(s, o)] = (
                    info.last_update if info is not None
                    else full.last_update
                )
                objs |= lists[(s, o)]
                if _merge_chain(getattr(full, "past_acting", b"")):
                    # chain-follow: the old home knew an even older one
                    chain_grew = True
                    prior = self._prior_pairs(pool, pg, pairs)
                    for pair in prior:
                        if pair not in queried:
                            worklist.append((pair, None))
                if info is None and full.last_update > lg.info.last_update:
                    # adopt the prior member's log delta so ops from
                    # the foreign interval (e.g. DELETEs) replay here
                    # instead of the old state resurrecting
                    t2 = Transaction()
                    self._ensure_coll(t2, myc)
                    if full.log_tail > lg.info.last_update:
                        lg.set_tail(t2, full.log_tail)
                    for raw in full.entries:
                        e = pg_log_entry_t.decode(raw)
                        if e.version > lg.info.last_update:
                            lg.append(t2, e)
                            objs.add(e.oid)
                    lg.trim(t2, self._log_keep)
                    if not t2.empty():
                        self.store.queue_transaction(t2)
            if chain_grew:
                self._save_past_acting()  # one write after the drain
            auth = max(lus, key=lambda k: lus[k])
            strays = objs - lists[auth]
        else:
            objs = scope
        all_ok = True
        rsleep = self.conf["osd_recovery_sleep"]

        async def _one(oid: str) -> bool:
            # osd_recovery_max_active: in-flight reconciliations per
            # daemon, across every concurrently-reserved PG; each one
            # then admits through the mClock gate at recovery weight,
            # so saturated client I/O overtakes it (admission strictly
            # BEFORE the object lock — a lock holder must never wait
            # on admission, or slots+locks could cycle)
            async with self._recovery_budget:
                async with self.op_gate.admit("recovery"):
                    ok = await self._reconcile_object(
                        pool, pg, pairs, oid, stray=oid in strays,
                        prior_pairs=prior,
                    )
                if rsleep:
                    await asyncio.sleep(rsleep)
                return bool(ok)

        results = await asyncio.gather(
            *[_one(oid) for oid in sorted(objs)], return_exceptions=True,
        )
        for oid, r in zip(sorted(objs), results):
            if isinstance(r, (OSError, asyncio.TimeoutError, ConnectionError)):
                log.warning(
                    "osd.%d: reconcile %s/%s interrupted: %r",
                    self.id, pg, oid, r,
                )
                return False
            if isinstance(r, BaseException):
                raise r
            all_ok &= r
        # log sync
        for (s, o), info in peer_infos.items():
            if info.last_update >= lg.info.last_update:
                continue
            entries = [
                e.encode() for e in lg.entries_after(info.last_update)
            ]
            try:
                await self._pg_log_send(pool, pg, s, o, entries, lg.info.log_tail)
            except (OSError, asyncio.TimeoutError, ConnectionError):
                continue
        # only a FULLY verified pass (every object confirmed on every
        # target) may forget the prior intervals — a swallowed push
        # failure must keep the old home reachable for the retry
        if all_ok:
            if self._past_acting.pop((pg.pool, pg.ps), None) is not None:
                self._save_past_acting()
        else:
            log.warning(
                "osd.%d: %s recovery pass incomplete; retaining past "
                "intervals", self.id, pg)
        return all_ok

    async def _reconcile_object(
        self, pool: PgPool, pg: pg_t, pairs: list[tuple[int, int]], oid: str,
        stray: bool = False, have_lock: bool = False,
        prior_pairs: list[tuple[int, int]] | None = None,
    ) -> bool:
        """Bring one object to its newest version on every acting
        member: replay deletes, remove strays, reconstruct
        stale/missing shards from the members holding the newest
        version.

        Serializes against client writes via the object lock — probing
        mid-write would see a partial fan-out and wrongly roll it back
        (``have_lock`` for callers inside the write path that already
        hold it)."""
        with self.tracer.span(
            "recover_object", pg=str(pg), oid=oid,
        ):
            if not have_lock:
                async with self._obj_lock(pool.id, oid):
                    return await self._reconcile_object_locked(
                        pool, pg, pairs, oid, stray, prior_pairs)
            return await self._reconcile_object_locked(
                pool, pg, pairs, oid, stray, prior_pairs)

    async def _reconcile_object_locked(
        self, pool: PgPool, pg: pg_t, pairs: list[tuple[int, int]], oid: str,
        stray: bool = False,
        prior_pairs: list[tuple[int, int]] | None = None,
    ) -> bool:
        """Returns True when the object verifiably reached every
        target (False = retry on a later pass)."""
        from ceph_tpu.common.fault_injector import FAULTS

        await FAULTS.check("osd.recover_object")
        is_ec = pool.is_erasure()
        my_shard = next(s for s, o in pairs if o == self.id)
        lg = self._pg_log(self._shard_coll(pool, pg, my_shard))
        latest: pg_log_entry_t | None = None
        for v in sorted(lg.entries, reverse=True):
            if lg.entries[v].oid == oid:
                latest = lg.entries[v]
                break

        state: dict[tuple[int, int], tuple[bool, eversion_t, dict]] = {}
        for s, o in pairs:
            try:
                payload, attrs = await self._probe_shard(pool, pg, s, o, oid)
            except (OSError, asyncio.TimeoutError, ConnectionError):
                continue  # unreachable: not a source nor target now
            if payload is None:
                state[(s, o)] = (False, ZERO, {})
            else:
                state[(s, o)] = (
                    True, _v_parse((attrs or {}).get(VERSION_ATTR)), attrs or {}
                )
        # prior-interval members: extra SOURCES (never targets) — data
        # a full remap left on the old acting set
        prior_state: dict[tuple[int, int], tuple[bool, eversion_t, dict]] = {}
        for s, o in prior_pairs or ():
            try:
                payload, attrs = await self._probe_shard(pool, pg, s, o, oid)
            except (OSError, asyncio.TimeoutError, ConnectionError):
                continue
            if payload is not None:
                prior_state[(s, o)] = (
                    True, _v_parse((attrs or {}).get(VERSION_ATTR)), attrs or {}
                )

        delete_entry = latest is not None and latest.op == DELETE
        if delete_entry or (stray and latest is None):
            # logged delete replay, or a backfill stray (only stale
            # members hold it; its DELETE entry was trimmed)
            guard = latest.version if latest else lg.info.last_update
            for (s, o), (present, _v, _a) in state.items():
                if present:
                    await self._recovery_delete(pool, pg, s, o, oid, guard)
            return True

        all_state = {**prior_state, **state}
        versions = [v for (p, v, _a) in all_state.values() if p]
        if not versions:
            return True  # nothing anywhere to recover from
        vmax = max(versions)
        sources = {
            s: o for (s, o), (p, v, _a) in all_state.items()
            if p and v == vmax
        }
        targets = [
            (s, o) for (s, o), (p, v, _a) in state.items()
            if not p or v < vmax
        ]
        if not targets:
            return True
        log.info(
            "osd.%d: recovering %s/%s to %s on %s", self.id, pg, oid,
            vmax, targets,
        )
        self.perf.inc("recovery_ops")
        src_attrs = next(
            a for (s, o), (p, v, a) in all_state.items() if p and v == vmax
        )
        if not is_ec:
            s0, o0 = next(iter(sources.items()))
            payload, _a, _e = await self._read_shard_quiet(
                pool, pg, s0, o0, oid
            )
            if payload is None:
                return False
            results = await asyncio.gather(*(
                self._push(pool, pg, s, o, oid, payload, src_attrs)
                for s, o in targets
            ), return_exceptions=True)  # a dead target must not abort
            return not any(              # the rest of the recovery pass
                isinstance(r, BaseException) for r in results)
        ec = self._ec_for(pool)
        sinfo = self._sinfo(ec)
        k = ec.get_data_chunk_count()
        force_push = False
        if len(sources) < k:
            # vmax is not reconstructible (a client write died mid
            # fan-out): ROLL BACK to the newest version at least k
            # shards agree on, overwriting the partial newer shards —
            # the reference's divergent-entry rollback (PGLog merge_log)
            # expressed at shard granularity.  The rolled-back write's
            # log entries are stripped so a client retry re-applies it.
            # rollback candidates come from the CURRENT interval only:
            # prior-interval members hold old versions by definition,
            # and letting them vote would roll back writes whose newer
            # copies merely sit on temporarily-down current members
            by_v: dict = {}
            for (s, o), (p, v, _a) in state.items():
                if p:
                    by_v.setdefault(v, []).append((s, o))
            candidates = [v for v, lst in by_v.items() if len(lst) >= k]
            if not candidates:
                log.error(
                    "osd.%d: %s/%s unrecoverable: %d/%d consistent shards",
                    self.id, pg, oid, len(sources), k,
                )
                return False
            v_star = max(candidates)
            log.warning(
                "osd.%d: %s/%s rolling back %s -> %s (partial write)",
                self.id, pg, oid, vmax, v_star,
            )
            vmax = v_star
            sources = dict(by_v[v_star])
            targets = [
                (s, o) for (s, o), (p, v, _a) in state.items()
                if not p or v != v_star
            ]
            src_attrs = next(
                a for (s, o), (p, v, a) in state.items()
                if p and v == v_star
            )
            force_push = True
            t = Transaction()
            self._ensure_coll(t, self._shard_coll(pool, pg, my_shard))
            lg.rollback_divergent(t, oid, v_star)
            if getattr(self.store, "blocking_commit", False):
                await asyncio.to_thread(self.store.queue_transaction, t)
            else:
                self.store.queue_transaction(t)
        need = {s for s, _ in targets}
        # single-shard repair of a regenerating code: thread
        # minimum_to_decode's (sub-chunk offset, count) runs down to
        # ranged shard reads so only sub_chunk_no/q of each helper
        # crosses the wire (reference ECCommon.cc:262-299 +
        # ErasureCodeClay::repair_one_lost_chunk) — CLAY's whole point
        repair_extents: dict[int, list[tuple[int, int]]] | None = None
        if (
            len(need) == 1 and ec.get_sub_chunk_count() > 1
            and not getattr(self, "disable_subchunk_repair", False)
        ):
            try:
                if ec.is_repair(need, set(sources)):
                    minimum = ec.minimum_to_decode(need, set(sources))
                    cs = sinfo.chunk_size
                    sub = cs // ec.get_sub_chunk_count()
                    size = int(src_attrs.get(SIZE_ATTR, b"0"))
                    ns = max(
                        1, sinfo.logical_to_next_chunk_offset(size) // cs
                    )
                    repair_extents = {
                        s: [
                            (stripe * cs + o * sub, c * sub)
                            for stripe in range(ns)
                            for o, c in runs
                        ]
                        for s, runs in minimum.items()
                    }
            except Exception:
                repair_extents = None  # fall back to full-chunk reads
        # helper-shard reads and shard pushes both fan out concurrently
        # (the reference's ECSubRead/MOSDPGPush are fire-and-gather)
        chunks: dict[int, np.ndarray] = {}
        used_packed = False
        if repair_extents is not None and set(repair_extents) <= set(sources):
            src_items = [(s, sources[s]) for s in sorted(repair_extents)]
            payloads = await asyncio.gather(*(
                self._read_shard_quiet(
                    pool, pg, s, o, oid, extents=repair_extents[s]
                )
                for s, o in src_items
            ))
            for (s, o), (payload, _a, _e) in zip(src_items, payloads):
                if payload is not None:
                    chunks[s] = np.frombuffer(payload, np.uint8)
            if len(chunks) < len(repair_extents):
                chunks = {}  # a helper vanished: retry with full reads
            else:
                used_packed = True
        if not chunks:
            src_items = list(sources.items())
            payloads = await asyncio.gather(*(
                self._read_shard_quiet(pool, pg, s, o, oid)
                for s, o in src_items
            ))
            for (s, o), (payload, _a, _e) in zip(src_items, payloads):
                if payload is not None:
                    chunks[s] = np.frombuffer(payload, np.uint8)
            if len(chunks) < k:
                log.error(
                    "osd.%d: %s/%s recovery aborted: %d/%d source reads "
                    "succeeded", self.id, pg, oid, len(chunks), k,
                )
                return False
        # the timed decode stage (BASELINE.md #5; reference
        # ECBackend.cc:365-431 handle_recovery_read_complete): measured
        # IN the running daemon, not inferred from microbenches
        _t0 = time.perf_counter()
        rebuilt = await ecutil.decode_shards_async(
            sinfo, ec, chunks, need, packed_repair=used_packed,
            service=self.encode_service,
        )
        self.perf.inc("recovery_decode_seconds",
                      time.perf_counter() - _t0)
        self.perf.inc("recovery_decode_bytes",
                      sum(v.nbytes for v in rebuilt.values()))
        results = await asyncio.gather(*(
            self._push(pool, pg, s, o, oid, rebuilt[s].tobytes(), src_attrs,
                       force=force_push)
            for s, o in targets
        ), return_exceptions=True)  # dead targets retry on the next pass
        return not any(isinstance(r, BaseException) for r in results)

    async def _recovery_delete(
        self, pool, pg, shard, osd, oid, guard: eversion_t
    ) -> None:
        """Replay of a logged delete on a stale member (unlogged: the
        log itself syncs separately).  ``guard`` protects a concurrent
        re-create: members whose object is newer than the delete keep
        it."""
        if osd == self.id:
            c = self._shard_coll(pool, pg, shard)
            if self._object_version(c, ghobject_t(oid, shard=shard)) > guard:
                return
            await self._apply_shard_write_async(
                pool, pg, shard, oid, b"", {}, delete=True
            )
            return
        tid = next(self._tids)
        await self._sub_op(osd, MOSDECSubOpWrite(
            tid=tid, pg=pg, shard=shard, from_osd=self.id, oid=oid,
            off=0, data=b"", attrs={}, epoch=self.epoch, delete=True,
            guard=guard,
        ), tid)

    async def _pg_query(
        self, pool, pg, shard, osd, since, want_objects: bool = False
    ) -> MOSDPGInfo:
        if osd == self.id:
            raise ValueError("query self")
        tid = next(self._tids)
        return await self._sub_op(osd, MOSDPGQuery(
            tid=tid, pg=pg, shard=shard, from_osd=self.id, since=since,
            want_objects=want_objects, epoch=self.epoch,
        ), tid)

    async def _pg_log_send(self, pool, pg, shard, osd, entries, tail) -> None:
        tid = next(self._tids)
        await self._sub_op(osd, MOSDPGLog(
            tid=tid, pg=pg, shard=shard, from_osd=self.id,
            entries=entries, epoch=self.epoch, tail=tail,
        ), tid)

    def _spawn_peering(self, coro) -> None:
        """Run a peering handler as its own task, strongly referenced
        (the loop holds tasks weakly)."""
        task = asyncio.ensure_future(coro)
        tasks = getattr(self, "_peering_tasks", None)
        if tasks is None:
            tasks = self._peering_tasks = set()
        tasks.add(task)
        task.add_done_callback(tasks.discard)

    async def _wait_for_epoch(self, epoch: int, timeout: float = 10.0) -> None:
        """Peering messages are meaningful only at (or after) the
        sender's epoch — the reference queues them behind map catch-up
        (OSD::wait_for_new_map).  Without this, a primary splitting a
        PG can query a peer that hasn't refiled yet, read an empty
        child collection, and wrongly conclude the PG is clean."""
        if self.epoch >= epoch:
            return
        try:
            await self._request_map_fill()
        except (ConnectionError, OSError):
            pass
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while (self.epoch < epoch and loop.time() < deadline
               and not self.stopping):
            await asyncio.sleep(0.05)

    async def _handle_pg_query(self, msg: MOSDPGQuery) -> None:
        await self._wait_for_epoch(msg.epoch)
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        c = self._shard_coll(pool, msg.pg, msg.shard)
        lg = self._pg_log(c)
        entries = [e.encode() for e in lg.entries_after(msg.since)]
        objects: list[tuple[str, bytes]] = []
        if msg.want_objects and self.store.collection_exists(c):
            for name in self._local_objects(pool, msg.pg, msg.shard):
                o = ghobject_t(name, shard=msg.shard)
                try:
                    v = self.store.getattr(c, o, VERSION_ATTR)
                except (FileNotFoundError, KeyError):
                    v = b""
                objects.append((name, v))
        import json as _json

        if not self._past_acting_loaded:
            self._load_past_acting()
        chain = self._past_acting.get((msg.pg.pool, msg.pg.ps), [])
        await msg.conn.send_message(MOSDPGInfo(
            tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
            last_update=lg.info.last_update, log_tail=lg.info.log_tail,
            entries=entries, objects=objects, epoch=self.epoch,
            past_acting=_json.dumps(chain).encode() if chain else b"",
        ))

    async def _handle_pg_log(self, msg: MOSDPGLog) -> None:
        await self._wait_for_epoch(msg.epoch)
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        c = self._shard_coll(pool, msg.pg, msg.shard)
        lg = self._pg_log(c)
        t = Transaction()
        self._ensure_coll(t, c)
        lg.set_tail(t, msg.tail)
        for raw in msg.entries:
            e = pg_log_entry_t.decode(raw)
            if e.version > lg.info.last_update:
                lg.append(t, e)
        lg.trim(t, self._log_keep)
        if not t.empty():
            self.store.queue_transaction(t)
        await msg.conn.send_message(MOSDPGLogAck(
            tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
            result=0, epoch=self.epoch,
        ))

    async def _probe_shard(self, pool, pg, shard, osd, oid):
        """Presence probe: zero-length read with attrs."""
        if osd == self.id:
            c = self._shard_coll(pool, pg, shard)
            o = ghobject_t(oid, shard=shard)
            if not self.store.exists(c, o):
                return None, None
            return b"", self.store.getattrs(c, o)
        tid = next(self._tids)
        rep = await self._sub_op(osd, MOSDECSubOpRead(
            tid=tid, pg=pg, shard=shard, from_osd=self.id, oid=oid,
            off=0, length=1, want_attrs=True, epoch=self.epoch,
        ), tid)
        if rep.result != 0:
            return None, None
        return rep.data, rep.attrs

    async def _push(self, pool, pg, shard, osd, oid, payload, attrs,
                    force: bool = False) -> None:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        tid = next(self._tids)
        self._push_waiters[tid] = fut
        try:
            conn = await self._osd_conn(osd)
            await conn.send_message(MOSDPGPush(
                pg=pg, shard=shard, from_osd=self.id,
                pushes=[(oid, payload, attrs)], epoch=self.epoch,
                force=force, tid=tid,
            ))
            await asyncio.wait_for(fut, SUBOP_TIMEOUT)
        finally:
            self._push_waiters.pop(tid, None)

    # -- scrub (src/osd/scrubber/, simplified to one pass) -------------

    async def _handle_scrub(self, msg: MOSDScrub) -> None:
        import json

        try:
            report = await self.scrub_pg(
                msg.pool, msg.ps, deep=msg.deep,
                repair=getattr(msg, "repair", False))
            reply = MOSDScrubReply(
                tid=msg.tid, result=0, report=json.dumps(report).encode()
            )
        except Exception as e:
            log.exception("osd.%d: scrub failed", self.id)
            reply = MOSDScrubReply(
                tid=msg.tid, result=-errno.EIO, report=str(e).encode()
            )
        try:
            await msg.conn.send_message(reply)
        except ConnectionError:
            pass

    async def scrub_pg(
        self, pool_id: int, ps: int, deep: bool = False,
        repair: bool = False,
    ) -> dict:
        """Consistency check of one PG across its acting set, CHUNKED so
        client I/O interleaves (reference src/osd/scrubber/: chunked
        scrubs that block writes only on the objects in the current
        chunk).  Shallow compares object sets and versions; ``deep``
        additionally verifies every shard payload's crc32c against the
        stored HashInfo chain (or the parity equations for RMW'd
        objects).  ``repair`` reconstructs bad shards from the
        surviving ones afterwards — the `ceph pg repair` verb
        (scrub_backend authoritative-copy repair role)."""
        pool = self.osdmap.get_pg_pool(pool_id)
        if pool is None:
            return {"error": f"no pool {pool_id}"}
        pg = pg_t(pool_id, ps)
        _, _, acting, primary = self.osdmap.pg_to_up_acting_osds(pg, folded=True)
        if primary != self.id:
            return {"error": f"osd.{self.id} is not primary for {pool_id}.{ps}"}
        pairs = self._pg_members(pool, acting)

        # enumerate the object set (bulk; per-object state is probed
        # fresh under the object lock as each chunk is scrubbed)
        names: set[str] = set()
        for s_, o_ in pairs:
            if o_ == self.id:
                names.update(self._local_objects(pool, pg, s_))
            else:
                try:
                    info = await self._pg_query(
                        pool, pg, s_, o_, since=ZERO, want_objects=True
                    )
                    names.update(n for n, _v in info.objects)
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    pass
        all_oids = sorted(names)

        chunk_max = self.conf["osd_scrub_chunk_max"]
        chunk_sleep = self.conf["osd_scrub_sleep"]
        inconsistencies: list[dict] = []
        for base in range(0, len(all_oids), chunk_max):
            # one gate admission per chunk at best-effort weight:
            # saturated client I/O outranks the scan (admission before
            # the object locks, per the opqueue deadlock rule)
            async with self.op_gate.admit("best_effort"):
                for oid in all_oids[base : base + chunk_max]:
                    async with self._obj_lock(pool.id, oid):
                        inconsistencies.extend(
                            await self._scrub_object(
                                pool, pg, pairs, oid, deep)
                        )
            await asyncio.sleep(chunk_sleep)

        repaired: list[str] = []
        if repair and inconsistencies:
            bad_oids = sorted({i["object"] for i in inconsistencies})
            for oid in bad_oids:
                # hold the object lock across re-verify + repair so a
                # concurrent client write can neither be torn by the
                # force-pushes nor produce a false inconsistency
                async with self._obj_lock(pool.id, oid):
                    incs = await self._scrub_object(
                        pool, pg, pairs, oid, deep)
                    if not incs:
                        continue  # fixed itself (e.g. write raced scan)
                    try:
                        await self._repair_object(pool, pg, pairs, oid, incs)
                        repaired.append(oid)
                    except Exception:
                        log.exception(
                            "osd.%d: repair of %s/%s failed",
                            self.id, pg, oid)
            # re-verify: the report carries what survived repair
            remaining: list[dict] = []
            for oid in bad_oids:
                async with self._obj_lock(pool.id, oid):
                    remaining.extend(
                        await self._scrub_object(pool, pg, pairs, oid, deep)
                    )
            inconsistencies = remaining
        self._scrub_stamps[(pool_id, ps)] = (
            time.monotonic(),
            time.monotonic() if deep else
            self._scrub_stamps.get((pool_id, ps), (0.0, 0.0))[1],
        )
        return {
            "pg": f"{pool_id}.{ps}",
            "acting": [o for _, o in pairs],
            "objects": len(all_oids),
            "deep": deep,
            "repaired": repaired,
            "inconsistencies": inconsistencies,
        }

    async def _scrub_object(
        self, pool, pg, pairs, oid: str, deep: bool
    ) -> list[dict]:
        """One object's scrub checks (caller holds the object lock)."""
        from ceph_tpu.native import crc32c

        out: list[dict] = []
        versions: dict[str, bytes | None] = {}
        payloads: dict[int, bytes] = {}
        hinfos: dict[int, bytes | None] = {}
        crcs: dict[str, int] = {}
        present = 0
        for s, o in pairs:
            key = f"{s}@osd.{o}"
            if deep:
                payload, attrs, _e = await self._read_shard_quiet(
                    pool, pg, s, o, oid)
            else:
                try:
                    payload, attrs = await self._probe_shard(
                        pool, pg, s, o, oid)
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    payload, attrs = None, None
            if payload is None:
                versions[key] = None
                continue
            present += 1
            versions[key] = (attrs or {}).get(VERSION_ATTR, b"")
            if deep:
                crcs[key] = crc32c(payload)
                payloads[s] = payload
                hinfos[s] = (attrs or {}).get(HINFO_ATTR)
        if present == 0:
            return out  # deleted everywhere between listing and scrub
        have = {k: v for k, v in versions.items() if v is not None}
        if len(have) != len(pairs) or len(set(have.values())) > 1:
            out.append({
                "object": oid, "kind": "shallow",
                "versions": {
                    k: (v.decode() if v else None)
                    for k, v in versions.items()
                },
            })
            return out
        if not deep:
            return out
        # deep: payload crc vs the stored HashInfo chain; RMW'd objects
        # have no hinfo (the overwrite broke the append chain) — verify
        # the parity equations instead by re-encoding the data shards
        hinfo_raw = None
        if pool.is_erasure() and hinfos:
            chains = {h for h in hinfos.values() if h is not None}
            if len(chains) == 1 and all(
                h is not None for h in hinfos.values()
            ):
                hinfo_raw = chains.pop()
                hi = ecutil.HashInfo.from_bytes(hinfo_raw)
                for s, o in pairs:
                    key = f"{s}@osd.{o}"
                    if key not in crcs:
                        continue
                    want = hi.get_chunk_hash(s)
                    if want != crcs[key]:
                        out.append({
                            "object": oid, "kind": "deep-crc",
                            "member": key, "shard": s,
                            "stored": want, "computed": crcs[key],
                        })
            elif chains:
                out.append({
                    "object": oid, "kind": "deep-hinfo-mismatch",
                    "members": sorted(
                        f"{s}" for s, h in hinfos.items() if h is not None
                    ),
                })
        if pool.is_erasure() and hinfo_raw is None and payloads:
            ec = self._ec_for(pool)
            sinfo = self._sinfo(ec)
            k = ec.get_data_chunk_count()
            import numpy as _np

            if all(s in payloads for s in range(k)) and len(payloads[0]):
                chunks = {
                    s: _np.frombuffer(payloads[s], _np.uint8)
                    for s in range(k)
                }
                logical = ecutil.decode_concat(sinfo, ec, chunks)
                expect = ecutil.encode(sinfo, ec, logical)
                for s, payload in payloads.items():
                    if s in expect and expect[s].tobytes() != payload:
                        out.append({
                            "object": oid, "kind": "deep-parity",
                            "member": f"{s}", "shard": s,
                        })
        if not pool.is_erasure() and len(set(crcs.values())) > 1:
            out.append({
                "object": oid, "kind": "deep-replica-crc", "crcs": crcs,
            })
        return out

    async def _repair_object(self, pool, pg, pairs, oid, incs) -> None:
        """`pg repair`: rebuild the authoritative copy of a damaged
        object and push it over the bad members (reference
        scrub_backend authoritative-copy selection + repair_object)."""
        kinds = {i["kind"] for i in incs}
        if pool.is_erasure():
            bad_shards = {
                i["shard"] for i in incs if "shard" in i
            }
            if bad_shards and not kinds - {"deep-crc", "deep-parity"}:
                # corrupt shard payloads at a consistent version:
                # reconstruct from the k+ clean shards and push over
                ec = self._ec_for(pool)
                sinfo = self._sinfo(ec)
                good = {}
                src_attrs = None
                for s, o in pairs:
                    if s in bad_shards:
                        continue
                    payload, attrs, _e = await self._read_shard_quiet(
                        pool, pg, s, o, oid)
                    if payload is not None:
                        import numpy as _np

                        good[s] = _np.frombuffer(payload, _np.uint8)
                        src_attrs = src_attrs or attrs
                _t0 = time.perf_counter()
                rebuilt = await ecutil.decode_shards_async(
                    sinfo, ec, good, bad_shards,
                    service=self.encode_service,
                )
                self.perf.inc("recovery_decode_seconds",
                              time.perf_counter() - _t0)
                self.perf.inc("recovery_decode_bytes",
                              sum(v.nbytes for v in rebuilt.values()))
                osd_of = dict(pairs)
                await asyncio.gather(*(
                    self._push(pool, pg, s, osd_of[s], oid,
                               rebuilt[s].tobytes(), src_attrs or {},
                               force=True)
                    for s in bad_shards
                ))
                return
        if "deep-replica-crc" in kinds:
            # replicated payload divergence at one version: the
            # majority crc wins (primary breaks ties) and is pushed
            # over the minority — authoritative-copy selection
            crcs = next(
                i["crcs"] for i in incs if i["kind"] == "deep-replica-crc")
            from collections import Counter

            winner_crc, _n = Counter(crcs.values()).most_common(1)[0]
            winner_key = next(
                k for k, v in sorted(crcs.items()) if v == winner_crc)
            ws, wo = winner_key.split("@osd.")
            payload, attrs, _e = await self._read_shard_quiet(
                pool, pg, int(ws), int(wo), oid)
            if payload is None:
                return
            await asyncio.gather(*(
                self._push(pool, pg, s, o, oid, payload, attrs or {},
                           force=True)
                for s, o in pairs
                if crcs.get(f"{s}@osd.{o}") != winner_crc
            ))
            return
        # version-level divergence (shallow / hinfo mismatch): the
        # recovery reconciliation machinery is the repair (caller holds
        # the object lock)
        await self._reconcile_object(pool, pg, pairs, oid, have_lock=True)

    async def _scrub_scheduler(self) -> None:
        """Background scrub scheduling (reference
        src/osd/scrubber/osd_scrub_sched.cc role): periodically scrub
        the PG this OSD leads with the stalest stamp; deep scrubs on
        their own (longer) cadence."""
        interval = self.conf["osd_scrub_interval"]
        deep_interval = self.conf["osd_deep_scrub_interval"]
        if interval <= 0:
            return
        tick = max(0.05, min(interval, deep_interval or interval) / 4)
        while not self.stopping:
            await asyncio.sleep(tick)
            try:
                om = self.osdmap
                if om is None:
                    continue
                now = time.monotonic()
                due: list[tuple[float, int, int, bool]] = []
                for pid, pool in om.pools.items():
                    for ps in range(pool.pg_num):
                        _u, _up, _a, primary = om.pg_to_up_acting_osds(
                            pg_t(pid, ps), folded=True)
                        if primary != self.id:
                            continue
                        if (pid, ps) not in self._scrub_stamps:
                            # stamps are in-RAM (the reference persists
                            # them in pg info): seed at first sight so a
                            # restart doesn't deep-scrub everything at
                            # once — first scrub lands one interval out
                            self._scrub_stamps[(pid, ps)] = (now, now)
                            continue
                        last, last_deep = self._scrub_stamps[(pid, ps)]
                        if deep_interval and now - last_deep > deep_interval:
                            due.append((last_deep, pid, ps, True))
                        elif now - last > interval:
                            due.append((last, pid, ps, False))
                # drain everything due this tick, stalest first, so
                # configured intervals hold however many PGs we lead
                for _stamp, pid, ps, deep in sorted(due):
                    if self.stopping:
                        break
                    await self.scrub_pg(pid, ps, deep=deep)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("osd.%d: scheduled scrub failed", self.id)

    async def _handle_push(self, msg: MOSDPGPush) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        for oid, payload, attrs in msg.pushes:
            # never regress: a write may have landed here between the
            # primary's probe and this push (the reference serializes
            # this with per-object rw locks; we reconcile on the next
            # recovery pass instead)
            c = self._shard_coll(pool, msg.pg, msg.shard)
            o = ghobject_t(oid, shard=msg.shard)
            local_v = self._object_version(c, o)
            pushed_v = _v_parse(attrs.get(VERSION_ATTR))
            if local_v > pushed_v and not msg.force:
                continue
            if local_v > pushed_v:
                # divergent rollback: the newer local write is being
                # rolled back cluster-wide; strip its log entries so
                # dup detection stops vouching for it
                t0 = Transaction()
                self._pg_log(c).rollback_divergent(t0, oid, pushed_v)
                if t0.ops:
                    if getattr(self.store, "blocking_commit", False):
                        await asyncio.to_thread(
                            self.store.queue_transaction, t0)
                    else:
                        self.store.queue_transaction(t0)
            # a push REPLACES the object: stale local attrs the source
            # doesn't carry (e.g. a hinfo dropped by an RMW this member
            # missed) must go, or deep scrub sees a phantom crc chain
            stale_attrs = []
            if self.store.exists(c, o):
                stale_attrs = [
                    n for n in self.store.getattrs(c, o) if n not in attrs
                ]
            await self._apply_shard_write_async(
                pool, msg.pg, msg.shard, oid, payload, attrs,
                rmattrs=stale_attrs,
            )
        await msg.conn.send_message(MOSDPGPushReply(
            pg=msg.pg, shard=msg.shard, from_osd=self.id, epoch=self.epoch,
            tid=msg.tid,
        ))


ECConnErrors = (ConnectionError, asyncio.TimeoutError)
