"""OSD-layer placement and data-path components.

The pure placement pipeline (pg -> up/acting OSD sets) lives in
``osdmap``; the batched whole-cluster remap engine in ``remap``.
"""

from ceph_tpu.osd.osdmap import OSDMap  # noqa: F401
from ceph_tpu.osd.types import PgPool, pg_t  # noqa: F401
