"""mClock admission gate: the op-scheduler seam wired into the daemon.

The reference queues every PG work item — client ops, recovery,
scrub — through one pluggable scheduler (src/osd/scheduler/
OpScheduler.h; mClockScheduler.h maps item class -> dmclock
(reservation, weight, limit)).  Here the asyncio twin: ops *admit*
through the gate before executing; while free slots remain admission
is immediate (work-conserving), and once ``max_inflight`` slots are
busy, waiters park inside :class:`MClockScheduler` so that the order
they unpark follows dmclock tags — client ops (high weight) overtake
background recovery (low weight) exactly when the OSD is saturated,
which is the only time ordering matters.

Deadlock rule: only TOP-LEVEL work admits (client MOSDOp, recovery
reconciliations, scrub chunks).  Sub-op service (replica writes, EC
shard reads, pushes) never admits — a held slot can therefore never
wait on a peer's held slot, so the distributed wait graph stays
acyclic (the reference gets the same property from queueing only PG
items, not message service).
"""

from __future__ import annotations

import asyncio
import time

from ceph_tpu.osd.scheduler import ClientProfile, MClockScheduler


def parse_qos_profiles(spec: str) -> dict[str, ClientProfile]:
    """Parse the ``osd_mclock_client_profiles`` option: comma-separated
    ``name:weight`` or ``name:reservation/weight/limit`` entries
    (``gold:30,bronze:3`` / ``gold:5/30/0``).  Malformed entries are
    skipped — a bad config line must not take the OSD down."""
    out: dict[str, ClientProfile] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry or ":" not in entry:
            continue
        name, _, params = entry.partition(":")
        name = name.strip()
        try:
            if "/" in params:
                r, w, lim = (float(x) for x in params.split("/"))
            else:
                r, w, lim = 0.0, float(params), 0.0
        except ValueError:
            continue
        if name and w > 0:
            out[name] = ClientProfile(
                reservation=r, weight=w, limit=lim)
    return out


class MClockGate:
    """Bounded-concurrency admission through dmclock ordering.

    Per-class fairness accounting: every admission counts into
    ``stats`` AND (when a ``perf`` collection is attached) into typed
    ``qos_*`` perf counters — admitted ops, ops that had to park,
    park time in µs, and payload cost served per class.  `perf dump`
    and the prometheus exposition render them directly, which is how
    the load harness proves mClock actually differentiates tenants.

    Tenant classes beyond the built-ins arrive via
    :meth:`ensure_class`: an unknown class inherits the ``client``
    profile unless ``tenant_profiles`` (the parsed
    ``osd_mclock_client_profiles`` option) names its own.
    """

    def __init__(self, max_inflight: int = 0,
                 profiles: dict[str, ClientProfile] | None = None,
                 perf=None,
                 tenant_profiles: dict[str, ClientProfile] | None = None):
        self.max_inflight = int(max_inflight)
        self.sched = MClockScheduler()
        for name, prof in (profiles or {}).items():
            self.sched.set_profile(name, prof)
        self.perf = perf
        self.tenant_profiles = dict(tenant_profiles or {})
        self._inflight = 0
        self._kick_handle = None
        self.stats = {"admitted": {}, "queued": {}, "wait_us": {},
                      "served_cost": {}, "peak_inflight": 0}

    def set_tenant_profiles(
            self, profiles: dict[str, ClientProfile]) -> None:
        """Install/refresh tenant QoS classes (config observer path):
        already-seen classes retag live, new ones apply on first op."""
        self.tenant_profiles = dict(profiles)
        for name, prof in profiles.items():
            if name in self.sched._clients:
                self.sched.set_profile(name, prof)

    def ensure_class(self, klass: str) -> None:
        """First op of an unseen class: give it its configured tenant
        profile, else a copy of the client class's (an untagged-equal
        default — never the weight-1 fallback that would silently
        starve tagged tenants)."""
        if klass in self.sched._clients:
            return
        prof = self.tenant_profiles.get(klass)
        if prof is None:
            base = self.sched._clients.get("client")
            prof = base.profile if base is not None else ClientProfile()
        self.sched.set_profile(klass, prof)

    def qos_dump(self) -> dict:
        """Per-class fairness snapshot (the dump_qos admin command)."""
        return {
            "max_inflight": self.max_inflight,
            "inflight": self._inflight,
            "queued_now": len(self.sched),
            "classes": {
                klass: {
                    "profile": {
                        "reservation": st.profile.reservation,
                        "weight": st.profile.weight,
                        "limit": st.profile.limit,
                    },
                    "admitted": self.stats["admitted"].get(klass, 0),
                    "queued": self.stats["queued"].get(klass, 0),
                    "wait_us": round(
                        self.stats["wait_us"].get(klass, 0.0)),
                    "served_cost": self.stats["served_cost"].get(
                        klass, 0.0),
                }
                for klass, st in sorted(self.sched._clients.items())
            },
        }

    def set_max_inflight(self, n: int) -> None:
        self.max_inflight = int(n)
        if self.max_inflight <= 0:
            # gating switched off: flush every parked waiter, still
            # counting their slots so the outstanding releases balance
            while len(self.sched):
                # now=inf: limit tags never block the flush
                nxt = self.sched.dequeue(float("inf"))
                if nxt is None:
                    break
                _klass, fut = nxt
                if not fut.done():
                    self._inflight += 1
                    fut.set_result(None)
            return
        self._drain()

    def admit(self, klass: str, cost: float = 1.0) -> "_Admission":
        return _Admission(self, klass, cost)

    # -- internals --------------------------------------------------------

    async def _acquire(self, klass: str, cost: float) -> bool:
        """Returns True when a slot was actually taken — the release
        must mirror THAT, not the max_inflight value at release time
        (toggling the config through 0 mid-flight must not corrupt the
        counter)."""
        self.ensure_class(klass)
        self.stats["admitted"][klass] = (
            self.stats["admitted"].get(klass, 0) + 1)
        self.stats["served_cost"][klass] = (
            self.stats["served_cost"].get(klass, 0.0) + cost)
        if self.perf is not None:
            self.perf.inc(f"qos_admitted_{klass}")
            self.perf.inc(f"qos_cost_{klass}", cost)
        if self.max_inflight <= 0:  # gating disabled
            return False
        if self._inflight < self.max_inflight:
            self._inflight += 1
            self.stats["peak_inflight"] = max(
                self.stats["peak_inflight"], self._inflight)
            return True
        self.stats["queued"][klass] = self.stats["queued"].get(klass, 0) + 1
        if self.perf is not None:
            self.perf.inc(f"qos_queued_{klass}")
        t0 = time.monotonic()
        fut = asyncio.get_running_loop().create_future()
        self.sched.enqueue(klass, fut, cost=cost, now=t0)
        try:
            await fut
        except asyncio.CancelledError:
            # the slot may have been handed to us between the grant
            # and the cancel landing; give it back
            if fut.done() and not fut.cancelled():
                self._release()
            raise
        # dmclock park time: the fairness signal — under saturation a
        # low-weight tenant's ops wait here while high-weight ones
        # overtake (summed per class, exported as qos_wait_us_<class>)
        wait_us = (time.monotonic() - t0) * 1e6
        self.stats["wait_us"][klass] = (
            self.stats["wait_us"].get(klass, 0.0) + wait_us)
        if self.perf is not None:
            self.perf.inc(f"qos_wait_us_{klass}", wait_us)
        return True

    def _release(self) -> None:
        self._inflight -= 1
        self._drain()

    def _drain(self) -> None:
        while self._inflight < self.max_inflight:
            nxt = self.sched.dequeue(time.monotonic())
            if nxt is None:
                # non-empty but nothing ready = every waiter is
                # limit-capped; retry when the earliest L tag matures
                if len(self.sched) and self._kick_handle is None:
                    loop = asyncio.get_event_loop()
                    self._kick_handle = loop.call_later(
                        0.005, self._timer_kick)
                return
            _klass, fut = nxt
            if fut.done():  # admission cancelled while queued
                continue
            self._inflight += 1
            self.stats["peak_inflight"] = max(
                self.stats["peak_inflight"], self._inflight)
            fut.set_result(None)

    def _timer_kick(self) -> None:
        self._kick_handle = None
        self._drain()


class _Admission:
    def __init__(self, gate: MClockGate, klass: str, cost: float):
        self.gate, self.klass, self.cost = gate, klass, cost
        self._took_slot = False

    async def __aenter__(self):
        self._took_slot = await self.gate._acquire(self.klass, self.cost)
        return self

    async def __aexit__(self, *exc):
        if self._took_slot:
            self._took_slot = False
            self.gate._release()
