"""mClock admission gate: the op-scheduler seam wired into the daemon.

The reference queues every PG work item — client ops, recovery,
scrub — through one pluggable scheduler (src/osd/scheduler/
OpScheduler.h; mClockScheduler.h maps item class -> dmclock
(reservation, weight, limit)).  Here the asyncio twin: ops *admit*
through the gate before executing; while free slots remain admission
is immediate (work-conserving), and once ``max_inflight`` slots are
busy, waiters park inside :class:`MClockScheduler` so that the order
they unpark follows dmclock tags — client ops (high weight) overtake
background recovery (low weight) exactly when the OSD is saturated,
which is the only time ordering matters.

Deadlock rule: only TOP-LEVEL work admits (client MOSDOp, recovery
reconciliations, scrub chunks).  Sub-op service (replica writes, EC
shard reads, pushes) never admits — a held slot can therefore never
wait on a peer's held slot, so the distributed wait graph stays
acyclic (the reference gets the same property from queueing only PG
items, not message service).
"""

from __future__ import annotations

import asyncio
import time

from ceph_tpu.osd.scheduler import ClientProfile, MClockScheduler


class MClockGate:
    """Bounded-concurrency admission through dmclock ordering."""

    def __init__(self, max_inflight: int = 0,
                 profiles: dict[str, ClientProfile] | None = None):
        self.max_inflight = int(max_inflight)
        self.sched = MClockScheduler()
        for name, prof in (profiles or {}).items():
            self.sched.set_profile(name, prof)
        self._inflight = 0
        self._kick_handle = None
        self.stats = {"admitted": {}, "queued": {}, "peak_inflight": 0}

    def set_max_inflight(self, n: int) -> None:
        self.max_inflight = int(n)
        if self.max_inflight <= 0:
            # gating switched off: flush every parked waiter, still
            # counting their slots so the outstanding releases balance
            while len(self.sched):
                # now=inf: limit tags never block the flush
                nxt = self.sched.dequeue(float("inf"))
                if nxt is None:
                    break
                _klass, fut = nxt
                if not fut.done():
                    self._inflight += 1
                    fut.set_result(None)
            return
        self._drain()

    def admit(self, klass: str, cost: float = 1.0) -> "_Admission":
        return _Admission(self, klass, cost)

    # -- internals --------------------------------------------------------

    async def _acquire(self, klass: str, cost: float) -> bool:
        """Returns True when a slot was actually taken — the release
        must mirror THAT, not the max_inflight value at release time
        (toggling the config through 0 mid-flight must not corrupt the
        counter)."""
        self.stats["admitted"][klass] = (
            self.stats["admitted"].get(klass, 0) + 1)
        if self.max_inflight <= 0:  # gating disabled
            return False
        if self._inflight < self.max_inflight:
            self._inflight += 1
            self.stats["peak_inflight"] = max(
                self.stats["peak_inflight"], self._inflight)
            return True
        self.stats["queued"][klass] = self.stats["queued"].get(klass, 0) + 1
        fut = asyncio.get_running_loop().create_future()
        self.sched.enqueue(klass, fut, cost=cost, now=time.monotonic())
        try:
            await fut
        except asyncio.CancelledError:
            # the slot may have been handed to us between the grant
            # and the cancel landing; give it back
            if fut.done() and not fut.cancelled():
                self._release()
            raise
        return True

    def _release(self) -> None:
        self._inflight -= 1
        self._drain()

    def _drain(self) -> None:
        while self._inflight < self.max_inflight:
            nxt = self.sched.dequeue(time.monotonic())
            if nxt is None:
                # non-empty but nothing ready = every waiter is
                # limit-capped; retry when the earliest L tag matures
                if len(self.sched) and self._kick_handle is None:
                    loop = asyncio.get_event_loop()
                    self._kick_handle = loop.call_later(
                        0.005, self._timer_kick)
                return
            _klass, fut = nxt
            if fut.done():  # admission cancelled while queued
                continue
            self._inflight += 1
            self.stats["peak_inflight"] = max(
                self.stats["peak_inflight"], self._inflight)
            fut.set_result(None)

    def _timer_kick(self) -> None:
        self._kick_handle = None
        self._drain()


class _Admission:
    def __init__(self, gate: MClockGate, klass: str, cost: float):
        self.gate, self.klass, self.cost = gate, klass, cost
        self._took_slot = False

    async def __aenter__(self):
        self._took_slot = await self.gate._acquire(self.klass, self.cost)
        return self

    async def __aexit__(self, *exc):
        if self._took_slot:
            self._took_slot = False
            self.gate._release()
