"""RBD object-map + fast-diff (reference src/librbd/object_map/,
src/cls/rbd/cls_rbd.cc OBJECT_* states).

Two bits of state per data object, persisted in a small RADOS object
(``rbd_object_map.<image>`` for head, ``rbd_object_map.<image>.<snapid>``
frozen per snapshot):

  NONEXISTENT (0)  no data object — reads short-circuit to zeros /
                   parent without an ENOENT round trip
  EXISTS (1)       written since the last snapshot (dirty)
  PENDING (2)      delete in flight
  EXISTS_CLEAN (3) exists, unchanged since the last snapshot

Update protocol mirrors the reference's crash direction: the map is
marked EXISTS *before* the data write lands (a crash leaves a false
EXISTS — harmless), and PENDING before a delete with NONEXISTENT
recorded after (a crash re-runs the delete).

fast-diff falls out of the states: objects EXISTS/PENDING in a later
map differ from the earlier snapshot; EXISTS_CLEAN ones provably do
not — diffing two snapshots costs two small map reads instead of a
scan of every data object.
"""

from __future__ import annotations

import errno

OBJECT_NONEXISTENT = 0
OBJECT_EXISTS = 1
OBJECT_PENDING = 2
OBJECT_EXISTS_CLEAN = 3


class ObjectMap:
    """The per-image (or per-snapshot) 2-bit state vector."""

    def __init__(self, ioctx, image_name: str, n_objs: int,
                 snap_id: int | None = None):
        self._io = ioctx
        self.image_name = image_name
        self.snap_id = snap_id
        self.n_objs = n_objs
        self._bits = bytearray((n_objs + 3) // 4)
        self.loaded = False

    @property
    def oid(self) -> str:
        base = f"rbd_object_map.{self.image_name}"
        return base if self.snap_id is None else f"{base}.{self.snap_id:x}"

    # -- persistence -------------------------------------------------------

    async def load(self) -> "ObjectMap":
        try:
            raw = await self._io.read(self.oid, off=0, length=0)
        except OSError as e:
            if e.errno != errno.ENOENT:
                raise
            raw = b""
        bits = bytearray((self.n_objs + 3) // 4)
        bits[: len(raw)] = raw[: len(bits)]
        self._bits = bits
        self.loaded = True
        return self

    async def save(self) -> None:
        await self._io.write_full(self.oid, bytes(self._bits))

    async def remove(self) -> None:
        try:
            await self._io.remove(self.oid)
        except OSError as e:
            if e.errno != errno.ENOENT:
                raise

    # -- state bits --------------------------------------------------------

    def get(self, objno: int) -> int:
        if objno >= self.n_objs:
            return OBJECT_NONEXISTENT
        return (self._bits[objno >> 2] >> ((objno & 3) * 2)) & 3

    def set(self, objno: int, state: int) -> bool:
        """Returns True when the state actually changed."""
        byte, shift = objno >> 2, (objno & 3) * 2
        cur = (self._bits[byte] >> shift) & 3
        if cur == state:
            return False
        self._bits[byte] = (
            self._bits[byte] & ~(3 << shift)) | (state << shift)
        return True

    def resize(self, n_objs: int) -> None:
        bits = bytearray((n_objs + 3) // 4)
        keep = min(len(bits), len(self._bits))
        bits[:keep] = self._bits[:keep]
        if n_objs < self.n_objs:
            # clear the partial byte's dead lanes
            for objno in range(n_objs, min(self.n_objs, len(bits) * 4)):
                byte, shift = objno >> 2, (objno & 3) * 2
                if byte < len(bits):
                    bits[byte] &= ~(3 << shift)
        self._bits = bits
        self.n_objs = n_objs

    def freeze_clean(self) -> None:
        """snap_create transition: every EXISTS object becomes
        EXISTS_CLEAN — from here on EXISTS means 'dirtied since this
        snapshot' (the fast-diff invariant)."""
        for objno in range(self.n_objs):
            if self.get(objno) == OBJECT_EXISTS:
                self.set(objno, OBJECT_EXISTS_CLEAN)

    def snapshot_copy(self, snap_id: int) -> "ObjectMap":
        om = ObjectMap(self._io, self.image_name, self.n_objs, snap_id)
        om._bits = bytearray(self._bits)
        om.loaded = True
        return om

    # -- fast-diff ---------------------------------------------------------

    def diff(self, since: "ObjectMap | None") -> list[int]:
        """Object numbers that (may) differ from ``since`` (an older
        snapshot's map; None = everything that exists).  EXISTS_CLEAN
        in self with the same state in ``since`` is provably unchanged."""
        def present(state: int) -> bool:
            return state in (OBJECT_EXISTS, OBJECT_EXISTS_CLEAN)

        out = []
        for objno in range(self.n_objs):
            s = self.get(objno)
            if since is None:
                if s != OBJECT_NONEXISTENT:
                    out.append(objno)
                continue
            o = since.get(objno) if objno < since.n_objs \
                else OBJECT_NONEXISTENT
            if s in (OBJECT_EXISTS, OBJECT_PENDING):
                out.append(objno)  # dirtied since the last freeze
            elif present(s) != present(o):
                out.append(objno)  # appeared/vanished between maps
        return out
