"""RBD: a block-image layer over RADOS — the librbd slice.

Mirrors the reference's v2 image format essentials (src/librbd/,
doc/dev/rbd-layering.rst): a small header object holds image metadata
in omap (``rbd_header.<id>``: size, order, object_prefix, snapshots,
parent link), a directory object maps names to ids (``rbd_directory``),
and data lives in ``<prefix>.<objectno:016x>`` objects of 2^order bytes
each.  Like the reference's ``--data-pool`` images, metadata can sit on
a replicated pool while data objects ride an erasure-coded pool.

Capabilities:

- create / open / list / remove; ranged sparse read/write; resize; stat
- **snapshots** (librbd snap_create/snap_list/snap_set/snap_rollback/
  snap_remove, protect/unprotect): each image owns a self-managed RADOS
  SnapContext on its data pool, so image snapshots are object-level COW
  clones underneath (ceph_tpu/osd/snaps.py machinery);
- **layering** (librbd clone/flatten, rbd-layering.rst): a clone's
  header records (parent image, parent snap, overlap); reads fall
  through to the parent's snapshot for objects the child has not
  written; writes copy-up the parent object first, exactly the
  reference's object-granularity COW;
- **exclusive lock** via the in-OSD ``lock`` object class on the header
  (librbd's exclusive_lock feature over cls_lock);
- **object-map / fast-diff** (``features=["object-map"]``,
  src/librbd/object_map/): a 2-bit-per-object state vector that
  short-circuits reads of nonexistent objects and diffs two snapshots
  without touching data objects (ceph_tpu/rbd/objectmap.py);
- **journaling** (``features=["journaling"]``, src/librbd/journal/):
  write-ahead event log on the metadata pool, replayed on open after a
  crash and consumed by rbd-mirror (ceph_tpu/rbd/journal.py);
- **mirroring** (src/tools/rbd_mirror/): journal-based one-way replay
  into a second cluster, with promote/demote (ceph_tpu/rbd/mirror.py).
"""

from __future__ import annotations

import asyncio
import errno
import json

from ceph_tpu.rbd import objectmap as _OM

RBD_DIRECTORY = "rbd_directory"
DEFAULT_ORDER = 22  # 4 MiB objects, the reference default


class RBDError(OSError):
    pass


class RBD:
    """Pool-level image operations (librbd::RBD)."""

    def __init__(self, meta_ioctx, data_ioctx=None):
        self.meta = meta_ioctx
        self.data = data_ioctx or meta_ioctx

    async def create(
        self, name: str, size: int, order: int = DEFAULT_ORDER,
        features: tuple[str, ...] | list[str] = (),
    ) -> None:
        for f in features:
            if f not in ("object-map", "fast-diff", "journaling"):
                raise RBDError(errno.EINVAL, f"unknown feature {f!r}")
        existing = await self._dir()
        if name in existing:
            raise RBDError(errno.EEXIST, f"image {name!r} exists")
        header = f"rbd_header.{name}"
        await self.meta.omap_set(header, {
            "size": str(size).encode(),
            "order": str(order).encode(),
            "object_prefix": f"rbd_data.{name}".encode(),
            "features": ",".join(features).encode(),
            "primary": b"1",
        })
        await self.meta.omap_set(RBD_DIRECTORY, {name: b"1"})

    async def clone(
        self, parent_name: str, snap_name: str, clone_name: str,
    ) -> None:
        """librbd clone: a new image layered on a PROTECTED parent
        snapshot (rbd-layering.rst)."""
        parent = await self.open(parent_name)
        snap = parent.snaps.get(snap_name)
        if snap is None:
            raise RBDError(errno.ENOENT, f"no snap {snap_name!r}")
        if not snap.get("protected"):
            raise RBDError(
                errno.EINVAL, f"snap {snap_name!r} is not protected")
        existing = await self._dir()
        if clone_name in existing:
            raise RBDError(errno.EEXIST, f"image {clone_name!r} exists")
        await self.meta.omap_set(f"rbd_header.{clone_name}", {
            "size": str(snap["size"]).encode(),
            "order": str(parent.order).encode(),
            "object_prefix": f"rbd_data.{clone_name}".encode(),
            "parent": json.dumps({
                "image": parent_name, "snap": snap_name,
                "snapid": snap["id"], "overlap": snap["size"],
            }).encode(),
        })
        await self.meta.omap_set(RBD_DIRECTORY, {clone_name: b"1"})

    async def _dir(self) -> dict[str, bytes]:
        try:
            return await self.meta.omap_get(RBD_DIRECTORY)
        except OSError as e:
            if e.errno == errno.ENOENT:
                return {}
            raise

    async def list(self) -> list[str]:
        return sorted(await self._dir())

    async def remove(self, name: str) -> None:
        # replay=False: re-applying journal events into an image about
        # to be destroyed is wasted work, and an unreplayable event
        # (e.g. a crash-torn WRITE past a later shrink) would make the
        # image undeletable
        img = await self.open(name, replay=False)
        if img.snaps:
            raise RBDError(errno.ENOTEMPTY, "image has snapshots")
        await img.remove_data()
        try:
            await self.meta.remove(f"rbd_header.{name}")
        except OSError as e:
            if e.errno != errno.ENOENT:
                raise
        await self.meta.omap_rm_keys(RBD_DIRECTORY, [name])

    async def open(self, name: str, replay: bool = True) -> "Image":
        """``replay=False`` opens without journal crash-replay — the
        stance of a NON-OWNING reader (rbd-mirror): replaying another
        client's in-flight events would make this handle a second
        writer and advance the owner's commit_pos under it."""
        try:
            meta = await self.meta.omap_get(f"rbd_header.{name}")
        except OSError as e:
            raise RBDError(errno.ENOENT, f"no image {name!r}") from e
        if "size" not in meta:
            raise RBDError(errno.ENOENT, f"no image {name!r}")
        feats = meta.get("features", b"").decode()
        img = Image(
            self, name,
            size=int(meta["size"]),
            order=int(meta["order"]),
            prefix=meta["object_prefix"].decode(),
            snaps=json.loads(meta.get("snaps", b"{}")),
            parent=json.loads(meta["parent"]) if "parent" in meta else None,
            features=frozenset(f for f in feats.split(",") if f),
            primary=meta.get("primary", b"1") == b"1",
        )
        img._apply_snapc()
        await img._init_features(replay=replay)
        return img


class Image:
    """An open image handle (librbd::Image)."""

    def __init__(self, rbd: RBD, name: str, size: int, order: int,
                 prefix: str, snaps: dict | None = None,
                 parent: dict | None = None,
                 features: frozenset[str] = frozenset(),
                 primary: bool = True):
        self.rbd = rbd
        self.name = name
        self._size = size
        self.order = order
        self.obj_size = 1 << order
        self.prefix = prefix
        #: snap name -> {"id": rados snapid, "size": int, "protected": bool}
        self.snaps: dict[str, dict] = snaps or {}
        #: layering link: {"image", "snap", "snapid", "overlap"} or None
        self.parent = parent
        self.features = features
        #: mirroring role: a demoted (non-primary) image refuses writes
        self.primary = primary
        # per-image data handle: the image's own SnapContext lives here
        self._io = rbd.data.dup()
        self._read_snap_name: str | None = None
        self._parent_img: "Image | None" = None  # lazy, header cached
        self.objmap = None  # ObjectMap when the feature is on
        self.journal = None  # Journal when the feature is on
        self._replaying = False

    def _n_objs(self, size: int | None = None) -> int:
        size = self._size if size is None else size
        return (size + self.obj_size - 1) // self.obj_size

    async def _init_features(self, replay: bool = True) -> None:
        if "object-map" in self.features or "fast-diff" in self.features:
            from ceph_tpu.rbd.objectmap import ObjectMap

            self.objmap = await ObjectMap(
                self.rbd.meta, self.name, self._n_objs()).load()
        if "journaling" in self.features:
            from ceph_tpu.rbd.journal import Journal

            self.journal = Journal(self.rbd.meta, self.name)
            if replay:
                await self._journal_replay()

    async def _journal_replay(self) -> None:
        """Open-time crash recovery (librbd journal replay): re-apply
        every event past commit_pos; events are idempotent."""
        pos = await self.journal.commit_pos()
        events = await self.journal.events_after(pos)
        if not events:
            return
        for seq, head, payload in events:
            await self._apply_journal_event(head, payload)
            await self.journal.commit(seq)

    async def _apply_journal_event(self, head: dict, payload: bytes) -> None:
        """Apply one journaled event to the data path — shared by
        open-time crash replay and rbd-mirror replay (the single
        dispatch over event types; keep it the only one).

        Runs with the guards the PUBLIC ops enforce suspended: replay
        must succeed on a demoted image (mirror failover with a
        pending event would otherwise make the image unopenable), and
        a WRITE journaled before a later-applied shrink may exceed the
        current size — grow for the apply; the RESIZE event that
        follows in the log restores the final geometry."""
        from ceph_tpu.rbd import journal as J

        saved_primary, self.primary = self.primary, True
        self._replaying = True
        try:
            ev = head["event"]
            if ev == J.WRITE:
                end = head["off"] + len(payload)
                if end > self._size:
                    await self.resize(end)
                await self.write(head["off"], payload)
            elif ev == J.RESIZE:
                await self.resize(head["size"])
            elif ev == J.SNAP_CREATE:
                if head["name"] not in self.snaps:
                    await self.snap_create(head["name"])
            elif ev == J.SNAP_REMOVE:
                if head["name"] in self.snaps:
                    await self.snap_remove(head["name"])
        finally:
            self.primary = saved_primary
            self._replaying = False

    # -- basics --------------------------------------------------------

    def size(self) -> int:
        return self._size

    def _oid(self, objectno: int) -> str:
        return f"{self.prefix}.{objectno:016x}"

    def _extents(self, off: int, length: int):
        out = []
        pos, end = off, off + length
        while pos < end:
            objno, obj_off = divmod(pos, self.obj_size)
            n = min(self.obj_size - obj_off, end - pos)
            out.append((objno, obj_off, n))
            pos += n
        return out

    # -- snapshots -----------------------------------------------------

    def _apply_snapc(self) -> None:
        ids = sorted((s["id"] for s in self.snaps.values()), reverse=True)
        self._io.set_snap_context(ids[0] if ids else 0, ids)

    async def _save_header(self, **extra) -> None:
        kv = {"snaps": json.dumps(self.snaps).encode()}
        for k, v in extra.items():
            kv[k] = v
        await self.rbd.meta.omap_set(f"rbd_header.{self.name}", kv)

    async def snap_create(self, snap_name: str) -> int:
        """librbd snap_create: allocate a self-managed RADOS snap and
        fold it into the image's write context — data objects COW on
        the next write."""
        if snap_name in self.snaps:
            raise RBDError(errno.EEXIST, f"snap {snap_name!r} exists")
        if self.journal is not None and not self._replaying:
            from ceph_tpu.rbd import journal as J

            await self.journal.append(
                J.SNAP_CREATE, {"name": snap_name})
        snapid = await self._io.selfmanaged_snap_create()
        self.snaps[snap_name] = {
            "id": snapid, "size": self._size, "protected": False,
        }
        self._apply_snapc()
        await self._save_header()
        if self.objmap is not None:
            # freeze the map under the snap's name, then downgrade the
            # head's EXISTS to EXISTS_CLEAN: from now on EXISTS means
            # 'dirtied since this snapshot' (fast-diff invariant)
            await self.objmap.snapshot_copy(snapid).save()
            self.objmap.freeze_clean()
            await self.objmap.save()
        return snapid

    def snap_list(self) -> list[dict]:
        return [
            {"name": n, **info} for n, info in sorted(
                self.snaps.items(), key=lambda kv: kv[1]["id"])
        ]

    def snap_set(self, snap_name: str | None) -> None:
        """Point READS at a snapshot (None = head), librbd snap_set."""
        if snap_name is not None and snap_name not in self.snaps:
            raise RBDError(errno.ENOENT, f"no snap {snap_name!r}")
        self._read_snap_name = snap_name

    async def snap_protect(self, snap_name: str) -> None:
        self._snap(snap_name)["protected"] = True
        await self._save_header()

    async def snap_unprotect(self, snap_name: str) -> None:
        # the reference refuses while children exist; scan the directory
        for child in await self.rbd.list():
            try:
                img = await self.rbd.open(child)
            except RBDError:
                continue
            if img.parent and img.parent["image"] == self.name \
                    and img.parent["snap"] == snap_name:
                raise RBDError(errno.EBUSY, f"snap has child {child!r}")
        self._snap(snap_name)["protected"] = False
        await self._save_header()

    def _snap(self, snap_name: str) -> dict:
        try:
            return self.snaps[snap_name]
        except KeyError:
            raise RBDError(errno.ENOENT, f"no snap {snap_name!r}") from None

    async def snap_remove(self, snap_name: str) -> None:
        info = self._snap(snap_name)
        if info.get("protected"):
            raise RBDError(errno.EBUSY, f"snap {snap_name!r} is protected")
        if self.journal is not None and not self._replaying:
            from ceph_tpu.rbd import journal as J

            await self.journal.append(
                J.SNAP_REMOVE, {"name": snap_name})
        if self._read_snap_name == snap_name:
            self._read_snap_name = None  # handle falls back to head
        del self.snaps[snap_name]
        self._apply_snapc()
        await self._save_header()
        await self._io.selfmanaged_snap_remove(info["id"])
        if self.objmap is not None:
            from ceph_tpu.rbd.objectmap import ObjectMap

            await ObjectMap(
                self.rbd.meta, self.name, 0, info["id"]).remove()

    async def snap_rollback(self, snap_name: str) -> None:
        """librbd snap_rollback: restore head data to the snapshot."""
        info = self._snap(snap_name)
        snapid = info["id"]
        snap_objs = (info["size"] + self.obj_size - 1) // self.obj_size
        head_objs = (self._size + self.obj_size - 1) // self.obj_size
        reader = self._io.dup()
        reader.snap_set_read(snapid)

        async def _one(objno: int) -> None:
            oid = self._oid(objno)
            try:
                await reader.stat(oid)
                existed = True
            except OSError as e:
                if e.errno != errno.ENOENT:
                    raise
                existed = False
            if existed:
                await self._io.rollback(oid, snapid)
            else:
                try:
                    await self._io.remove(oid)
                except OSError as e:
                    if e.errno != errno.ENOENT:
                        raise

        await asyncio.gather(*(
            _one(i) for i in range(max(snap_objs, head_objs))
        ))
        self._size = info["size"]
        await self._save_header(size=str(self._size).encode())
        if self.objmap is not None:
            # head data now equals the snapshot: adopt its frozen map
            from ceph_tpu.rbd.objectmap import ObjectMap

            snap_map = await ObjectMap(
                self.rbd.meta, self.name,
                self._n_objs(info["size"]), snapid).load()
            self.objmap._bits = bytearray(snap_map._bits)
            self.objmap.n_objs = snap_map.n_objs
            await self.objmap.save()

    # -- exclusive lock (cls_lock over the header) ---------------------

    async def lock_acquire(self, owner: str, shared: bool = False) -> None:
        """librbd exclusive_lock via the in-OSD lock class."""
        try:
            await self.rbd.meta.execute(
                f"rbd_header.{self.name}", "lock", "lock",
                json.dumps({
                    "name": "rbd_lock",
                    "type": "shared" if shared else "exclusive",
                    "cookie": "", "owner": owner,
                }).encode())
        except OSError as e:
            if e.errno == errno.EBUSY:
                raise RBDError(errno.EBUSY, "image is locked") from e
            raise

    async def lock_release(self, owner: str) -> None:
        await self.rbd.meta.execute(
            f"rbd_header.{self.name}", "lock", "unlock",
            json.dumps({
                "name": "rbd_lock", "cookie": "", "owner": owner,
            }).encode())

    async def lock_break(self, owner: str) -> None:
        await self.rbd.meta.execute(
            f"rbd_header.{self.name}", "lock", "break_lock",
            json.dumps({"name": "rbd_lock", "owner": owner}).encode())

    # -- layering ------------------------------------------------------

    async def _parent_read(self, objno: int) -> bytes | None:
        """The parent snapshot's bytes for this child object (None =
        beyond overlap / parent hole)."""
        if self.parent is None:
            return None
        base = objno * self.obj_size
        if base >= self.parent["overlap"]:
            return None
        if self._parent_img is None:
            self._parent_img = await self.rbd.open(self.parent["image"])
        parent = self._parent_img
        pio = parent._io.dup()
        pio.snap_set_read(self.parent["snapid"])
        want = min(self.obj_size, self.parent["overlap"] - base)
        try:
            data = await pio.read(self._oid_of(parent, objno), off=0,
                                  length=want)
        except OSError as e:
            if e.errno == errno.ENOENT:
                return None
            raise
        return data

    @staticmethod
    def _oid_of(img: "Image", objno: int) -> str:
        return f"{img.prefix}.{objno:016x}"

    async def _copy_up(self, objno: int) -> None:
        """Object-granularity COW from the parent before the first
        child write (librbd copy-up)."""
        data = await self._parent_read(objno)
        if data:
            await self._io.write_full(self._oid(objno), data)

    async def flatten(self) -> None:
        """librbd flatten: copy every still-inherited object up, then
        sever the parent link."""
        if self.parent is None:
            return
        n_objs = (self._size + self.obj_size - 1) // self.obj_size

        async def _one(objno: int) -> None:
            try:
                await self._io.stat(self._oid(objno))
                return  # child already owns it
            except OSError as e:
                if e.errno != errno.ENOENT:
                    raise
            await self._copy_up(objno)

        await asyncio.gather(*(_one(i) for i in range(n_objs)))
        self.parent = None
        self._parent_img = None
        await self.rbd.meta.omap_rm_keys(
            f"rbd_header.{self.name}", ["parent"])

    # -- I/O -----------------------------------------------------------

    async def write(self, off: int, data: bytes) -> None:
        if self._read_snap_name is not None:
            raise RBDError(errno.EROFS, "image is set to a snapshot")
        if not self.primary:
            raise RBDError(errno.EROFS, "image is non-primary (demoted)")
        if off + len(data) > self._size:
            raise RBDError(errno.EINVAL, "write past image size")
        seq = None
        if self.journal is not None and not self._replaying:
            from ceph_tpu.rbd import journal as J

            # write-ahead: the event is durable before any data object
            # changes (journal replay re-applies it after a crash)
            seq = await self.journal.append(J.WRITE, {"off": off}, data)
        if self.objmap is not None:
            # mark EXISTS before the data lands: a crash leaves a
            # false EXISTS (harmless), never a false NONEXISTENT
            await self._objmap_mark(
                [e[0] for e in self._extents(off, len(data))],
                _OM.OBJECT_EXISTS)
        pos = 0
        writes = []
        for objno, obj_off, n in self._extents(off, len(data)):
            writes.append(self._write_one(
                objno, obj_off, data[pos : pos + n]))
            pos += n
        await asyncio.gather(*writes)
        if seq is not None:
            await self.journal.commit(seq)

    async def _objmap_mark(self, objnos, state: int) -> None:
        changed = [self.objmap.set(o, state) for o in list(objnos)]
        if any(changed):
            await self.objmap.save()

    async def _write_one(self, objno: int, obj_off: int, chunk: bytes) -> None:
        if self.parent is not None:
            # copy-up unless the child already owns the object
            try:
                await self._io.stat(self._oid(objno))
            except OSError as e:
                if e.errno == errno.ENOENT:
                    await self._copy_up(objno)
                else:
                    raise
        await self._io.write(self._oid(objno), chunk, off=obj_off)

    async def read(self, off: int, length: int) -> bytes:
        read_snap = None
        bound = self._size
        if self._read_snap_name is not None:
            info = self._snap(self._read_snap_name)
            read_snap = info["id"]
            bound = info["size"]
        end = min(off + length, bound)
        if off >= end:
            return b""

        async def _one(objno: int, obj_off: int, n: int) -> bytes:
            if (
                read_snap is None and self.objmap is not None
                and self.objmap.get(objno) == _OM.OBJECT_NONEXISTENT
            ):
                # object-map fast path: provably no data object — skip
                # the OSD round trip, fall straight to parent/zeros
                chunk = b""
                if self.parent is not None:
                    pdata = await self._parent_read(objno)
                    if pdata is not None:
                        chunk = pdata[obj_off : obj_off + n]
                return chunk.ljust(n, b"\0")
            io = self._io
            if read_snap is not None:
                io = self._io.dup()
                io.snap_set_read(read_snap)
            try:
                chunk = await io.read(
                    self._oid(objno), off=obj_off, length=n
                )
            except OSError as e:
                if e.errno == errno.ENOENT:
                    chunk = b""
                else:
                    raise
            if not chunk and self.parent is not None:
                pdata = await self._parent_read(objno)
                if pdata is not None:
                    chunk = pdata[obj_off : obj_off + n]
            return chunk.ljust(n, b"\0")

        parts = await asyncio.gather(*(
            _one(*ext) for ext in self._extents(off, end - off)
        ))
        return b"".join(parts)

    async def resize(self, new_size: int) -> None:
        if self.journal is not None and not self._replaying:
            from ceph_tpu.rbd import journal as J

            seq = await self.journal.append(J.RESIZE, {"size": new_size})
        else:
            seq = None
        await self._resize_inner(new_size)
        if self.objmap is not None:
            self.objmap.resize(self._n_objs(new_size))
            await self.objmap.save()
        if seq is not None:
            await self.journal.commit(seq)

    async def _resize_inner(self, new_size: int) -> None:
        if new_size < self._size:
            # drop whole objects past the end; trim the boundary object
            first_dead = (new_size + self.obj_size - 1) // self.obj_size
            last_old = (self._size + self.obj_size - 1) // self.obj_size
            ops = []
            for objno in range(first_dead, last_old):
                ops.append(self._remove_quiet(self._oid(objno)))
            if new_size % self.obj_size:
                ops.append(self._trim_quiet(
                    self._oid(new_size // self.obj_size),
                    new_size % self.obj_size,
                ))
            if ops:
                await asyncio.gather(*ops)
            if self.parent is not None and \
                    self.parent["overlap"] > new_size:
                # shrink clips the parent overlap permanently: space
                # re-grown later must read zeros, not parent bytes
                self.parent["overlap"] = new_size
                await self.rbd.meta.omap_set(
                    f"rbd_header.{self.name}",
                    {"parent": json.dumps(self.parent).encode()})
        self._size = new_size
        await self.rbd.meta.omap_set(f"rbd_header.{self.name}", {
            "size": str(new_size).encode(),
        })

    async def _trim_quiet(self, oid: str, keep: int) -> None:
        try:
            cur = await self._io.stat(oid)
        except OSError as e:
            if e.errno == errno.ENOENT:
                return
            raise
        if cur > keep:
            # through the image handle: its SnapContext makes the OSD
            # clone before the cut, so snapshots keep the trimmed bytes
            await self._io.truncate(oid, keep)

    async def _remove_quiet(self, oid: str) -> None:
        try:
            await self._io.remove(oid)
        except OSError as e:
            if e.errno != errno.ENOENT:
                raise

    # -- fast-diff / mirroring roles -----------------------------------

    async def fast_diff(
        self, from_snap: str | None = None,
    ) -> list[tuple[int, int]]:
        """librbd diff_iterate with whole-object=true over the object
        maps (src/librbd/api/DiffIterate.cc fast-diff path): byte
        extents that may differ from ``from_snap`` (None = allocated
        extents), WITHOUT reading any data object.

        EXISTS in a map means 'dirtied since the PREVIOUS snapshot',
        so the endpoint maps alone can't see a write that landed
        between two intermediate snapshots and was then frozen to
        EXISTS_CLEAN — the union over every snapshot map taken after
        ``from_snap``, plus head, can."""
        if self.objmap is None:
            raise RBDError(errno.EOPNOTSUPP, "fast-diff requires object-map")
        if from_snap is None:
            changed = set(self.objmap.diff(None))
        else:
            from ceph_tpu.rbd.objectmap import ObjectMap

            info = self._snap(from_snap)
            since = await ObjectMap(
                self.rbd.meta, self.name,
                self._n_objs(info["size"]), info["id"]).load()
            later = [
                s for s in self.snaps.values() if s["id"] > info["id"]
            ]
            maps = list(await asyncio.gather(*(
                ObjectMap(
                    self.rbd.meta, self.name,
                    self._n_objs(s["size"]), s["id"]).load()
                for s in sorted(later, key=lambda s: s["id"])
            ))) + [self.objmap]
            changed = set()
            for m in maps:
                changed.update(m.diff(since))
        out = []
        for objno in sorted(changed):
            base = objno * self.obj_size
            if base < self._size:
                out.append((base, min(self.obj_size, self._size - base)))
        return out

    async def demote(self) -> None:
        """rbd mirror demote: this side stops accepting writes (the
        peer may promote)."""
        self.primary = False
        await self.rbd.meta.omap_set(
            f"rbd_header.{self.name}", {"primary": b"0"})

    async def promote(self) -> None:
        self.primary = True
        await self.rbd.meta.omap_set(
            f"rbd_header.{self.name}", {"primary": b"1"})

    async def remove_data(self) -> None:
        if self.objmap is not None:
            await self.objmap.remove()
        if self.journal is not None:
            await self.journal.destroy()
        await asyncio.gather(*(
            self._remove_quiet(self._oid(i)) for i in range(self._n_objs())
        ))
