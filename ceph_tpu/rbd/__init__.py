"""RBD-lite: a block-image layer over RADOS — the librbd slice.

Mirrors the reference's v2 image format essentials (src/librbd/,
doc/dev/rbd-layering.rst): a small header object holds image metadata
in omap (``rbd_header.<id>``: size, order, object_prefix), a directory
object maps names to ids (``rbd_directory``), and data lives in
``<prefix>.<objectno:016x>`` objects of 2^order bytes each.  Like the
reference's ``--data-pool`` images, metadata can sit on a replicated
pool while data objects ride an erasure-coded pool.

Capabilities: create / open / list / remove, ranged read/write at any
offset (sparse: unwritten extents read as zeros), resize, stat.
"""

from __future__ import annotations

import asyncio
import errno

RBD_DIRECTORY = "rbd_directory"
DEFAULT_ORDER = 22  # 4 MiB objects, the reference default


class RBDError(OSError):
    pass


class RBD:
    """Pool-level image operations (librbd::RBD)."""

    def __init__(self, meta_ioctx, data_ioctx=None):
        self.meta = meta_ioctx
        self.data = data_ioctx or meta_ioctx

    async def create(
        self, name: str, size: int, order: int = DEFAULT_ORDER
    ) -> None:
        existing = await self._dir()
        if name in existing:
            raise RBDError(errno.EEXIST, f"image {name!r} exists")
        header = f"rbd_header.{name}"
        await self.meta.omap_set(header, {
            "size": str(size).encode(),
            "order": str(order).encode(),
            "object_prefix": f"rbd_data.{name}".encode(),
        })
        await self.meta.omap_set(RBD_DIRECTORY, {name: b"1"})

    async def _dir(self) -> dict[str, bytes]:
        try:
            return await self.meta.omap_get(RBD_DIRECTORY)
        except OSError as e:
            if e.errno == errno.ENOENT:
                return {}
            raise

    async def list(self) -> list[str]:
        return sorted(await self._dir())

    async def remove(self, name: str) -> None:
        img = await self.open(name)
        await img.remove_data()
        try:
            await self.meta.remove(f"rbd_header.{name}")
        except OSError as e:
            if e.errno != errno.ENOENT:
                raise
        await self.meta.omap_rm_keys(RBD_DIRECTORY, [name])

    async def open(self, name: str) -> "Image":
        try:
            meta = await self.meta.omap_get(f"rbd_header.{name}")
        except OSError as e:
            raise RBDError(errno.ENOENT, f"no image {name!r}") from e
        if "size" not in meta:
            raise RBDError(errno.ENOENT, f"no image {name!r}")
        return Image(
            self, name,
            size=int(meta["size"]),
            order=int(meta["order"]),
            prefix=meta["object_prefix"].decode(),
        )


class Image:
    """An open image handle (librbd::Image)."""

    def __init__(self, rbd: RBD, name: str, size: int, order: int, prefix: str):
        self.rbd = rbd
        self.name = name
        self._size = size
        self.order = order
        self.obj_size = 1 << order
        self.prefix = prefix

    def size(self) -> int:
        return self._size

    def _oid(self, objectno: int) -> str:
        return f"{self.prefix}.{objectno:016x}"

    def _extents(self, off: int, length: int):
        out = []
        pos, end = off, off + length
        while pos < end:
            objno, obj_off = divmod(pos, self.obj_size)
            n = min(self.obj_size - obj_off, end - pos)
            out.append((objno, obj_off, n))
            pos += n
        return out

    async def write(self, off: int, data: bytes) -> None:
        if off + len(data) > self._size:
            raise RBDError(errno.EINVAL, "write past image size")
        pos = 0
        writes = []
        for objno, obj_off, n in self._extents(off, len(data)):
            writes.append(self.rbd.data.write(
                self._oid(objno), data[pos : pos + n], off=obj_off
            ))
            pos += n
        await asyncio.gather(*writes)

    async def read(self, off: int, length: int) -> bytes:
        end = min(off + length, self._size)
        if off >= end:
            return b""

        async def _one(objno: int, obj_off: int, n: int) -> bytes:
            try:
                chunk = await self.rbd.data.read(
                    self._oid(objno), off=obj_off, length=n
                )
            except OSError as e:
                if e.errno == errno.ENOENT:
                    chunk = b""  # never written: zeros
                else:
                    raise
            return chunk.ljust(n, b"\0")

        parts = await asyncio.gather(*(
            _one(*ext) for ext in self._extents(off, end - off)
        ))
        return b"".join(parts)

    async def resize(self, new_size: int) -> None:
        if new_size < self._size:
            # drop whole objects past the end; trim the boundary object
            first_dead = (new_size + self.obj_size - 1) // self.obj_size
            last_old = (self._size + self.obj_size - 1) // self.obj_size
            ops = []
            for objno in range(first_dead, last_old):
                ops.append(self._remove_quiet(self._oid(objno)))
            if new_size % self.obj_size:
                ops.append(self._trim_quiet(
                    self._oid(new_size // self.obj_size),
                    new_size % self.obj_size,
                ))
            if ops:
                await asyncio.gather(*ops)
        self._size = new_size
        await self.rbd.meta.omap_set(f"rbd_header.{self.name}", {
            "size": str(new_size).encode(),
        })

    async def _trim_quiet(self, oid: str, keep: int) -> None:
        try:
            cur = await self.rbd.data.stat(oid)
        except OSError as e:
            if e.errno == errno.ENOENT:
                return
            raise
        if cur > keep:
            await self.rbd.data.truncate(oid, keep)

    async def _remove_quiet(self, oid: str) -> None:
        try:
            await self.rbd.data.remove(oid)
        except OSError as e:
            if e.errno != errno.ENOENT:
                raise

    async def remove_data(self) -> None:
        n_objs = (self._size + self.obj_size - 1) // self.obj_size
        await asyncio.gather(*(
            self._remove_quiet(self._oid(i)) for i in range(n_objs)
        ))
