"""RBD image journaling (reference src/journal/ Journaler +
src/librbd/journal/): a durable, ordered event log of image mutations,
written BEFORE the data path applies them.

Layout on the metadata pool:

  ``journal.<image>``            omap: ``commit_pos`` (highest seq the
                                 data path has durably applied) and
                                 per-peer mirror positions
                                 (``peer.<name>``)
  ``journal_data.<image>.<seq>`` one object per event: a JSON header
                                 line + raw payload bytes

Crash contract (the reference's journal replay on open): an event at
seq > commit_pos may or may not have reached the data objects — replay
re-applies every such event in order; all events are idempotent
(absolute-offset writes, absolute resizes), so double-apply is safe.

The same log is the rbd-mirror feed (ceph_tpu/rbd/mirror.py): a peer
replays events into a secondary cluster and records its own position
under ``peer.<name>`` so trim never drops an event a peer still needs.
"""

from __future__ import annotations

import asyncio
import errno
import json


WRITE = "write"
DISCARD = "discard"
RESIZE = "resize"
SNAP_CREATE = "snap_create"
SNAP_REMOVE = "snap_remove"


class Journal:
    def __init__(self, ioctx, image_name: str):
        self._io = ioctx
        self.image_name = image_name
        self.header_oid = f"journal.{image_name}"
        self._next_seq: int | None = None
        # seqs whose data-path application finished but whose
        # predecessors have not: commit_pos may only advance over a
        # CONTIGUOUS applied prefix, or replay-after-crash would skip
        # a durably journaled, never-applied event
        self._applied: set[int] = set()
        # tail_seq must be MONOTONIC on the wire: concurrent appends
        # completing out of order must not regress it (a regressed
        # tail hides a durably appended event from replay)
        self._tail_lock = asyncio.Lock()
        self._tail_persisted = -1
        self._commit_lock = asyncio.Lock()

    def _data_oid(self, seq: int) -> str:
        return f"journal_data.{self.image_name}.{seq:016x}"

    async def _header(self) -> dict[str, bytes]:
        try:
            return await self._io.omap_get(self.header_oid)
        except OSError as e:
            if e.errno == errno.ENOENT:
                return {}
            raise

    async def commit_pos(self) -> int:
        return int((await self._header()).get("commit_pos", b"-1"))

    async def tail_seq(self) -> int:
        """Highest seq ever appended (-1 = empty journal)."""
        return int((await self._header()).get("tail_seq", b"-1"))

    # -- producer ----------------------------------------------------------

    async def append(self, event: str, meta: dict, payload: bytes = b"") -> int:
        """Durably log one event; returns its seq.  MUST complete
        before the data path applies the mutation (write-ahead)."""
        if self._next_seq is None:
            self._next_seq = await self.tail_seq() + 1
        seq = self._next_seq
        self._next_seq += 1
        head = dict(meta)
        head["event"] = event
        hdr = json.dumps(head).encode()
        await self._io.write_full(
            self._data_oid(seq),
            len(hdr).to_bytes(4, "big") + hdr + payload)
        async with self._tail_lock:
            if seq > self._tail_persisted:
                await self._io.omap_set(
                    self.header_oid, {"tail_seq": str(seq).encode()})
                self._tail_persisted = seq
        return seq

    async def commit(self, seq: int) -> None:
        """The data path has durably applied event ``seq``.  commit_pos
        advances to the end of the contiguous applied prefix — an
        out-of-order completion (concurrent writes) parks here until
        its predecessors land."""
        self._applied.add(seq)
        # the read-advance-write below must be atomic: two concurrent
        # commits both reading a stale cur can transiently regress
        # commit_pos (parking trim below an applied seq) — same race
        # _tail_lock closes for tail_seq
        async with self._commit_lock:
            cur = await self.commit_pos()
            new = cur
            while new + 1 in self._applied:
                new += 1
            if new > cur:
                for s in range(cur + 1, new + 1):
                    self._applied.discard(s)
                await self._io.omap_set(
                    self.header_oid, {"commit_pos": str(new).encode()})

    # -- consumers ---------------------------------------------------------

    async def read_event(self, seq: int) -> tuple[dict, bytes] | None:
        try:
            raw = await self._io.read(self._data_oid(seq))
        except OSError as e:
            if e.errno == errno.ENOENT:
                return None
            raise
        n = int.from_bytes(raw[:4], "big")
        return json.loads(raw[4 : 4 + n]), raw[4 + n :]

    async def events_after(self, pos: int):
        """(seq, header, payload) for every event with seq > pos, in
        order."""
        tail = await self.tail_seq()
        out = []
        for seq in range(pos + 1, tail + 1):
            ev = await self.read_event(seq)
            if ev is not None:
                out.append((seq, ev[0], ev[1]))
        return out

    # -- mirror peers ------------------------------------------------------

    async def peer_pos(self, peer: str) -> int:
        return int((await self._header()).get(f"peer.{peer}", b"-1"))

    async def peer_commit(self, peer: str, seq: int) -> None:
        cur = await self.peer_pos(peer)
        if seq > cur:
            await self._io.omap_set(
                self.header_oid, {f"peer.{peer}": str(seq).encode()})

    async def register_peer(self, peer: str) -> None:
        hdr = await self._header()
        if f"peer.{peer}" not in hdr:
            await self._io.omap_set(
                self.header_oid, {f"peer.{peer}": b"-1"})

    # -- trim --------------------------------------------------------------

    async def trim(self) -> int:
        """Drop event objects every consumer (data path + all peers)
        has passed.  Returns how many were removed."""
        hdr = await self._header()
        floor = int(hdr.get("commit_pos", b"-1"))
        for k, v in hdr.items():
            if k.startswith("peer."):
                floor = min(floor, int(v))
        trimmed = int(hdr.get("trimmed_to", b"-1"))
        n = 0
        for seq in range(trimmed + 1, floor + 1):
            try:
                await self._io.remove(self._data_oid(seq))
                n += 1
            except OSError as e:
                if e.errno != errno.ENOENT:
                    raise
        if floor > trimmed:
            await self._io.omap_set(
                self.header_oid, {"trimmed_to": str(floor).encode()})
        return n

    async def destroy(self) -> None:
        tail = await self.tail_seq()
        for seq in range(tail + 1):
            try:
                await self._io.remove(self._data_oid(seq))
            except OSError as e:
                if e.errno != errno.ENOENT:
                    raise
        try:
            await self._io.remove(self.header_oid)
        except OSError as e:
            if e.errno != errno.ENOENT:
                raise
