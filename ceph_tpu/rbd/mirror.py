"""rbd-mirror: journal-based one-way image replication (reference
src/tools/rbd_mirror/: ImageReplayer bootstrap + journal replay,
promote/demote via the primary flag).

A :class:`MirrorDaemon` watches journaled primary images in a source
pool and replays their events into a destination pool — typically a
different cluster's RADOS client, here any second ``RBD`` handle:

  1. **bootstrap**: a missing destination image is created
     (non-primary) and fully synced object-by-object;
  2. **replay**: events past this peer's recorded position
     (``peer.<name>`` in the source journal header) are applied to the
     destination via the normal Image ops, then the position advances —
     at-least-once delivery, safe because events are idempotent;
  3. **failover**: ``demote()`` the source, ``promote()`` the
     destination; direction is enforced by the primary flag (a
     non-primary image refuses writes, ceph_tpu/rbd/__init__.py).
"""

from __future__ import annotations

import asyncio
import errno

from ceph_tpu.rbd import RBD, Image, RBDError


class MirrorDaemon:
    def __init__(self, src: RBD, dst: RBD, peer_name: str = "mirror"):
        self.src = src
        self.dst = dst
        self.peer = peer_name
        self.stats = {"events_replayed": 0, "images_bootstrapped": 0}
        self._task: asyncio.Task | None = None
        # open handles cached across polls: re-opening every 200ms
        # would re-read header+objmap per image per tick — and, worse,
        # re-run journal replay on the OWNER's journal (open with
        # replay=False is the non-owning stance; see RBD.open)
        self._src_imgs: dict[str, Image] = {}
        self._dst_imgs: dict[str, Image] = {}
        self.stopping = False

    # -- one image, one pass ----------------------------------------------

    async def _src_open(self, name: str) -> Image:
        img = self._src_imgs.get(name)
        if img is None:
            img = await self.src.open(name, replay=False)
            self._src_imgs[name] = img
        else:
            # primary/demote flips arrive out-of-band: re-read the flag
            hdr = await self.src.meta.omap_get(f"rbd_header.{name}")
            img.primary = hdr.get("primary", b"1") == b"1"
        return img

    async def sync_image(self, name: str) -> int:
        """Bootstrap if needed, then replay pending events.  Returns
        how many events were applied."""
        src_img = await self._src_open(name)
        if src_img.journal is None:
            raise RBDError(
                errno.EOPNOTSUPP, f"image {name!r} has no journaling")
        if not src_img.primary:
            return 0  # demoted: nothing flows from this side
        await src_img.journal.register_peer(self.peer)
        dst_img = await self._ensure_dst(name, src_img)
        pos = await src_img.journal.peer_pos(self.peer)
        applied = 0
        for seq, head, payload in await src_img.journal.events_after(pos):
            await self._apply(dst_img, head, payload)
            await src_img.journal.peer_commit(self.peer, seq)
            applied += 1
        self.stats["events_replayed"] += applied
        return applied

    async def _ensure_dst(self, name: str, src_img: Image) -> Image:
        cached = self._dst_imgs.get(name)
        if cached is not None:
            return cached
        hdr_oid = f"rbd_header.{name}"
        fresh = False
        try:
            img = await self.dst.open(name, replay=False)
            hdr = await self.dst.meta.omap_get(hdr_oid)
            complete = hdr.get("mirror_bootstrapped") == b"1"
        except RBDError as e:
            if e.errno != errno.ENOENT:
                raise
            # bootstrap: the copy is non-primary from birth and no
            # journaling feature — its writes come only from replay
            await self.dst.create(
                name, src_img.size(), order=src_img.order,
                features=tuple(
                    f for f in src_img.features if f != "journaling"),
            )
            img = await self.dst.open(name)
            complete = False
            fresh = True
        if not complete:
            # (re)run the full object copy: a crash mid-bootstrap left
            # a half-synced image that MUST NOT pass as replicated —
            # the completion flag is written only after the last
            # object lands (and the demote happens before any data, so
            # no crash window leaves both sides primary)
            await img.demote()
            img.primary = True  # temporarily, for the initial copy
            try:
                if img.size() != src_img.size():
                    # the source grew/shrank since a crashed attempt
                    # created dst — without this every resumed copy
                    # past the stale size fails forever
                    await img.resize(src_img.size())
                step = img.obj_size
                for off in range(0, src_img.size(), step):
                    n = min(step, src_img.size() - off)
                    data = await src_img.read(off, n)
                    if data.strip(b"\0"):
                        await img.write(off, data)
                    elif not fresh:
                        # resumed bootstrap: a crashed earlier attempt
                        # may have copied a block the source has since
                        # zeroed (and the journal event may already be
                        # trimmed — this peer wasn't registered yet).
                        # Skipping would leave the stale block behind a
                        # bootstrapped=1 flag: a silently divergent
                        # replica.  Sparse-skip is only safe on a
                        # just-created destination.
                        await img.write(off, data)
            finally:
                img.primary = False
            await self.dst.meta.omap_set(
                hdr_oid, {"mirror_bootstrapped": b"1"})
            self.stats["images_bootstrapped"] += 1
        self._dst_imgs[name] = img
        return img

    async def _apply(self, dst_img: Image, head: dict, payload: bytes) -> None:
        """Replay one source event onto the (non-primary) destination
        through the SAME dispatcher open-time crash replay uses
        (Image._apply_journal_event) — one switch over event types,
        with the demoted-image and size guards suspended there."""
        await dst_img._apply_journal_event(head, payload)

    # -- continuous mode ---------------------------------------------------

    async def run(self, interval: float = 0.2) -> None:
        """Poll-and-replay every journaled image until stop()."""
        while not self.stopping:
            try:
                for name in await self.src.list():
                    try:
                        await self.sync_image(name)
                    except RBDError:
                        continue  # not journaled / mid-create
            except OSError:
                pass  # source cluster briefly unavailable: retry
            await asyncio.sleep(interval)

    def start(self, interval: float = 0.2) -> None:
        self.stopping = False
        self._task = asyncio.ensure_future(self.run(interval))

    async def stop(self) -> None:
        self.stopping = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
