"""CephFS client-lite: POSIX-style API over MDS + direct data I/O.

Twin of the userspace client (src/client/Client.cc): metadata ops go
to the MDS as MClientRequest/MClientReply; file DATA bypasses the MDS
entirely — the client stripes bytes straight to the data pool using
the file's layout (src/osdc/Striper.cc file_to_extents, objects named
``<ino hex>.<objno 08x>``).

Capabilities (Client.cc cap handling, lite): ``open``/``create``
return cap bits.  A sole writer holds EXCL and BUFFERS size/mtime
updates locally — no per-write round-trip — flushing them on
``fsync``/``close``/``unmount`` or when the MDS recalls the cap
(MClientCaps REVOKE -> FLUSH -> ACK).  Without EXCL, each extending
write reports its size synchronously (``report_size``, which the MDS
only accepts from write-cap holders).

Snapshots: the MDS pushes the data pool's snap context (MClientCaps
SNAPC) and the client stamps it on writes, so object-level COW clones
happen under overwrite; ``dir/.snap/<name>/file`` paths open
read-only handles whose data reads resolve at the snapid.
"""

from __future__ import annotations

import asyncio
import errno
import itertools

from ceph_tpu.client.rados import IoCtx, RadosError
from ceph_tpu.client.striper import Layout, file_to_extents
from ceph_tpu.msg.messages import MClientCaps, MClientReply, MClientRequest
from ceph_tpu.msg.messenger import Messenger

from .mds import CAP_EXCL, CAP_RD, CAP_WR, FSError  # noqa: F401

REQUEST_TIMEOUT = 30.0


class FSClient:
    """Mounts the filesystem: MDS session + data-pool handle."""

    def __init__(self, mds_addr: tuple[str, int], data_io: IoCtx,
                 client_id: int | None = None):
        import os

        self.mds_addr = mds_addr
        self.data_io = data_io
        cid = client_id if client_id is not None else (os.getpid() << 8) | 3
        self.messenger = Messenger(("client", cid), self._dispatch)
        self._conn = None
        self._tids = itertools.count(1)
        self._waiters: dict[int, asyncio.Future] = {}
        # unique per MOUNT, not per entity: reqids from a previous
        # session of the same client must never hit the MDS's
        # completed-request cache (the reference's mon-issued global_id
        # plays this role)
        self._session = os.urandom(8).hex()
        # caps: ino -> bits; dirty buffered attrs: ino -> {path, size,
        # mtime} (flushed on fsync/close/recall/unmount)
        self._caps: dict[int, int] = {}
        self._dirty: dict[int, dict] = {}

    async def mount(self) -> None:
        self._conn = await self.messenger.connect(*self.mds_addr)

    async def unmount(self) -> None:
        try:
            await self.flush_dirty()
        except (FSError, ConnectionError, OSError):
            pass
        await self.messenger.shutdown()

    async def _dispatch(self, msg) -> None:
        if isinstance(msg, MClientReply):
            fut = self._waiters.get(msg.tid)
            if fut and not fut.done():
                fut.set_result(msg)
        elif isinstance(msg, MClientCaps):
            if msg.op == MClientCaps.REVOKE:
                await self._handle_revoke(msg)
            elif msg.op == MClientCaps.SNAPC:
                self.data_io.set_snap_context(msg.snap_seq, msg.snaps)

    async def _handle_revoke(self, msg: MClientCaps) -> None:
        """Flush buffered dirty state, downgrade to msg.caps, ack."""
        dirty = self._dirty.pop(msg.ino, None)
        try:
            if dirty is not None:
                await msg.conn.send_message(MClientCaps(
                    op=MClientCaps.FLUSH, ino=msg.ino,
                    path=dirty["path"], size=dirty.get("size", -1),
                    mtime=dirty.get("mtime", -1.0)))
            if msg.caps:
                self._caps[msg.ino] = msg.caps
            else:
                self._caps.pop(msg.ino, None)
            await msg.conn.send_message(MClientCaps(
                tid=msg.tid, op=MClientCaps.ACK, ino=msg.ino))
        except (ConnectionError, OSError):
            pass

    async def flush_dirty(self) -> None:
        """Push every buffered size/mtime to the MDS (cap flush on
        unmount / fsync-all)."""
        for ino, dirty in list(self._dirty.items()):
            await self.request(
                "report_size", path=dirty["path"], ino=ino,
                size=dirty.get("size", 0),
                mtime=dirty.get("mtime"))
            self._dirty.pop(ino, None)

    async def request(self, op: str, **args) -> dict:
        # one reqid across every retry of this logical request: the MDS
        # deduplicates a mutation whose first attempt landed but whose
        # reply was lost (completed_requests, Client.cc resend rules)
        args["_reqid"] = f"{self._session}:{next(self._tids)}"
        for attempt in range(3):
            tid = next(self._tids)
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._waiters[tid] = fut
            try:
                await self._conn.send_message(
                    MClientRequest(tid=tid, op=op, args=args))
                reply: MClientReply = await asyncio.wait_for(
                    fut, REQUEST_TIMEOUT)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                # session reset (MDS restart) or lost reply: reconnect
                # and resend — the Client.cc session-reconnect behavior.
                # Caps are session state: a reset drops them all.
                self._caps.clear()
                await asyncio.sleep(0.2 * (attempt + 1))
                try:
                    self._conn = await self.messenger.connect(*self.mds_addr)
                except (ConnectionError, OSError):
                    pass
                continue
            finally:
                self._waiters.pop(tid, None)
            if reply.result < 0:
                raise FSError(-reply.result, f"{op} failed")
            return reply.out
        raise FSError(errno.ETIMEDOUT, f"{op}: mds unreachable")

    # -- metadata ------------------------------------------------------

    async def mkdir(self, path: str, mode: int = 0o755) -> None:
        await self.request("mkdir", path=path, mode=mode)

    async def rmdir(self, path: str) -> None:
        await self.request("rmdir", path=path)

    async def unlink(self, path: str) -> None:
        await self.request("unlink", path=path)

    async def rename(self, src: str, dst: str) -> None:
        await self.request("rename", src=src, dst=dst)
        for d in self._dirty.values():
            if d.get("path") == src:
                d["path"] = dst  # flushes must chase the new name

    async def stat(self, path: str) -> dict:
        attr = (await self.request("stat", path=path))["attr"]
        # overlay OUR buffered (EXCL) attrs: a client always sees its
        # own writes even before the cap flush lands.  A snapshot view
        # is frozen past — the live file's buffered size must NOT leak
        # into it (the attr shares the live ino)
        if attr.get("snapid") is not None:
            return attr
        d = self._dirty.get(attr.get("ino"))
        if d is not None:
            if "size" in d:
                attr["size"] = max(attr.get("size", 0), d["size"])
            if "mtime" in d:
                attr["mtime"] = d["mtime"]
        return attr

    async def readdir(self, path: str) -> dict[str, dict]:
        return (await self.request("readdir", path=path))["entries"]

    async def symlink(self, path: str, target: str) -> None:
        await self.request("symlink", path=path, target=target)

    async def readlink(self, path: str) -> str:
        return (await self.request("readlink", path=path))["target"]

    async def truncate(self, path: str, size: int) -> None:
        # flush OUR buffered extension first: the MDS decides
        # shrink-vs-grow against its recorded size, so a buffered
        # larger size must land before the truncate judges it
        for ino, d in list(self._dirty.items()):
            if d.get("path") == path:
                self._dirty.pop(ino, None)
                await self.request(
                    "report_size", path=path, ino=ino,
                    size=d.get("size", 0), mtime=d.get("mtime"))
        await self.request("setattr", path=path, size=size)

    async def sync(self) -> None:
        """fsync-the-filesystem: flush caps + force the MDS journal
        trim."""
        await self.flush_dirty()
        await self.request("flush")

    # -- snapshots -----------------------------------------------------

    async def snap_create(self, path: str, name: str) -> int:
        # buffered EXCL size/mtime must reach the MDS BEFORE it freezes
        # the manifest, or the snapshot records a stale smaller size
        # and snap reads silently truncate acked writes
        await self.flush_dirty()
        out = await self.request("snap_create", path=path, name=name)
        seq, snaps = out["snapc"]
        self.data_io.set_snap_context(seq, snaps)
        return out["snapid"]

    async def snap_remove(self, path: str, name: str) -> None:
        out = await self.request("snap_remove", path=path, name=name)
        seq, snaps = out["snapc"]
        self.data_io.set_snap_context(seq, snaps)

    # -- file I/O ------------------------------------------------------

    def _adopt(self, out: dict) -> None:
        if out.get("caps"):
            self._caps[out["ino"]] = out["caps"]
        snapc = out.get("snapc")
        if snapc:
            self.data_io.set_snap_context(snapc[0], snapc[1])

    def _eff_size(self, out: dict) -> int:
        d = self._dirty.get(out["ino"])
        if d is not None and "size" in d:
            return max(out["size"], d["size"])
        return out["size"]

    async def create(self, path: str, mode: int = 0o644) -> "File":
        out = await self.request("create", path=path, mode=mode)
        self._adopt(out)
        return File(self, path, out["ino"], self._eff_size(out),
                    Layout(*out["layout"]))

    async def open(self, path: str, want: str = "r") -> "File":
        out = await self.request("open", path=path, want=want)
        self._adopt(out)
        return File(self, path, out["ino"], self._eff_size(out),
                    Layout(*out["layout"]),
                    snapid=out.get("snapid"))


class File:
    """An open file: striped data I/O + cap-aware size tracking (Fh).
    ``snapid`` set = a read-only handle inside a ``.snap`` path."""

    def __init__(self, fs: FSClient, path: str, ino: int, size: int,
                 layout: Layout, snapid: int | None = None):
        self.fs = fs
        self.path = path
        self.ino = ino
        self.size = size
        self.layout = layout
        self.snapid = snapid
        if snapid is not None:
            # dedicated snap-read handle: reads resolve at the snapid
            # (librados snap_set_read), never at head
            self._io = IoCtx(fs.data_io.client, fs.data_io.pool_id)
            self._io.snap_set_read(snapid)
        else:
            self._io = fs.data_io

    def _oid(self, objectno: int) -> str:
        return f"{self.ino:x}.{objectno:08x}"

    async def write(self, off: int, data: bytes) -> None:
        if self.snapid is not None:
            raise FSError(errno.EROFS, "snapshot handle")
        if not data:
            return
        pos = 0
        writes = []
        for objectno, obj_off, n in file_to_extents(
                self.layout, off, len(data)):
            writes.append(self.fs.data_io.write(
                self._oid(objectno), data[pos:pos + n], off=obj_off))
            pos += n
        await asyncio.gather(*writes)
        if off + len(data) > self.size:
            self.size = off + len(data)
            if self.fs._caps.get(self.ino, 0) & CAP_EXCL:
                # sole writer: buffer the attr update (no round-trip);
                # flushed on fsync/close/recall
                d = self.fs._dirty.setdefault(
                    self.ino, {"path": self.path})
                d["size"] = max(d.get("size", 0), self.size)
                import time as _time

                d["mtime"] = _time.time()
            else:
                await self.fs.request(
                    "report_size", path=self.path, ino=self.ino,
                    size=self.size)

    async def read(self, off: int = 0, length: int | None = None) -> bytes:
        end = self.size if length is None else min(off + length, self.size)
        if off >= end:
            return b""

        async def _one(objectno: int, obj_off: int, n: int) -> bytes:
            try:
                chunk = await self._io.read(
                    self._oid(objectno), off=obj_off, length=n)
            except RadosError as e:
                if e.errno != errno.ENOENT:
                    raise
                chunk = b""  # sparse hole
            return chunk.ljust(n, b"\0")

        parts = await asyncio.gather(*(
            _one(*ext)
            for ext in file_to_extents(self.layout, off, end - off)))
        return b"".join(parts)

    async def fsync(self) -> None:
        """Flush buffered caps state; refresh our size view."""
        dirty = self.fs._dirty.pop(self.ino, None)
        if dirty is not None:
            await self.fs.request(
                "report_size", path=self.path, ino=self.ino,
                size=dirty.get("size", 0), mtime=dirty.get("mtime"))
        attr = await self.fs.stat(self.path)
        self.size = attr["size"]

    async def close(self) -> None:
        """Flush buffered size/mtime (the cap-flush half of release)."""
        dirty = self.fs._dirty.pop(self.ino, None)
        if dirty is not None:
            await self.fs.request(
                "report_size", path=self.path, ino=self.ino,
                size=dirty.get("size", 0), mtime=dirty.get("mtime"))
