"""CephFS client-lite: POSIX-style API over MDS + direct data I/O.

Twin of the userspace client (src/client/Client.cc): metadata ops go
to the MDS as MClientRequest/MClientReply; file DATA bypasses the MDS
entirely — the client stripes bytes straight to the data pool using
the file's layout (src/osdc/Striper.cc file_to_extents, objects named
``<ino hex>.<objno 08x>``).  Cap-free v1: after a write extends a file
the client reports the new size to the MDS (setattr) instead of
holding a size cap.
"""

from __future__ import annotations

import asyncio
import errno
import itertools

from ceph_tpu.client.rados import IoCtx, RadosError
from ceph_tpu.client.striper import Layout, file_to_extents
from ceph_tpu.msg.messages import MClientReply, MClientRequest
from ceph_tpu.msg.messenger import Messenger

from .mds import FSError

REQUEST_TIMEOUT = 30.0


class FSClient:
    """Mounts the filesystem: MDS session + data-pool handle."""

    def __init__(self, mds_addr: tuple[str, int], data_io: IoCtx,
                 client_id: int | None = None):
        import os

        self.mds_addr = mds_addr
        self.data_io = data_io
        cid = client_id if client_id is not None else (os.getpid() << 8) | 3
        self.messenger = Messenger(("client", cid), self._dispatch)
        self._conn = None
        self._tids = itertools.count(1)
        self._waiters: dict[int, asyncio.Future] = {}
        # unique per MOUNT, not per entity: reqids from a previous
        # session of the same client must never hit the MDS's
        # completed-request cache (the reference's mon-issued global_id
        # plays this role)
        self._session = os.urandom(8).hex()

    async def mount(self) -> None:
        self._conn = await self.messenger.connect(*self.mds_addr)

    async def unmount(self) -> None:
        await self.messenger.shutdown()

    async def _dispatch(self, msg) -> None:
        if isinstance(msg, MClientReply):
            fut = self._waiters.get(msg.tid)
            if fut and not fut.done():
                fut.set_result(msg)

    async def request(self, op: str, **args) -> dict:
        # one reqid across every retry of this logical request: the MDS
        # deduplicates a mutation whose first attempt landed but whose
        # reply was lost (completed_requests, Client.cc resend rules)
        args["_reqid"] = f"{self._session}:{next(self._tids)}"
        for attempt in range(3):
            tid = next(self._tids)
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._waiters[tid] = fut
            try:
                await self._conn.send_message(
                    MClientRequest(tid=tid, op=op, args=args))
                reply: MClientReply = await asyncio.wait_for(
                    fut, REQUEST_TIMEOUT)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                # session reset (MDS restart) or lost reply: reconnect
                # and resend — the Client.cc session-reconnect behavior
                await asyncio.sleep(0.2 * (attempt + 1))
                try:
                    self._conn = await self.messenger.connect(*self.mds_addr)
                except (ConnectionError, OSError):
                    pass
                continue
            finally:
                self._waiters.pop(tid, None)
            if reply.result < 0:
                raise FSError(-reply.result, f"{op} failed")
            return reply.out
        raise FSError(errno.ETIMEDOUT, f"{op}: mds unreachable")

    # -- metadata ------------------------------------------------------

    async def mkdir(self, path: str, mode: int = 0o755) -> None:
        await self.request("mkdir", path=path, mode=mode)

    async def rmdir(self, path: str) -> None:
        await self.request("rmdir", path=path)

    async def unlink(self, path: str) -> None:
        await self.request("unlink", path=path)

    async def rename(self, src: str, dst: str) -> None:
        await self.request("rename", src=src, dst=dst)

    async def stat(self, path: str) -> dict:
        return (await self.request("stat", path=path))["attr"]

    async def readdir(self, path: str) -> dict[str, dict]:
        return (await self.request("readdir", path=path))["entries"]

    async def symlink(self, path: str, target: str) -> None:
        await self.request("symlink", path=path, target=target)

    async def readlink(self, path: str) -> str:
        return (await self.request("readlink", path=path))["target"]

    async def truncate(self, path: str, size: int) -> None:
        await self.request("setattr", path=path, size=size)

    async def sync(self) -> None:
        """fsync-the-filesystem: force the MDS flush + journal trim."""
        await self.request("flush")

    # -- file I/O ------------------------------------------------------

    async def create(self, path: str, mode: int = 0o644) -> "File":
        out = await self.request("create", path=path, mode=mode)
        return File(self, path, out["ino"], out["size"],
                    Layout(*out["layout"]))

    async def open(self, path: str) -> "File":
        out = await self.request("open", path=path)
        return File(self, path, out["ino"], out["size"],
                    Layout(*out["layout"]))


class File:
    """An open file: striped data I/O + size reporting (Fh)."""

    def __init__(self, fs: FSClient, path: str, ino: int, size: int,
                 layout: Layout):
        self.fs = fs
        self.path = path
        self.ino = ino
        self.size = size
        self.layout = layout

    def _oid(self, objectno: int) -> str:
        return f"{self.ino:x}.{objectno:08x}"

    async def write(self, off: int, data: bytes) -> None:
        if not data:
            return
        pos = 0
        writes = []
        for objectno, obj_off, n in file_to_extents(
                self.layout, off, len(data)):
            writes.append(self.fs.data_io.write(
                self._oid(objectno), data[pos:pos + n], off=obj_off))
            pos += n
        await asyncio.gather(*writes)
        if off + len(data) > self.size:
            self.size = off + len(data)
            await self.fs.request("setattr", path=self.path, size=self.size)

    async def read(self, off: int = 0, length: int | None = None) -> bytes:
        end = self.size if length is None else min(off + length, self.size)
        if off >= end:
            return b""
        async def _one(objectno: int, obj_off: int, n: int) -> bytes:
            try:
                chunk = await self.fs.data_io.read(
                    self._oid(objectno), off=obj_off, length=n)
            except RadosError as e:
                if e.errno != errno.ENOENT:
                    raise
                chunk = b""  # sparse hole
            return chunk.ljust(n, b"\0")

        parts = await asyncio.gather(*(
            _one(*ext)
            for ext in file_to_extents(self.layout, off, end - off)))
        return b"".join(parts)

    async def fsync(self) -> None:
        """Refresh our size view + push mtime (no caps to flush)."""
        attr = await self.fs.stat(self.path)
        self.size = attr["size"]
