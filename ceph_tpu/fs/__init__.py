"""CephFS-lite: single-MDS filesystem on RADOS.

The reference's file service (src/mds/ 92 kLoC + src/client/ 29 kLoC)
reduced to its load-bearing shape: dirfrag omaps + journaled metadata
mutations on the MDS (:mod:`mds`, :mod:`journal`), striped direct
data I/O on the client (:mod:`client`).
"""

from .client import File, FSClient  # noqa: F401
from .mds import FSError, MDSDaemon  # noqa: F401
