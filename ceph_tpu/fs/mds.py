"""MDS-lite: the single-active metadata server.

Behavioral twin of the reference MDS reduced to one rank, no subtree
migration (src/mds/MDSDaemon.cc boot, src/mds/Server.cc request
dispatch, src/mds/MDCache.cc the inode/dentry cache): directory
content lives as omap on per-directory "dirfrag" objects in the
metadata pool (``<ino hex>.00000000``, the CDir backing store), with
each inode embedded in its parent's primary dentry exactly like the
reference stores InodeStore inline; every mutation journals first
(:mod:`ceph_tpu.fs.journal`, the src/mds/journal.cc EMetaBlob
discipline) then applies to the cache, and dirty dirfrags flush back
lazily — restart replays the journal over the flushed state.

File DATA does not pass through the MDS: clients stripe file bytes
directly to the data pool as ``<ino hex>.<objno 8x>`` objects (the
CephFS file layout); the MDS allocates inos, owns size/mtime truth,
and purges data on unlink — the PurgeQueue role, done inline.

**Capabilities (the Locker role, src/mds/Locker.cc reduced to one
file lock class).**  Per-(session, ino) cap bits: RD (may cache
attrs), WR (may report size), EXCL (may BUFFER size/mtime updates
locally).  A writer opening alone gets RD|WR|EXCL; a second client
touching the file forces a recall — the MDS sends MClientCaps REVOKE,
the holder FLUSHes its buffered size/mtime (journaled as setattr) and
ACKs — so every size the MDS serves reflects all flushed writes, and
only sessions holding WR may move a size (closing the v1
any-client-reports-anything hole).

**Snapshots (SnapRealm-lite, src/mds/SnapRealm.cc + snapc plumbing).**
``snap_create(dir, name)`` allocates a self-managed snapid on the DATA
pool (object-level COW under overwrite, ceph_tpu/osd/snaps.py), then
freezes the subtree's metadata into a manifest object
(``snapmeta.<ino hex>.<snapid>``) — written before the journal event
so replay always finds it.  Clients learn the new snap context via an
MClientCaps SNAPC broadcast and stamp subsequent data writes with it.
Reads traverse ``dir/.snap/<name>/...`` against the manifest, with
file data read at the snapid.  The snap context is data-pool-global
(a conservative superset of the per-realm context the reference
computes — extra clones, never missing ones).
"""

from __future__ import annotations

import asyncio
import errno
import itertools
import logging
import time

from ceph_tpu.client.rados import ObjectOperation, RadosClient, RadosError
from ceph_tpu.client.striper import Layout, file_to_extents
from ceph_tpu.msg.messages import MClientCaps, MClientReply, MClientRequest
from ceph_tpu.msg.messenger import Messenger

from .journal import Journaler

log = logging.getLogger("ceph_tpu.mds")

ROOT_INO = 1  # MDS_INO_ROOT (src/mds/mdstypes.h)
DEFAULT_LAYOUT = [65536, 4, 4 * 2**20]  # [stripe_unit, stripe_count, object_size]

# cap bits (the CEPH_CAP_FILE_* lattice collapsed to three rungs)
CAP_RD = 1    # may cache attrs / serve stat locally
CAP_WR = 2    # may write data + report size (setattr/flush accepted)
CAP_EXCL = 4  # sole writer: may buffer size/mtime, flushed on recall


class FSError(OSError):
    pass


def _err(code: int, msg: str) -> FSError:
    return FSError(code, msg)


class MDSDaemon:
    """One MDS rank over the shared Messenger, backed by RADOS pools.

    ``flush_every``: dirty-dirfrag writeback + journal checkpoint cadence
    in events (LogSegment size, tiny here so tests hit both paths).
    """

    def __init__(self, rank: int, mon_addr: tuple[str, int],
                 meta_pool: str = "cephfs.meta",
                 data_pool: str = "cephfs.data",
                 flush_every: int = 128, conf=None):
        self.rank = rank
        self.mon_addr = mon_addr
        self.meta_pool = meta_pool
        self.data_pool = data_pool
        self.flush_every = flush_every
        self.messenger = Messenger(("mds", rank), self._dispatch)
        self.rados: RadosClient | None = None
        self.journal: Journaler | None = None
        self.ino_next = ROOT_INO + 1
        # MDCache: ino -> {"entries": {name: rec}, "dirty": bool}
        self._dirs: dict[int, dict] = {}
        self._doomed: set[int] = set()     # dirfrag objects to remove at flush
        self._mutation_lock = asyncio.Lock()  # single-MDS total order
        self._events_since_flush = 0
        # completed-request cache (the reference session's
        # completed_requests): reqid -> reply payload, rebuilt from the
        # journal on replay, so a client retrying a mutation whose
        # first attempt landed gets its original answer instead of
        # EEXIST/ENOENT
        self._completed: dict[str, dict] = {}
        self._cur_reqid: str | None = None
        self._cur_conn = None
        self.addr: tuple[str, int] | None = None
        # caps (Locker): ino -> {conn: bits}; conns are the sessions
        self._cap_holders: dict[int, dict] = {}
        self._cap_tids = itertools.count(1)
        self._cap_waiters: dict[int, asyncio.Future] = {}
        self._sessions: set = set()  # live conns (for SNAPC broadcast)
        # snapshots (SnapRealm-lite): dir ino -> {name: {"id", "t"}}
        self._realms: dict[int, dict] = {}
        self._snap_seq = 0
        # mgr report stream (MgrMap rides the rados session's mon
        # subscription; reports go out over our own messenger)
        from ceph_tpu.common import ConfigProxy, get_perf_counters
        from ceph_tpu.common.tracing import Tracer
        from ceph_tpu.mgr.client import MgrClient

        self.conf = conf if conf is not None else ConfigProxy()
        self.perf = get_perf_counters(f"mds.{rank}")
        self.tracer = Tracer(
            f"mds.{rank}",
            ring_max=self.conf["trace_ring_max"],
            sample_rate=self.conf["trace_sample_rate"],
            tail_slow_s=(self.conf["trace_tail_slow_s"] or None),
        )
        self.messenger.tracer = self.tracer
        self._admin = None
        self.mgr_client = MgrClient(
            f"mds.{rank}", self.messenger, self.conf,
            self._mgr_collect, tracers=(self.tracer,))

    # -- lifecycle -----------------------------------------------------

    async def start(self) -> None:
        self.rados = RadosClient(client_id=(7000 + self.rank))
        await self.rados.connect(*self.mon_addr)
        self.meta_io = self.rados.ioctx(self.meta_pool)
        self.data_io = self.rados.ioctx(self.data_pool)
        self.journal = Journaler(self.meta_io, f"mds{self.rank}.journal")
        state, events = await self.journal.load()
        self.ino_next = state.get("ino_next", ROOT_INO + 1)
        self._realms = {
            int(k): v for k, v in state.get("realms", {}).items()}
        self._snap_seq = state.get("snap_seq", 0)
        for ev in events:
            await self._apply(ev, replay=True)
        self.addr = await self.messenger.bind()
        sock_path = self.conf["admin_socket"]
        if sock_path:
            from ceph_tpu.common import AdminSocket

            self._admin = AdminSocket(
                sock_path.replace("$id", f"mds.{self.rank}"))
            self._admin.register(
                "dump_traces", "recent spans (blkin/otel role)",
                lambda cmd: self.tracer.dump(),
            )
            self._admin.register(
                "perf dump", "dump perf counters",
                lambda cmd: self.perf.dump(),
            )
            self._admin.register(
                "status", "daemon status",
                lambda cmd: {
                    "mds": self.rank,
                    "cached_dirs": len(self._dirs),
                    "sessions": len(self._sessions),
                },
            )
            await self._admin.start()
        self.rados.set_mgr_map_listener(self.mgr_client.handle_mgr_map)
        self.mgr_client.start()
        log.info("mds.%d: up at %s, replayed %d events",
                 self.rank, self.addr, len(events))

    async def stop(self) -> None:
        """Clean shutdown: flush + trim, then drop sessions."""
        await self.mgr_client.stop()
        if self._admin is not None:
            await self._admin.stop()
        async with self._mutation_lock:
            await self._flush()
        await self.messenger.shutdown()
        await self.rados.shutdown()

    async def crash(self) -> None:
        """Test hook: die WITHOUT flushing — restart must replay."""
        await self.mgr_client.stop()
        if self._admin is not None:
            await self._admin.stop()
        await self.messenger.shutdown()
        await self.rados.shutdown()

    def _mgr_collect(self) -> dict:
        return {
            "counters": self.perf.dump(),
            "gauges": {
                "cached_dirs": float(len(self._dirs)),
                "sessions": float(len(self._sessions)),
            },
            "status": {"rank": self.rank,
                       "snap_seq": self._snap_seq},
        }

    # -- dirfrag cache (MDCache/CDir) ----------------------------------

    def _dirfrag_oid(self, ino: int) -> str:
        return f"{ino:x}.00000000"

    async def _dir(self, ino: int) -> dict:
        d = self._dirs.get(ino)
        if d is None:
            import json

            try:
                omap = await self.meta_io.omap_get(self._dirfrag_oid(ino))
            except RadosError as e:
                if e.errno != errno.ENOENT:
                    raise
                omap = {}
            d = {"entries": {k: json.loads(v) for k, v in omap.items()},
                 "dirty": False}
            self._dirs[ino] = d
        return d

    async def _flush(self) -> None:
        """Write back dirty dirfrags, delete doomed ones, checkpoint
        the journal (LogSegment expiry)."""
        import json

        for ino, d in list(self._dirs.items()):
            if not d["dirty"] or ino in self._doomed:
                continue
            op = ObjectOperation().omap_clear().omap_set({
                name: json.dumps(rec).encode()
                for name, rec in d["entries"].items()
            })
            await self.meta_io.operate(self._dirfrag_oid(ino), op)
            d["dirty"] = False
        for ino in list(self._doomed):
            try:
                await self.meta_io.remove(self._dirfrag_oid(ino))
            except RadosError:
                pass
            self._doomed.discard(ino)
            self._dirs.pop(ino, None)
        await self.journal.checkpoint({
            "ino_next": self.ino_next,
            "realms": {str(k): v for k, v in self._realms.items()},
            "snap_seq": self._snap_seq,
        })
        self._events_since_flush = 0

    async def _journal_and_apply(self, ev: dict) -> None:
        if self._cur_reqid:
            ev["reqid"] = self._cur_reqid
        await self.journal.append(ev)
        await self._apply(ev)
        self._events_since_flush += 1
        if self._events_since_flush >= self.flush_every:
            await self._flush()

    @staticmethod
    def _reply_of(ev: dict) -> dict:
        """The reply payload a journaled mutation produced — derivable
        from the event, so replay can rebuild the completed-request
        cache."""
        op = ev["op"]
        if op == "create":
            return {"ino": ev["ino"], "size": 0, "layout": ev["layout"],
                    "existed": False}
        if op in ("mkdir", "symlink"):
            return {"ino": ev["ino"]}
        return {}

    def _record_completed(self, reqid: str, out: dict) -> None:
        self._completed[reqid] = out
        while len(self._completed) > 4096:
            self._completed.pop(next(iter(self._completed)))

    # -- event application (EMetaBlob::replay) -------------------------

    async def _apply(self, ev: dict, replay: bool = False) -> None:
        """Idempotent apply of a journal event to the cache.  During
        replay the affected dirfrags load from their flushed state
        first, then the event lands on top."""
        op = ev["op"]
        if ev.get("reqid"):
            self._record_completed(ev["reqid"], self._reply_of(ev))
        if op in ("mkdir", "create", "symlink"):
            d = await self._dir(ev["p"])
            rec = {"ino": ev["ino"], "mtime": ev["t"],
                   "mode": ev.get("mode", 0o644)}
            if op == "mkdir":
                rec["type"] = "dir"
            elif op == "create":
                rec["type"] = "file"
                rec["size"] = 0
                rec["layout"] = ev["layout"]
            else:
                rec["type"] = "symlink"
                rec["target"] = ev["target"]
            d["entries"][ev["n"]] = rec
            d["dirty"] = True
            if replay:
                self.ino_next = max(self.ino_next, ev["ino"] + 1)
        elif op in ("unlink", "rmdir"):
            d = await self._dir(ev["p"])
            d["entries"].pop(ev["n"], None)
            d["dirty"] = True
            if op == "rmdir":
                self._doomed.add(ev["ino"])
                self._dirs.pop(ev["ino"], None)
            purge = ev.get("purge")
            if purge:
                await self._purge_data(
                    purge["ino"], purge["size"], purge["layout"])
        elif op == "rename":
            src = await self._dir(ev["sp"])
            dst = await self._dir(ev["dp"])
            rec = src["entries"].pop(ev["sn"], None)
            purge = ev.get("purge")
            if purge:
                await self._purge_data(
                    purge["ino"], purge["size"], purge["layout"])
            if ev.get("doom") is not None:  # replaced an empty dir
                self._doomed.add(ev["doom"])
                self._dirs.pop(ev["doom"], None)
            if rec is not None:
                dst["entries"][ev["dn"]] = rec
            src["dirty"] = dst["dirty"] = True
        elif op == "setattr":
            d = await self._dir(ev["p"])
            rec = d["entries"].get(ev["n"])
            trunc = ev.get("truncate")
            if trunc:
                # data truncation lives HERE, after the event is
                # durable: a crash before the append leaves the file
                # intact; replay re-truncates (idempotent)
                await self._truncate_data(trunc, ev["size"])
            if rec is not None:
                for f in ("size", "mtime", "mode"):
                    if f in ev:
                        rec[f] = ev[f]
                d["dirty"] = True
        elif op == "snap_create":
            realm = self._realms.setdefault(ev["ino"], {})
            realm[ev["n"]] = {"id": ev["snapid"], "t": ev["t"]}
            self._snap_seq = max(self._snap_seq, ev["snapid"])
        elif op == "snap_remove":
            realm = self._realms.get(ev["ino"], {})
            realm.pop(ev["n"], None)
            if not realm:
                self._realms.pop(ev["ino"], None)
            # idempotent cleanup, also on replay: a crash between the
            # journal append and these removals must not leak the
            # manifest or the rados snap (clone space) forever
            try:
                await self.meta_io.remove(
                    f"snapmeta.{ev['ino']:x}.{ev['snapid']}")
            except RadosError:
                pass
            try:
                await self.data_io.selfmanaged_snap_remove(ev["snapid"])
            except RadosError:
                pass
        else:  # pragma: no cover
            log.warning("mds: unknown journal op %r", op)

    async def _purge_data(self, ino: int, size: int, layout: list) -> None:
        """Inline PurgeQueue: drop the file's data objects."""
        lay = Layout(*layout)
        objnos = {0}
        for objectno, _o, _n in file_to_extents(lay, 0, max(size, 1)):
            objnos.add(objectno)
        for objectno in objnos:
            try:
                await self.data_io.remove(f"{ino:x}.{objectno:08x}")
            except RadosError:
                pass

    # -- path resolution (MDCache::path_traverse) ----------------------

    @staticmethod
    def _split(path: str) -> list[str]:
        parts = [p for p in path.split("/") if p]
        if any(p == ".." for p in parts):
            raise _err(errno.EINVAL, "'..' not supported")
        return [p for p in parts if p != "."]

    async def _resolve_dir(self, parts: list[str]) -> int:
        """Walk every component as a directory; returns its ino."""
        ino = ROOT_INO
        for name in parts:
            d = await self._dir(ino)
            rec = d["entries"].get(name)
            if rec is None:
                raise _err(errno.ENOENT, name)
            if rec["type"] != "dir":
                raise _err(errno.ENOTDIR, name)
            ino = rec["ino"]
        return ino

    async def _resolve_parent(self, path: str) -> tuple[int, str]:
        parts = self._split(path)
        if not parts:
            raise _err(errno.EINVAL, "root")
        if ".snap" in parts:
            raise _err(errno.EROFS, "snapshots are read-only")
        return await self._resolve_dir(parts[:-1]), parts[-1]

    async def _snap_lookup(self, path: str) -> tuple[dict, int] | None:
        """Resolve a ``dir/.snap/<name>/rest`` path against the frozen
        manifest; returns (rec, snapid) or None for live paths."""
        import json

        parts = self._split(path)
        if ".snap" not in parts:
            return None
        i = parts.index(".snap")
        if i == len(parts) - 1:
            raise _err(errno.EINVAL, ".snap itself is not a snapshot")
        dino = await self._resolve_dir(parts[:i])
        name = parts[i + 1]
        snap = self._realms.get(dino, {}).get(name)
        if snap is None:
            raise _err(errno.ENOENT, f".snap/{name}")
        snapid = snap["id"]
        try:
            raw = await self.meta_io.read(f"snapmeta.{dino:x}.{snapid}")
        except RadosError:
            raise _err(errno.EIO, "snapshot manifest missing") from None
        node: dict = {"type": "dir", "ino": dino, "mode": 0o755,
                      "mtime": snap["t"], "children": json.loads(raw)}
        for comp in parts[i + 2:]:
            if node["type"] != "dir":
                raise _err(errno.ENOTDIR, comp)
            rec = node.get("children", {}).get(comp)
            if rec is None:
                raise _err(errno.ENOENT, comp)
            node = rec
        return node, snapid

    async def _lookup(self, path: str) -> dict:
        snap = await self._snap_lookup(path)
        if snap is not None:
            rec, snapid = snap
            out = {k: v for k, v in rec.items() if k != "children"}
            out["snapid"] = snapid
            return out
        parts = self._split(path)
        if not parts:
            return {"ino": ROOT_INO, "type": "dir", "mode": 0o755,
                    "mtime": 0}
        pino = await self._resolve_dir(parts[:-1])
        d = await self._dir(pino)
        rec = d["entries"].get(parts[-1])
        if rec is None:
            raise _err(errno.ENOENT, path)
        return rec

    # -- request dispatch (src/mds/Server.cc) --------------------------

    async def _dispatch(self, msg) -> None:
        if isinstance(msg, MClientCaps):
            await self._handle_caps(msg)
            return
        if not isinstance(msg, MClientRequest):
            return
        self._sessions.add(msg.conn)
        args = dict(msg.args)
        reqid = args.pop("_reqid", None)
        with self.tracer.span(
            "mds_req", ctx=msg.trace, op=msg.op,
            reqid=str(reqid or msg.tid),
        ):
            await self._serve_request(msg, args, reqid)

    async def _serve_request(self, msg, args: dict, reqid) -> None:
        import inspect

        handler = getattr(self, f"_op_{msg.op}", None)
        if handler is None:
            reply = MClientReply(msg.tid, -errno.EOPNOTSUPP)
        elif reqid is not None and reqid in self._completed:
            # a retry of a mutation that already landed: original answer
            reply = MClientReply(msg.tid, 0, self._completed[reqid])
        else:
            try:
                # bad client args must NOT be conflated with handler
                # bugs: bind-check here, so a TypeError raised deeper
                # inside the handler surfaces as a logged EIO below
                inspect.signature(handler).bind(**args)
            except TypeError:
                reply = MClientReply(msg.tid, -errno.EINVAL)
            else:
                try:
                    # cap recalls run BEFORE the mutation lock: a
                    # revoked holder's FLUSH needs the lock to journal
                    # its dirty size — recalling inside it would
                    # deadlock (Locker orders lock acquisition the
                    # same way)
                    await self._pre_recall(msg.op, args, msg.conn)
                    # reads serialize with mutations too: _apply awaits
                    # mid-event (dirfrag loads, purges), so an unlocked
                    # read could observe a half-applied rename
                    async with self._mutation_lock:
                        self._cur_reqid = reqid
                        self._cur_conn = msg.conn
                        try:
                            out = await handler(**args)
                        finally:
                            self._cur_reqid = None
                            self._cur_conn = None
                    reply = MClientReply(msg.tid, 0, out or {})
                except FSError as e:
                    reply = MClientReply(msg.tid, -(e.errno or errno.EIO))
                except Exception:
                    log.exception("mds: %s failed", msg.op)
                    reply = MClientReply(msg.tid, -errno.EIO)
        try:
            await msg.conn.send_message(reply)
        except ConnectionError:
            pass

    # -- capabilities (Locker) -----------------------------------------

    async def _handle_caps(self, msg: MClientCaps) -> None:
        if msg.op == MClientCaps.FLUSH:
            # dirty size/mtime from a (soon to be ex-) cap holder: the
            # session must actually hold WR or EXCL on the ino, and
            # the path must still resolve to it — anything else is
            # ignored (the trust hole v1 left open)
            bits = self._cap_holders.get(msg.ino, {}).get(msg.conn, 0)
            if not bits & (CAP_WR | CAP_EXCL):
                log.warning("mds: uncapped flush for ino %x dropped",
                            msg.ino)
                return
            async with self._mutation_lock:
                try:
                    pino, name = await self._resolve_parent(msg.path)
                    d = await self._dir(pino)
                    rec = d["entries"].get(name)
                except FSError:
                    rec = None
                if rec is None or rec.get("ino") != msg.ino:
                    return
                ev = {"op": "setattr", "p": pino, "n": name}
                if msg.size > rec.get("size", 0):
                    # flushes only EXTEND — truncation is an explicit
                    # MDS-executed op, and a stale flush racing a
                    # fresh truncate must not resurrect the old size
                    ev["size"] = msg.size
                if msg.mtime >= 0:
                    ev["mtime"] = msg.mtime
                if len(ev) > 3:
                    await self._journal_and_apply(ev)
        elif msg.op == MClientCaps.ACK:
            fut = self._cap_waiters.get(msg.tid)
            if fut and not fut.done():
                fut.set_result(msg)

    async def _pre_recall(self, op: str, args: dict, conn) -> None:
        """Revoke conflicting caps before the op runs (Locker's
        wrlock/rdlock acquisition order).  EXCL-only recalls flush the
        sole writer's buffered size; full recalls also invalidate
        reader caches (writer arriving / namespace op)."""
        paths: list[tuple[str, bool]] = []  # (path, only_excl)
        if op in ("stat", "readdir"):
            paths = [(args.get("path", ""), True)]
        elif op == "open":
            paths = [(args.get("path", ""),
                      args.get("want", "r") != "w")]
        elif op == "create":
            paths = [(args.get("path", ""), False)]
        elif op in ("setattr", "unlink"):
            paths = [(args.get("path", ""), False)]
        elif op == "rename":
            paths = [(args.get("src", ""), False),
                     (args.get("dst", ""), False)]
        elif op == "snap_create":
            # the freeze must see every holder's buffered size/mtime:
            # recall EXCL across the WHOLE subtree before the manifest
            # is frozen, or snapshot reads silently truncate acked
            # writes (ADVICE r5 #1)
            path = args.get("path", "")
            if path and ".snap" not in path.strip("/").split("/"):
                inos: list[int] = []
                async with self._mutation_lock:
                    try:
                        rec = await self._lookup(path)
                        if rec["type"] == "dir":
                            inos = await self._subtree_inos(rec["ino"])
                    except FSError:
                        inos = []
                for ino in inos:
                    if ino in self._cap_holders:
                        await self._recall(ino, except_conn=None,
                                           only_excl=True)
            return
        for path, only_excl in paths:
            # exact path-component test: only a literal ".snap"
            # component is a snapshot view — a file merely named e.g.
            # "dir/.snapshot" still needs cap coherence
            if not path or ".snap" in path.strip("/").split("/"):
                continue
            async with self._mutation_lock:
                try:
                    ino = (await self._lookup(path))["ino"]
                except FSError:
                    continue
            if ino in self._cap_holders:
                await self._recall(ino, except_conn=conn,
                                   only_excl=only_excl)

    async def _recall(self, ino: int, except_conn=None,
                      only_excl: bool = False) -> None:
        holders = self._cap_holders.get(ino)
        if not holders:
            return
        targets = [
            (c, bits) for c, bits in list(holders.items())
            if c is not except_conn
            and (bits & CAP_EXCL if only_excl else bits)
        ]
        loop = asyncio.get_running_loop()
        for conn, bits in targets:
            keep = (bits & ~CAP_EXCL) if only_excl else 0
            tid = next(self._cap_tids)
            fut: asyncio.Future = loop.create_future()
            self._cap_waiters[tid] = fut
            try:
                await conn.send_message(MClientCaps(
                    tid=tid, op=MClientCaps.REVOKE, ino=ino, caps=keep))
                await asyncio.wait_for(fut, 5.0)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                # dead or unresponsive session forfeits its caps (the
                # reference evicts after session autoclose)
                holders.pop(conn, None)
                continue
            finally:
                self._cap_waiters.pop(tid, None)
            if keep:
                holders[conn] = keep
            else:
                holders.pop(conn, None)
        if not holders:
            self._cap_holders.pop(ino, None)

    def _grant(self, ino: int, conn, bits: int) -> int:
        holders = self._cap_holders.setdefault(ino, {})
        cur = holders.get(conn, 0) | bits
        holders[conn] = cur
        return cur

    def _snapc(self) -> list:
        """[seq, snaps-newest-first] — the data pool's snap context."""
        ids = sorted(
            (s["id"] for realm in self._realms.values()
             for s in realm.values()), reverse=True)
        return [self._snap_seq, ids]

    async def _broadcast_snapc(self) -> None:
        seq, ids = self._snapc()
        for conn in list(self._sessions):
            try:
                await conn.send_message(MClientCaps(
                    op=MClientCaps.SNAPC, snap_seq=seq, snaps=ids))
            except (ConnectionError, OSError):
                self._sessions.discard(conn)

    # mutations --------------------------------------------------------

    async def _op_mkdir(self, path: str, mode: int = 0o755) -> dict:
        pino, name = await self._resolve_parent(path)
        d = await self._dir(pino)
        if name in d["entries"]:
            raise _err(errno.EEXIST, path)
        ino = self.ino_next
        self.ino_next += 1
        await self._journal_and_apply({
            "op": "mkdir", "p": pino, "n": name, "ino": ino,
            "mode": mode, "t": time.time(),
        })
        return {"ino": ino}

    async def _op_create(self, path: str, mode: int = 0o644,
                         layout: list | None = None) -> dict:
        pino, name = await self._resolve_parent(path)
        d = await self._dir(pino)
        rec = d["entries"].get(name)
        if rec is not None:
            if rec["type"] != "file":
                raise _err(errno.EISDIR, path)
            others = [
                c for c in self._cap_holders.get(rec["ino"], {})
                if c is not self._cur_conn
            ]
            bits = self._grant(
                rec["ino"], self._cur_conn,
                CAP_RD | CAP_WR | (0 if others else CAP_EXCL))
            return {"ino": rec["ino"], "size": rec["size"],
                    "layout": rec["layout"], "existed": True,
                    "caps": bits, "snapc": self._snapc()}
        ino = self.ino_next
        self.ino_next += 1
        lay = list(layout or DEFAULT_LAYOUT)
        await self._journal_and_apply({
            "op": "create", "p": pino, "n": name, "ino": ino,
            "mode": mode, "layout": lay, "t": time.time(),
        })
        bits = self._grant(ino, self._cur_conn,
                           CAP_RD | CAP_WR | CAP_EXCL)
        return {"ino": ino, "size": 0, "layout": lay, "existed": False,
                "caps": bits, "snapc": self._snapc()}

    async def _op_symlink(self, path: str, target: str) -> dict:
        pino, name = await self._resolve_parent(path)
        d = await self._dir(pino)
        if name in d["entries"]:
            raise _err(errno.EEXIST, path)
        ino = self.ino_next
        self.ino_next += 1
        await self._journal_and_apply({
            "op": "symlink", "p": pino, "n": name, "ino": ino,
            "target": target, "t": time.time(),
        })
        return {"ino": ino}

    async def _op_unlink(self, path: str) -> dict:
        pino, name = await self._resolve_parent(path)
        d = await self._dir(pino)
        rec = d["entries"].get(name)
        if rec is None:
            raise _err(errno.ENOENT, path)
        if rec["type"] == "dir":
            raise _err(errno.EISDIR, path)
        ev = {"op": "unlink", "p": pino, "n": name}
        if rec["type"] == "file":
            ev["purge"] = {"ino": rec["ino"], "size": rec["size"],
                           "layout": rec["layout"]}
        await self._journal_and_apply(ev)
        return {}

    async def _op_rmdir(self, path: str) -> dict:
        pino, name = await self._resolve_parent(path)
        d = await self._dir(pino)
        rec = d["entries"].get(name)
        if rec is None:
            raise _err(errno.ENOENT, path)
        if rec["type"] != "dir":
            raise _err(errno.ENOTDIR, path)
        child = await self._dir(rec["ino"])
        if child["entries"]:
            raise _err(errno.ENOTEMPTY, path)
        await self._journal_and_apply({
            "op": "rmdir", "p": pino, "n": name, "ino": rec["ino"],
        })
        return {}

    async def _op_rename(self, src: str, dst: str) -> dict:
        src_parts, dst_parts = self._split(src), self._split(dst)
        # POSIX rename(2): moving a directory into its own subtree
        # orphans it — EINVAL (paths are the namespace here, so a
        # prefix test is exact: no hardlinked dirs exist)
        if dst_parts[:len(src_parts)] == src_parts and src_parts:
            if len(dst_parts) > len(src_parts):
                raise _err(errno.EINVAL, "rename into own subtree")
        sp, sn = await self._resolve_parent(src)
        dp, dn = await self._resolve_parent(dst)
        sd = await self._dir(sp)
        rec = sd["entries"].get(sn)
        if rec is None:
            raise _err(errno.ENOENT, src)
        dd = await self._dir(dp)
        existing = dd["entries"].get(dn)
        ev = {"op": "rename", "sp": sp, "sn": sn, "dp": dp, "dn": dn}
        if existing is not None:
            if existing["ino"] == rec["ino"]:
                return {}
            if existing["type"] == "dir":
                if rec["type"] != "dir":
                    raise _err(errno.EISDIR, dst)
                if (await self._dir(existing["ino"]))["entries"]:
                    raise _err(errno.ENOTEMPTY, dst)
                ev["doom"] = existing["ino"]
            elif rec["type"] == "dir":
                raise _err(errno.ENOTDIR, dst)
            elif existing["type"] == "file":
                ev["purge"] = {"ino": existing["ino"],
                               "size": existing["size"],
                               "layout": existing["layout"]}
        await self._journal_and_apply(ev)
        return {}

    async def _op_setattr(self, path: str, size: int | None = None,
                          mtime: float | None = None,
                          mode: int | None = None) -> dict:
        pino, name = await self._resolve_parent(path)
        d = await self._dir(pino)
        rec = d["entries"].get(name)
        if rec is None:
            raise _err(errno.ENOENT, path)
        ev = {"op": "setattr", "p": pino, "n": name}
        if size is not None:
            if rec["type"] != "file":
                raise _err(errno.EINVAL, "size on non-file")
            if size < rec["size"]:
                # journal-first: _apply does the data truncation once
                # the event is durable
                ev["truncate"] = {"ino": rec["ino"], "size": rec["size"],
                                  "layout": rec["layout"]}
            ev["size"] = size
        if mtime is not None:
            ev["mtime"] = mtime
        if mode is not None:
            ev["mode"] = mode
        await self._journal_and_apply(ev)
        return {}

    async def _op_report_size(self, path: str, ino: int, size: int,
                              mtime: float | None = None) -> dict:
        """A writer's size report (the synchronous cousin of the cap
        FLUSH): only sessions holding a write cap on the ino may move
        its size — the MDS, not the client, is the size authority.
        Reports only EXTEND (shrinking goes through setattr/truncate,
        which the MDS executes itself)."""
        bits = self._cap_holders.get(ino, {}).get(self._cur_conn, 0)
        if not bits & (CAP_WR | CAP_EXCL):
            raise _err(errno.EPERM, "no write cap")
        pino, name = await self._resolve_parent(path)
        d = await self._dir(pino)
        rec = d["entries"].get(name)
        if rec is None or rec.get("ino") != ino:
            raise _err(errno.ENOENT, path)
        ev = {"op": "setattr", "p": pino, "n": name}
        if size > rec.get("size", 0):
            ev["size"] = size
        if mtime is not None:
            ev["mtime"] = mtime
        if len(ev) > 3:
            await self._journal_and_apply(ev)
        return {}

    async def _truncate_data(self, rec: dict, new_size: int) -> None:
        """Shrink: drop whole data objects past the end, trim the
        boundary object (Striper::truncate semantics, MDS-driven since
        v1 clients hold no caps)."""
        lay = Layout(*rec["layout"])
        live: dict[int, int] = {}
        if new_size > 0:
            for objectno, obj_off, n in file_to_extents(lay, 0, new_size):
                live[objectno] = max(live.get(objectno, 0), obj_off + n)
        for objectno, _o, _n in file_to_extents(lay, 0, max(rec["size"], 1)):
            oid = f"{rec['ino']:x}.{objectno:08x}"
            try:
                if objectno not in live:
                    await self.data_io.remove(oid)
                else:
                    await self.data_io.truncate(oid, live[objectno])
            except RadosError:
                pass

    # reads ------------------------------------------------------------

    async def _op_stat(self, path: str) -> dict:
        return {"attr": await self._lookup(path)}

    async def _op_open(self, path: str, want: str = "r") -> dict:
        snap = await self._snap_lookup(path)
        if snap is not None:
            if want == "w":
                raise _err(errno.EROFS, path)
            rec, snapid = snap
            if rec["type"] != "file":
                raise _err(errno.EISDIR, path)
            return {"ino": rec["ino"], "size": rec["size"],
                    "layout": rec["layout"], "snapid": snapid,
                    "caps": 0, "snapc": self._snapc()}
        rec = await self._lookup(path)
        if rec["type"] != "file":
            raise _err(errno.EISDIR, path)
        ino = rec["ino"]
        # grant (Locker::issue_caps): a lone writer gets EXCL and may
        # buffer size updates; _pre_recall already stripped conflicts
        others = [
            c for c in self._cap_holders.get(ino, {})
            if c is not self._cur_conn
        ]
        if want == "w":
            bits = CAP_RD | CAP_WR | (0 if others else CAP_EXCL)
        else:
            bits = CAP_RD
        bits = self._grant(ino, self._cur_conn, bits)
        return {"ino": ino, "size": rec["size"],
                "layout": rec["layout"], "caps": bits,
                "snapc": self._snapc()}

    async def _op_readdir(self, path: str) -> dict:
        parts = self._split(path)
        if parts and parts[-1] == ".snap":
            dino = await self._resolve_dir(parts[:-1])
            realm = self._realms.get(dino, {})
            return {"entries": {
                name: {"type": "dir", "ino": dino, "mtime": s["t"],
                       "mode": 0o755, "snapid": s["id"]}
                for name, s in sorted(realm.items())
            }}
        snap = await self._snap_lookup(path)
        if snap is not None:
            rec, _snapid = snap
            if rec["type"] != "dir":
                raise _err(errno.ENOTDIR, path)
            return {"entries": {
                name: {k: v for k, v in r.items() if k != "children"}
                for name, r in sorted(rec.get("children", {}).items())
            }}
        rec = await self._lookup(path)
        if rec["type"] != "dir":
            raise _err(errno.ENOTDIR, path)
        d = await self._dir(rec["ino"])
        return {"entries": {
            name: r for name, r in sorted(d["entries"].items())
        }}

    async def _op_readlink(self, path: str) -> dict:
        rec = await self._lookup(path)
        if rec["type"] != "symlink":
            raise _err(errno.EINVAL, path)
        return {"target": rec["target"]}

    # snapshots (SnapRealm-lite) ---------------------------------------

    async def _freeze(self, ino: int) -> dict:
        """Recursively serialize the subtree's metadata — the frozen
        past the reference keeps as snapid-versioned dentries."""
        d = await self._dir(ino)
        out = {}
        for name, rec in d["entries"].items():
            r = dict(rec)
            if rec["type"] == "dir":
                r["children"] = await self._freeze(rec["ino"])
            out[name] = r
        return out

    async def _subtree_inos(self, ino: int) -> list[int]:
        """Every file/dir ino under directory ``ino`` (recall scope of
        a snapshot freeze)."""
        out: list[int] = []
        d = await self._dir(ino)
        for rec in d["entries"].values():
            out.append(rec["ino"])
            if rec["type"] == "dir":
                out.extend(await self._subtree_inos(rec["ino"]))
        return out

    async def _op_snap_create(self, path: str, name: str) -> dict:
        import json

        if not name or "/" in name or name.startswith("."):
            raise _err(errno.EINVAL, f"bad snap name {name!r}")
        rec = await self._lookup(path)
        if rec["type"] != "dir":
            raise _err(errno.ENOTDIR, path)
        dino = rec["ino"]
        realm = self._realms.get(dino, {})
        if name in realm:
            raise _err(errno.EEXIST, name)
        # data-pool COW pivot first: writes stamped with the new snapc
        # clone; the manifest is written BEFORE the journal event so a
        # replayed snap_create always finds it (an orphan manifest
        # from a crash in between is harmless)
        snapid = await self.data_io.selfmanaged_snap_create()
        manifest = await self._freeze(dino)
        await self.meta_io.write_full(
            f"snapmeta.{dino:x}.{snapid}",
            json.dumps(manifest).encode())
        await self._journal_and_apply({
            "op": "snap_create", "ino": dino, "n": name,
            "snapid": snapid, "t": time.time(),
        })
        await self._broadcast_snapc()
        return {"snapid": snapid, "snapc": self._snapc()}

    async def _op_snap_remove(self, path: str, name: str) -> dict:
        rec = await self._lookup(path)
        if rec["type"] != "dir":
            raise _err(errno.ENOTDIR, path)
        snap = self._realms.get(rec["ino"], {}).get(name)
        if snap is None:
            raise _err(errno.ENOENT, name)
        await self._journal_and_apply({
            "op": "snap_remove", "ino": rec["ino"], "n": name,
            "snapid": snap["id"],
        })
        await self._broadcast_snapc()
        return {"snapc": self._snapc()}

    async def _op_flush(self) -> dict:
        """Admin/test verb: force writeback + journal trim."""
        await self._flush()
        return {}
