"""MDS metadata journal on RADOS — the Journaler twin.

The reference journals every metadata mutation into a striped RADOS
log before applying it to the in-memory cache (src/osdc/Journaler.cc:
a header object holding {trimmed_pos, expire_pos, write_pos} plus data
objects `<ino>.<objno>`), and replays it on MDS restart
(src/mds/journal.cc EMetaBlob::replay).

Lite shape, same contract: a header object (``<name>``) whose omap
carries {min_seg, next_seq, ino_next}; events append as JSON lines to
segment objects ``<name>.<seg>`` (rotated at ``seg_bytes``); replay
reads every live segment in order; checkpoint (after the dirty
dirfrags flush back) advances min_seg and deletes the old segments —
the LogSegment expiry dance.

Events must be idempotent under re-apply: a crash between the dirfrag
flush and the trim replays a prefix of already-applied events.
"""

from __future__ import annotations

import errno
import json

from ceph_tpu.client.rados import RadosError

HEADER_KEY = "journal.header"


class Journaler:
    def __init__(self, io, name: str = "mds0.journal",
                 seg_bytes: int = 4 * 2**20):
        self.io = io
        self.name = name
        self.seg_bytes = seg_bytes
        self.min_seg = 0       # first live segment
        self.cur_seg = 0       # segment appends go to
        self.next_seq = 1
        self._cur_size = 0

    def _seg_oid(self, seg: int) -> str:
        return f"{self.name}.{seg:08x}"

    async def load(self) -> tuple[dict, list[dict]]:
        """Read header + replay events.  Returns (header_state, events)
        where events is every record since the last checkpoint, in
        append order."""
        state: dict = {}
        try:
            got = await self.io.omap_get_vals_by_keys(self.name, [HEADER_KEY])
            raw = got.get(HEADER_KEY)
            if raw:
                state = json.loads(raw)
        except RadosError as e:
            if e.errno != errno.ENOENT:
                raise
        self.min_seg = state.get("min_seg", 0)
        events: list[dict] = []
        seg = self.min_seg
        while True:
            try:
                data = await self.io.read(self._seg_oid(seg))
            except RadosError as e:
                if e.errno == errno.ENOENT:
                    break
                raise
            self.cur_seg, self._cur_size = seg, len(data)
            for line in data.splitlines():
                if line.strip():
                    events.append(json.loads(line))
            seg += 1
        if events:
            self.next_seq = max(e["seq"] for e in events) + 1
        else:
            self.next_seq = state.get("next_seq", 1)
            self.cur_seg = max(self.cur_seg, self.min_seg)
        return state, events

    async def append(self, event: dict) -> int:
        """Durable append; returns the assigned seq.  The write rides
        the replicated meta pool's commit path, so when this returns
        the event survives an MDS crash."""
        event = dict(event)
        event["seq"] = self.next_seq
        self.next_seq += 1
        line = json.dumps(event).encode() + b"\n"
        if self._cur_size and self._cur_size + len(line) > self.seg_bytes:
            self.cur_seg += 1
            self._cur_size = 0
        await self.io.append(self._seg_oid(self.cur_seg), line)
        self._cur_size += len(line)
        return event["seq"]

    async def checkpoint(self, state: dict) -> None:
        """All events so far are reflected in the flushed dirfrags:
        persist the header and drop every old segment (LogSegment
        expiry + Journaler::trim)."""
        old_min = self.min_seg
        # appends continue into a fresh segment; everything before it
        # is dead weight once the header lands
        if self._cur_size:
            self.cur_seg += 1
            self._cur_size = 0
        self.min_seg = self.cur_seg
        hdr = dict(state)
        hdr["min_seg"] = self.min_seg
        hdr["next_seq"] = self.next_seq
        await self.io.omap_set(self.name, {
            HEADER_KEY: json.dumps(hdr).encode(),
        })
        for seg in range(old_min, self.min_seg):
            try:
                await self.io.remove(self._seg_oid(seg))
            except RadosError:
                pass
