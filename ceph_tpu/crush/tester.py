"""CrushTester: offline placement-quality analysis.

Behavioral twin of the reference's CrushTester
(src/crush/CrushTester.{h,cc}, driven by `crushtool --test`): simulate
placements for a range of inputs against one rule, and report
per-device utilization, expected-vs-actual deviation, and bad (short)
mappings.  The batch runs through the jit/vmap engine when the map
supports it — the whole x-range is one device program — with the
scalar interpreter as fallback.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.crush.jaxmapper import (
    BatchedRuleMapper,
    UnsupportedMap,
    compile_map,
)
from ceph_tpu.crush.mapper import crush_do_rule
from ceph_tpu.crush.types import CRUSH_ITEM_NONE, CrushMap


@dataclass
class TestResult:
    rule: int
    num_rep: int
    total_mappings: int
    bad_mappings: list[int] = field(default_factory=list)
    device_counts: dict[int, int] = field(default_factory=dict)
    mappings: dict[int, list[int]] = field(default_factory=dict)

    @property
    def expected_per_device(self) -> float:
        used = len(self.device_counts)
        return (self.total_mappings * self.num_rep / used) if used else 0.0

    def statistics(self) -> dict:
        counts = np.array(sorted(self.device_counts.values())) if self.device_counts else np.zeros(0)
        return {
            "rule": self.rule,
            "num_rep": self.num_rep,
            "mappings": self.total_mappings,
            "bad_mappings": len(self.bad_mappings),
            "devices_used": len(self.device_counts),
            "expected_per_device": round(self.expected_per_device, 2),
            "min": int(counts.min()) if counts.size else 0,
            "max": int(counts.max()) if counts.size else 0,
            "stddev": round(float(counts.std()), 2) if counts.size else 0.0,
        }


class CrushTester:
    def __init__(self, crush: CrushMap):
        self.crush = crush

    def test(
        self,
        rule: int,
        num_rep: int,
        min_x: int = 0,
        max_x: int = 1023,
        weights: list[int] | None = None,
        keep_mappings: bool = False,
    ) -> TestResult:
        """CrushTester::test (CrushTester.h:351): place x in
        [min_x, max_x], collect stats; a mapping shorter than num_rep
        (or with holes) is 'bad' (--show-bad-mappings semantics)."""
        xs = np.arange(min_x, max_x + 1, dtype=np.uint32)
        res = TestResult(rule=rule, num_rep=num_rep, total_mappings=len(xs))
        rows: list[list[int]] = []
        try:
            cc = compile_map(self.crush)
            bm = BatchedRuleMapper(cc, rule, num_rep)
            vals, cnt = bm(xs, weights)
            for i in range(len(xs)):
                rows.append([int(v) for v in vals[i, : cnt[i]]])
        except (UnsupportedMap, KeyError):
            for x in xs:
                rows.append(
                    crush_do_rule(self.crush, rule, int(x), num_rep, weights)
                )
        for x, row in zip(xs, rows):
            devices = [o for o in row if o != CRUSH_ITEM_NONE]
            if len(devices) < num_rep:
                res.bad_mappings.append(int(x))
            for o in devices:
                res.device_counts[o] = res.device_counts.get(o, 0) + 1
            if keep_mappings:
                res.mappings[int(x)] = row
        return res
