"""Scalar CRUSH placement interpreter — the behavioral oracle twin.

Bit-identical re-implementation of the reference placement function
(src/crush/mapper.c): straw2 exponential-minimum draws over the fixed
point crush_ln (mapper.c:229-271,342-365), the firstn rejection-retry
descent (mapper.c:441-629), the positionally-stable indep variant
(mapper.c:636-824) and the rule-step interpreter
(crush_do_rule_no_retry, mapper.c:826-1032), including the uniform
bucket's cached permutation (bucket_perm_choose, mapper.c:54-119) and
the legacy list/tree/straw bucket algorithms.

This scalar version is the reference oracle for the batched JAX engine
(ceph_tpu/crush/jaxmapper.py) and serves small/one-off lookups on the
host control plane; golden vectors generated from the reference's own C
pin it down (tests/test_crush_golden.py).
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.crush._ln_tables import LL_TBL, RH_LH_TBL
from ceph_tpu.crush.types import (
    RULE_TYPE_MSR_FIRSTN,
    RULE_TYPE_MSR_INDEP,
    CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF,
    Bucket,
    BucketAlg,
    ChooseArg,
    CrushMap,
    Rule,
    RuleOp,
)
from ceph_tpu.ops.hashing import (
    crush_hash32_2,
    crush_hash32_3,
    crush_hash32_4,
)

S64_MIN = -(2 ** 63)


def crush_ln(xin: int) -> int:
    """2^44 * log2(xin + 1), fixed point (mapper.c:229-271)."""
    x = (xin + 1) & 0xFFFFFFFF
    iexpon = 15
    if not (x & 0x18000):
        # __builtin_clz(x & 0x1FFFF) - 16  ==  16 - bit_length
        bits = 16 - int(x & 0x1FFFF).bit_length()
        x = (x << bits) & 0xFFFFFFFF
        iexpon = 15 - bits
    index1 = (x >> 8) << 1
    RH = int(RH_LH_TBL[index1 - 256])
    LH = int(RH_LH_TBL[index1 + 1 - 256])
    xl64 = (x * RH) >> 48
    result = iexpon << 44
    index2 = xl64 & 0xFF
    LL = int(LL_TBL[index2])
    LH = LH + LL
    LH >>= (48 - 12 - 32)
    return result + LH


def _div64(a: int, b: int) -> int:
    """C-style truncating signed 64-bit division."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


def straw2_draw(hash_alg: int, x: int, item_id: int, r: int, weight: int) -> int:
    """generate_exponential_distribution (mapper.c:315-340)."""
    u = int(crush_hash32_3(x, item_id, r)) & 0xFFFF
    ln = crush_ln(u) - 0x1000000000000
    return _div64(ln, weight)


class _Work:
    """Per-lookup scratch: the uniform-bucket permutation cache
    (struct crush_work_bucket, mapper.c:54-112)."""

    def __init__(self) -> None:
        self.perm_x: dict[int, int] = {}
        self.perm_n: dict[int, int] = {}
        self.perm: dict[int, list[int]] = {}


def _choose_arg_weights(bucket: Bucket, arg: ChooseArg | None, position: int) -> list[int]:
    if arg is None or arg.weight_set is None:
        return bucket.item_weights
    if position >= len(arg.weight_set):
        position = len(arg.weight_set) - 1
    return arg.weight_set[position]


def _choose_arg_ids(bucket: Bucket, arg: ChooseArg | None) -> list[int]:
    if arg is None or arg.ids is None:
        return bucket.items
    return arg.ids


_STRAW2_NATIVE = None
_STRAW2_PROBED = False


def _straw2_native():
    """The native straw2 choose (ceph_tpu/native/crush_hash.cc) or
    None; probed once.  Moves the per-item hash+ln+div+argmax loop to
    one C call per bucket level — the Python loop costs ~25us/item,
    which stalls daemon event loops on per-PG mapping (bench cfg 5)."""
    global _STRAW2_NATIVE, _STRAW2_PROBED
    if not _STRAW2_PROBED:
        _STRAW2_PROBED = True
        try:
            from ceph_tpu import native

            _STRAW2_NATIVE = native.straw2_lib()
        except Exception:
            _STRAW2_NATIVE = None
    return _STRAW2_NATIVE


def bucket_straw2_choose(
    bucket: Bucket, x: int, r: int, arg: ChooseArg | None, position: int
) -> int:
    weights = _choose_arg_weights(bucket, arg, position)
    ids = _choose_arg_ids(bucket, arg)
    n = bucket.size
    lib = _straw2_native()
    if lib is not None and n:
        ids_a = np.asarray(ids[:n], dtype=np.int32)
        w_a = np.asarray(weights[:n], dtype=np.uint32)
        i = lib.ceph_tpu_straw2_choose(
            x & 0xFFFFFFFF, r & 0xFFFFFFFF,
            ids_a.ctypes.data, w_a.ctypes.data, n)
        return bucket.items[i]
    high = 0
    high_draw = 0
    for i in range(n):
        if weights[i]:
            draw = straw2_draw(bucket.hash, x, ids[i], r, weights[i])
        else:
            draw = S64_MIN
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def bucket_perm_choose(bucket: Bucket, work: _Work, x: int, r: int) -> int:
    """Pseudo-random permutation choose for uniform buckets
    (mapper.c:54-112), including the cached-permutation and the magic
    0xffff first-slot fast path."""
    bid = bucket.id
    pr = r % bucket.size
    if work.perm_x.get(bid) != x or work.perm_n.get(bid, 0) == 0:
        work.perm_x[bid] = x
        if pr == 0:
            s = int(crush_hash32_3(x, bid, 0)) % bucket.size
            work.perm[bid] = [s] + [0] * (bucket.size - 1)
            work.perm_n[bid] = 0xFFFF
            return bucket.items[s]
        work.perm[bid] = list(range(bucket.size))
        work.perm_n[bid] = 0
    elif work.perm_n[bid] == 0xFFFF:
        p = work.perm[bid]
        for i in range(1, bucket.size):
            p[i] = i
        p[p[0]] = 0
        work.perm_n[bid] = 1
    perm = work.perm[bid]
    while work.perm_n[bid] <= pr:
        p = work.perm_n[bid]
        if p < bucket.size - 1:
            i = int(crush_hash32_3(x, bid, p)) % (bucket.size - p)
            if i:
                perm[p + i], perm[p] = perm[p], perm[p + i]
        work.perm_n[bid] += 1
    return bucket.items[perm[pr]]


def bucket_list_choose(bucket: Bucket, x: int, r: int) -> int:
    for i in range(bucket.size - 1, -1, -1):
        w = int(crush_hash32_4(x, bucket.items[i], r, bucket.id)) & 0xFFFF
        w = (w * bucket.sum_weights[i]) >> 16
        if w < bucket.item_weights[i]:
            return bucket.items[i]
    return bucket.items[0]


def bucket_tree_choose(bucket: Bucket, x: int, r: int) -> int:
    n = len(bucket.node_weights) >> 1
    while not (n & 1):
        w = bucket.node_weights[n]
        t = (int(crush_hash32_4(x, n, r, bucket.id)) * w) >> 32
        h = 0
        nn = n
        while (nn & 1) == 0:
            h += 1
            nn >>= 1
        left = n - (1 << (h - 1))
        n = left if t < bucket.node_weights[left] else n + (1 << (h - 1))
    return bucket.items[n >> 1]


def bucket_straw_choose(bucket: Bucket, x: int, r: int) -> int:
    high = 0
    high_draw = -1
    for i in range(bucket.size):
        draw = (int(crush_hash32_3(x, bucket.items[i], r)) & 0xFFFF) * bucket.straws[i]
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def crush_bucket_choose(
    bucket: Bucket, work: _Work, x: int, r: int, arg: ChooseArg | None, position: int
) -> int:
    if bucket.size == 0:
        raise ValueError("empty bucket")
    if bucket.alg == BucketAlg.STRAW2:
        return bucket_straw2_choose(bucket, x, r, arg, position)
    if bucket.alg == BucketAlg.UNIFORM:
        return bucket_perm_choose(bucket, work, x, r)
    if bucket.alg == BucketAlg.LIST:
        return bucket_list_choose(bucket, x, r)
    if bucket.alg == BucketAlg.TREE:
        return bucket_tree_choose(bucket, x, r)
    if bucket.alg == BucketAlg.STRAW:
        return bucket_straw_choose(bucket, x, r)
    return bucket.items[0]


def is_out(map_: CrushMap, weights: list[int], item: int, x: int) -> bool:
    """Device overload rejection (mapper.c:405-419); ``weights`` is the
    OSD reweight vector (16.16), distinct from CRUSH weights."""
    if item >= len(weights):
        return True
    w = weights[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    return (int(crush_hash32_2(x, item)) & 0xFFFF) >= w


def _choose_firstn(
    map_: CrushMap, work: _Work, bucket: Bucket, weights: list[int],
    x: int, numrep: int, type_: int, out: list[int], outpos: int,
    out_size: int, tries: int, recurse_tries: int, local_retries: int,
    local_fallback_retries: int, recurse_to_leaf: bool, vary_r: int,
    stable: int, out2: list[int] | None, parent_r: int,
    choose_args: dict[int, ChooseArg] | None,
) -> int:
    """crush_choose_firstn (mapper.c:441-629)."""
    count = out_size
    rep = 0 if stable else outpos
    while rep < numrep and count > 0:
        ftotal = 0
        skip_rep = False
        retry_descent = True
        while retry_descent:
            retry_descent = False
            in_ = bucket
            flocal = 0
            retry_bucket = True
            while retry_bucket:
                retry_bucket = False
                r = rep + parent_r + ftotal
                if in_.size == 0:
                    reject = True
                    collide = False
                    item = 0
                else:
                    if (local_fallback_retries > 0
                            and flocal >= (in_.size >> 1)
                            and flocal > local_fallback_retries):
                        item = bucket_perm_choose(in_, work, x, r)
                    else:
                        arg = (choose_args or {}).get(in_.id)
                        item = crush_bucket_choose(in_, work, x, r, arg, outpos)
                    if item >= map_.max_devices:
                        skip_rep = True
                        break
                    known = item >= 0 or item in map_.buckets
                    itemtype = map_.buckets[item].type if (item < 0 and known) else 0
                    if not known or itemtype != type_:
                        if item >= 0 or not known:
                            skip_rep = True
                            break
                        in_ = map_.buckets[item]
                        retry_bucket = True
                        continue
                    collide = any(out[i] == item for i in range(outpos))
                    reject = False
                    if not collide and recurse_to_leaf:
                        if item < 0:
                            sub_r = r >> (vary_r - 1) if vary_r else 0
                            if _choose_firstn(
                                map_, work, map_.buckets[item], weights, x,
                                1 if stable else outpos + 1, 0,
                                out2, outpos, count,
                                recurse_tries, 0,
                                local_retries, local_fallback_retries,
                                False, vary_r, stable, None, sub_r,
                                choose_args,
                            ) <= outpos:
                                reject = True
                        else:
                            out2[outpos] = item
                    if not reject and not collide and itemtype == 0:
                        reject = is_out(map_, weights, item, x)
                if reject or collide:
                    ftotal += 1
                    flocal += 1
                    if collide and flocal <= local_retries:
                        retry_bucket = True
                    elif (local_fallback_retries > 0
                          and flocal <= in_.size + local_fallback_retries):
                        retry_bucket = True
                    elif ftotal < tries:
                        retry_descent = True
                    else:
                        skip_rep = True
        if not skip_rep:
            out[outpos] = item
            outpos += 1
            count -= 1
        rep += 1
    return outpos


def _choose_indep(
    map_: CrushMap, work: _Work, bucket: Bucket, weights: list[int],
    x: int, left: int, numrep: int, type_: int, out: list[int],
    outpos: int, tries: int, recurse_tries: int, recurse_to_leaf: bool,
    out2: list[int] | None, parent_r: int,
    choose_args: dict[int, ChooseArg] | None,
) -> None:
    """crush_choose_indep (mapper.c:636-824): breadth-first positionally
    stable selection used by erasure-coded pools."""
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[rep] = CRUSH_ITEM_UNDEF
        if out2 is not None:
            out2[rep] = CRUSH_ITEM_UNDEF
    ftotal = 0
    while left > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] != CRUSH_ITEM_UNDEF:
                continue
            in_ = bucket
            while True:
                r = rep + parent_r
                if (in_.alg == BucketAlg.UNIFORM
                        and in_.size % numrep == 0):
                    r += (numrep + 1) * ftotal
                else:
                    r += numrep * ftotal
                if in_.size == 0:
                    break
                arg = (choose_args or {}).get(in_.id)
                item = crush_bucket_choose(in_, work, x, r, arg, outpos)
                if item >= map_.max_devices:
                    out[rep] = CRUSH_ITEM_NONE
                    if out2 is not None:
                        out2[rep] = CRUSH_ITEM_NONE
                    left -= 1
                    break
                known = item >= 0 or item in map_.buckets
                itemtype = map_.buckets[item].type if (item < 0 and known) else 0
                if not known or itemtype != type_:
                    if item >= 0 or not known:
                        out[rep] = CRUSH_ITEM_NONE
                        if out2 is not None:
                            out2[rep] = CRUSH_ITEM_NONE
                        left -= 1
                        break
                    in_ = map_.buckets[item]
                    continue
                if any(out[i] == item for i in range(outpos, endpos)):
                    break
                if recurse_to_leaf:
                    if item < 0:
                        _choose_indep(
                            map_, work, map_.buckets[item], weights, x,
                            1, numrep, 0, out2, rep, recurse_tries, 0,
                            False, None, r, choose_args,
                        )
                        if out2 is not None and out2[rep] == CRUSH_ITEM_NONE:
                            break
                    elif out2 is not None:
                        out2[rep] = item
                if itemtype == 0 and is_out(map_, weights, item, x):
                    break
                out[rep] = item
                left -= 1
                break
        ftotal += 1
    for rep in range(outpos, endpos):
        if out[rep] == CRUSH_ITEM_UNDEF:
            out[rep] = CRUSH_ITEM_NONE
        if out2 is not None and out2[rep] == CRUSH_ITEM_UNDEF:
            out2[rep] = CRUSH_ITEM_NONE


# ---------------------------------------------------------------------------
# MSR (multi-step-retry) rules — crush_msr_do_rule (mapper.c:1723-1930)
# ---------------------------------------------------------------------------
#
# msr_firstn / msr_indep rules retry the WHOLE descent when a leaf is
# rejected, so marking an OSD out can remap to a different failure
# domain even when the rule places several OSDs per domain (wide EC on
# small clusters — mapper.c:1633-1720 commentary).  Statement-level
# transliteration like the classic interpreter above: bit-identical
# placements are pinned by golden vectors compiled from the reference C
# (tools/golden/crush_oracle.c).

def _msr_scan_config_steps(rule: Rule) -> tuple[int, int | None, int | None]:
    """mapper.c:1088 — returns (next stepno, descents, collision_tries)."""
    descents = tries = None
    for stepno, step in enumerate(rule.steps):
        if step.op == RuleOp.SET_MSR_DESCENTS:
            descents = step.arg1
        elif step.op == RuleOp.SET_MSR_COLLISION_TRIES:
            tries = step.arg1
        else:
            return stepno, descents, tries
    return len(rule.steps), descents, tries


def _msr_scan_next(
    rule: Rule, result_max: int, stepno: int
) -> tuple[int, int] | None:
    """mapper.c:1139 — (total_children, emit stepno) or None (invalid)."""
    if stepno + 1 >= len(rule.steps):
        return None
    if rule.steps[stepno].op != RuleOp.TAKE:
        return None
    stepno += 1
    total_children = 1
    while stepno < len(rule.steps):
        step = rule.steps[stepno]
        if step.op == RuleOp.EMIT:
            break
        if step.op != RuleOp.CHOOSE_MSR:
            return None
        total_children *= step.arg1 if step.arg1 else result_max
        stepno += 1
    if stepno >= len(rule.steps):
        return None
    return total_children, stepno


def _msr_retry_value(
    result_max: int, index: int, tryno: int, local_tryno: int
) -> int:
    """mapper.c:1249 crush_msr_get_retry_value."""
    return (((tryno * result_max) + index) << 16) + local_tryno


def _msr_descend(
    map_: CrushMap, work: _Work, bucket: Bucket, type_: int,
    x: int, result_max: int, tryno: int, local_tryno: int, index: int,
    choose_args: dict[int, ChooseArg] | None,
) -> int | None:
    """mapper.c:1274 — descend until a device or a bucket of type_.

    Returns None on a map-integrity failure (empty bucket, dangling
    child id, out-of-range device) — the classic interpreter's bad-item
    guards (mapper.c reject paths); the caller treats it as a collision
    and retries."""
    while True:
        if bucket.size == 0:
            return None
        arg = (choose_args or {}).get(bucket.id)
        candidate = crush_bucket_choose(
            bucket, work, x,
            _msr_retry_value(result_max, index, tryno, local_tryno),
            arg, index,
        )
        if candidate >= 0:
            if candidate >= map_.max_devices:
                return None  # dangling device id
            return candidate
        nxt = map_.buckets.get(candidate)
        if nxt is None:
            return None  # dangling child bucket id
        bucket = nxt
        if bucket.type == type_:
            return bucket.id


def _msr_valid_candidate(
    vec: list[int],
    exclude_start: int, exclude_end: int,
    include_start: int, include_end: int,
    candidate: int,
) -> bool:
    """mapper.c:1331 — already-in-stride ok; used by another stride no."""
    for i in range(exclude_start, exclude_end):
        if vec[i] == candidate:
            return include_start <= i < include_end
    return True


def _msr_push_used(
    vec: list[int], stride_start: int, stride_end: int, candidate: int
) -> bool:
    """mapper.c:1388."""
    for i in range(stride_start, stride_end):
        if vec[i] == candidate:
            return False
        if vec[i] == CRUSH_ITEM_UNDEF:
            vec[i] = candidate
            return True
    raise AssertionError("impossible")


def _msr_pop_used(
    vec: list[int], stride_start: int, stride_end: int, candidate: int
) -> None:
    """mapper.c:1425."""
    for i in range(stride_end - 1, stride_start - 1, -1):
        if vec[i] != CRUSH_ITEM_UNDEF:
            assert vec[i] == candidate
            vec[i] = CRUSH_ITEM_UNDEF
            return
    raise AssertionError("impossible")


class _MsrOutput:
    """mapper.c:1067 crush_msr_output."""

    def __init__(self, result_max: int):
        self.out = [CRUSH_ITEM_NONE] * result_max
        self.returned_so_far = 0

    def emit(self, rule_type: int, position: int, result: int) -> None:
        if rule_type == RULE_TYPE_MSR_FIRSTN:
            self.out[self.returned_so_far] = result
            self.returned_so_far += 1
        else:
            self.out[position] = result
            self.returned_so_far += 1


def _msr_choose(
    map_: CrushMap, rule: Rule, work: _Work, step_vecs: list[list[int]],
    output: _MsrOutput, bucket: Bucket, total_descendants: int,
    start_index: int, end_index: int,
    current_stepno: int, start_stepno: int, end_stepno: int,
    tryno: int, x: int, result_max: int, weights: list[int],
    collision_tries: int, choose_args: dict[int, ChooseArg] | None,
) -> int:
    """mapper.c:1507 crush_msr_choose — one descent pass for one
    CHOOSE_MSR step over its strides."""
    curstep = rule.steps[current_stepno]
    assert curstep.op == RuleOp.CHOOSE_MSR
    num_strides = curstep.arg1 if curstep.arg1 else result_max
    assert total_descendants % num_strides == 0
    stride_length = total_descendants // num_strides
    vec = step_vecs[current_stepno - start_stepno]
    leaf_vec = step_vecs[end_stepno - start_stepno - 1]

    undo = [CRUSH_ITEM_UNDEF] * num_strides
    mapped = 0
    stride_index = 0
    stride_start = start_index
    while stride_start < end_index:
        stride_end = min(stride_start + stride_length, end_index)
        if all(
            leaf_vec[i] != CRUSH_ITEM_UNDEF
            for i in range(stride_start, stride_end)
        ):
            stride_start += stride_length
            stride_index += 1
            continue
        found = False
        candidate = 0
        for local_tryno in range(collision_tries):
            candidate = _msr_descend(
                map_, work, bucket, curstep.arg2, x, result_max,
                tryno, local_tryno, stride_index, choose_args,
            )
            if candidate is None:
                continue  # map-integrity reject: retry like a collision
            if _msr_valid_candidate(
                vec, start_index, end_index,
                stride_start, stride_end, candidate,
            ):
                found = True
                break
        if not found:
            stride_start += stride_length
            stride_index += 1
            continue
        if curstep.arg2 == 0:  # leaf step
            if stride_length != 1 or current_stepno + 1 != end_stepno:
                pass  # malformed rule: skip stride
            elif is_out(map_, weights, candidate, x):
                pass  # crush_msr_do_rule retries, msr_descents permitting
            else:
                pushed = _msr_push_used(
                    vec, stride_start, stride_end, candidate)
                assert pushed
                output.emit(rule.rule_type, stride_start, candidate)
                mapped += 1
        else:  # interior step
            if current_stepno + 1 >= end_stepno or candidate >= 0:
                pass  # malformed rule / device where an interior type
                      # was requested: skip the stride
            else:
                child_bucket = map_.buckets[candidate]
                child_mapped = _msr_choose(
                    map_, rule, work, step_vecs, output, child_bucket,
                    stride_length, stride_start, stride_end,
                    current_stepno + 1, start_stepno, end_stepno,
                    tryno, x, result_max, weights, collision_tries,
                    choose_args,
                )
                pushed = _msr_push_used(
                    vec, stride_start, stride_end, candidate)
                if pushed and child_mapped == 0:
                    undo[stride_index] = candidate
                else:
                    mapped += child_mapped
        stride_start += stride_length
        stride_index += 1

    stride_index = 0
    stride_start = start_index
    while stride_start < end_index:
        if undo[stride_index] != CRUSH_ITEM_UNDEF:
            stride_end = min(stride_start + stride_length, end_index)
            _msr_pop_used(
                vec, stride_start, stride_end, undo[stride_index])
        stride_start += stride_length
        stride_index += 1
    return mapped


def _msr_do_rule(
    map_: CrushMap, rule: Rule, x: int, result_max: int,
    weights: list[int], choose_args: dict[int, ChooseArg] | None,
) -> list[int]:
    """mapper.c:1809 crush_msr_do_rule."""
    t = map_.tunables
    start_stepno, descents, collision_tries = _msr_scan_config_steps(rule)
    if descents is None:
        descents = t.msr_descents
    if collision_tries is None:
        collision_tries = t.msr_collision_tries

    work = _Work()
    output = _MsrOutput(result_max)
    start_index = 0
    while start_stepno < len(rule.steps):
        scan = _msr_scan_next(rule, result_max, start_stepno)
        if scan is None:
            return []  # invalid rule: "return whatever we have" (= none)
        total_children, emit_stepno = scan
        take_step = rule.steps[start_stepno]
        assert take_step.op == RuleOp.TAKE
        if take_step.arg1 >= 0:
            if start_stepno + 1 != emit_stepno:
                return []
            output.emit(rule.rule_type, start_index, take_step.arg1)
        else:
            root_bucket = map_.buckets[take_step.arg1]
            start_stepno += 1
            n_steps = emit_stepno - start_stepno
            step_vecs = [
                [CRUSH_ITEM_UNDEF] * result_max for _ in range(n_steps)
            ]
            end_index = min(start_index + total_children, result_max)
            return_limit = output.returned_so_far + (end_index - start_index)
            tries_so_far = 0
            while (tries_so_far < descents
                   and output.returned_so_far < return_limit):
                _msr_choose(
                    map_, rule, work, step_vecs, output, root_bucket,
                    total_children, start_index, end_index,
                    start_stepno, start_stepno, emit_stepno,
                    tries_so_far, x, result_max, weights,
                    collision_tries, choose_args,
                )
                tries_so_far += 1
            start_index = end_index
        start_stepno = emit_stepno + 1

    if rule.rule_type == RULE_TYPE_MSR_FIRSTN:
        return output.out[: output.returned_so_far]
    return output.out


def crush_do_rule(
    map_: CrushMap,
    ruleno: int,
    x: int,
    result_max: int,
    weights: list[int] | None = None,
    choose_args: dict[int, ChooseArg] | None = None,
) -> list[int]:
    """crush_do_rule_no_retry (mapper.c:826-1032).

    ``weights`` is the OSD reweight vector (16.16; defaults to all-in).
    Returns the raw result vector (may contain CRUSH_ITEM_NONE holes for
    indep rules).
    """
    if ruleno not in map_.rules:
        return []
    rule = map_.rules[ruleno]
    if weights is None:
        weights = [0x10000] * map_.max_devices
    if rule.device_class is not None:
        # class-restricted rule: OSDs of other classes get weight 0,
        # which is_out() rejects — selecting exactly the same OSD set
        # the reference reaches via per-class shadow hierarchies
        # (CrushWrapper::populate_classes); draw order may differ from
        # the shadow-tree draw, which is fine for a from-scratch map.
        weights = [
            w if map_.device_classes.get(osd) == rule.device_class else 0
            for osd, w in enumerate(weights)
        ]
    if rule.rule_type in (RULE_TYPE_MSR_FIRSTN, RULE_TYPE_MSR_INDEP):
        return _msr_do_rule(
            map_, rule, x, result_max, weights, choose_args)

    t = map_.tunables
    work = _Work()

    choose_tries = t.choose_total_tries + 1
    choose_leaf_tries = 0
    choose_local_retries = t.choose_local_tries
    choose_local_fallback_retries = t.choose_local_fallback_tries
    vary_r = t.chooseleaf_vary_r
    stable = t.chooseleaf_stable

    result: list[int] = []
    w: list[int] = []
    for step in rule.steps:
        op = step.op
        if op == RuleOp.TAKE:
            if (0 <= step.arg1 < map_.max_devices) or step.arg1 in map_.buckets:
                w = [step.arg1]
        elif op == RuleOp.SET_CHOOSE_TRIES:
            if step.arg1 > 0:
                choose_tries = step.arg1
        elif op == RuleOp.SET_CHOOSELEAF_TRIES:
            if step.arg1 > 0:
                choose_leaf_tries = step.arg1
        elif op == RuleOp.SET_CHOOSE_LOCAL_TRIES:
            if step.arg1 >= 0:
                choose_local_retries = step.arg1
        elif op == RuleOp.SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if step.arg1 >= 0:
                choose_local_fallback_retries = step.arg1
        elif op == RuleOp.SET_CHOOSELEAF_VARY_R:
            if step.arg1 >= 0:
                vary_r = step.arg1
        elif op == RuleOp.SET_CHOOSELEAF_STABLE:
            if step.arg1 >= 0:
                stable = step.arg1
        elif op in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSELEAF_FIRSTN,
                    RuleOp.CHOOSE_INDEP, RuleOp.CHOOSELEAF_INDEP):
            if not w:
                continue
            firstn = op in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSELEAF_FIRSTN)
            recurse_to_leaf = op in (RuleOp.CHOOSELEAF_FIRSTN, RuleOp.CHOOSELEAF_INDEP)
            # the reference hands each input bucket an *offset* output
            # window (o+osize with j=0, mapper.c:970,992): r-values,
            # collision scans and choose_args positions are all relative
            # to the window, so model it with per-bucket slices
            o: list[int] = []
            c: list[int] = []
            for wi in w:
                numrep = step.arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                if wi >= 0 or wi not in map_.buckets:
                    continue
                bucket = map_.buckets[wi]
                avail = result_max - len(o)
                o_i = [0] * avail
                c_i = [0] * avail
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif t.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    n_i = _choose_firstn(
                        map_, work, bucket, weights, x, numrep, step.arg2,
                        o_i, 0, avail, choose_tries,
                        recurse_tries, choose_local_retries,
                        choose_local_fallback_retries, recurse_to_leaf,
                        vary_r, stable, c_i, 0, choose_args,
                    )
                else:
                    n_i = min(numrep, avail)
                    _choose_indep(
                        map_, work, bucket, weights, x, n_i, numrep,
                        step.arg2, o_i, 0, choose_tries,
                        choose_leaf_tries if choose_leaf_tries else 1,
                        recurse_to_leaf, c_i, 0, choose_args,
                    )
                o.extend(o_i[:n_i])
                c.extend(c_i[:n_i])
            w = c if recurse_to_leaf else o
        elif op == RuleOp.EMIT:
            result.extend(w[: result_max - len(result)])
            w = []
    return result
