"""CRUSH map construction — the CrushWrapper/builder analogue.

Covers the mutation surface the control plane needs (reference:
src/crush/builder.c, src/crush/CrushWrapper.cc): bucket creation
(straw2/uniform/list; tree with its heap-array weights), hierarchy
assembly, device reweighting, and the two standard rule shapes —
replicated chooseleaf-firstn (CrushWrapper::add_simple_rule) and the
erasure indep rule created for EC profiles
(ErasureCode::create_rule -> add_simple_rule(..., "indep", ...),
reference src/erasure-code/ErasureCode.cc:70-102).
"""

from __future__ import annotations

from ceph_tpu.crush.types import (
    RULE_TYPE_MSR_INDEP,
    Bucket,
    BucketAlg,
    CrushMap,
    Rule,
    RuleOp,
    RuleStep,
)


def make_bucket(
    map_: CrushMap,
    alg: BucketAlg,
    type_: int,
    items: list[int],
    weights: list[int],
    bucket_id: int | None = None,
) -> Bucket:
    """Create and add a bucket; derives the per-alg auxiliary arrays
    (list prefix sums, tree heap weights)."""
    if bucket_id is None:
        bucket_id = min(map_.buckets.keys(), default=0) - 1
    assert bucket_id < 0 and bucket_id not in map_.buckets
    b = Bucket(id=bucket_id, type=type_, alg=alg,
               items=list(items), item_weights=list(weights))
    if alg == BucketAlg.LIST:
        total = 0
        b.sum_weights = []
        for w in weights:
            total += w
            b.sum_weights.append(total)
    elif alg == BucketAlg.TREE:
        b.node_weights = _tree_node_weights(items, weights)
    elif alg == BucketAlg.UNIFORM:
        # uniform buckets carry one weight for all items
        if weights:
            b.item_weights = [weights[0]] * len(items)
    map_.buckets[bucket_id] = b
    for it in items:
        if it >= 0:
            map_.max_devices = max(map_.max_devices, it + 1)
    return b


def _tree_node_weights(items: list[int], weights: list[int]) -> list[int]:
    """Binary-heap node weights for tree buckets (builder.c
    crush_make_tree_bucket layout: leaves at odd indices)."""
    n = len(items)
    depth = max(1, (n - 1).bit_length() + 1) if n > 1 else 1
    num_nodes = 1 << depth
    node_weights = [0] * num_nodes
    for j, w in enumerate(weights):
        node_weights[(j << 1) + 1] = w

    # interior sums level by level (a node with h trailing zero bits has
    # height h; children sit +/- 2^(h-1))
    for h in range(1, depth + 1):
        for node in range(1 << h, num_nodes, 1 << (h + 1)):
            left = node - (1 << (h - 1))
            right = node + (1 << (h - 1))
            node_weights[node] = node_weights[left] + (
                node_weights[right] if right < num_nodes else 0
            )
    return node_weights


def build_hierarchy(
    map_: CrushMap,
    osds_per_host: int,
    n_hosts: int,
    osd_weight: int = 0x10000,
    alg: BucketAlg = BucketAlg.STRAW2,
    host_type: int = 1,
    root_type: int = 10,
) -> Bucket:
    """Standard root -> host -> osd tree; returns the root bucket."""
    host_ids = []
    host_weights = []
    for h in range(n_hosts):
        osds = list(range(h * osds_per_host, (h + 1) * osds_per_host))
        hb = make_bucket(map_, alg, host_type, osds, [osd_weight] * osds_per_host)
        map_.bucket_names.setdefault(f"host{h}", hb.id)
        host_ids.append(hb.id)
        host_weights.append(hb.weight)
    root = make_bucket(map_, alg, root_type, host_ids, host_weights)
    map_.bucket_names.setdefault("default", root.id)
    return root


def build_rack_hierarchy(
    map_: CrushMap,
    osds_per_host: int,
    hosts_per_rack: int,
    n_racks: int,
    osd_weight: int = 0x10000,
    alg: BucketAlg = BucketAlg.STRAW2,
    host_type: int = 1,
    rack_type: int = 3,
    root_type: int = 10,
) -> Bucket:
    """root -> rack -> host -> osd tree (the rack-scale failure-domain
    shape); registers ``rack{r}``/``host{h}``/``default`` bucket names.
    OSD ids are dense: host h holds osds [h*per_host, (h+1)*per_host)."""
    rack_ids = []
    rack_weights = []
    for r in range(n_racks):
        host_ids = []
        host_weights = []
        for hh in range(hosts_per_rack):
            h = r * hosts_per_rack + hh
            osds = list(range(h * osds_per_host, (h + 1) * osds_per_host))
            hb = make_bucket(
                map_, alg, host_type, osds, [osd_weight] * osds_per_host)
            map_.bucket_names.setdefault(f"host{h}", hb.id)
            host_ids.append(hb.id)
            host_weights.append(hb.weight)
        rb = make_bucket(map_, alg, rack_type, host_ids, host_weights)
        map_.bucket_names.setdefault(f"rack{r}", rb.id)
        rack_ids.append(rb.id)
        rack_weights.append(rb.weight)
    root = make_bucket(map_, alg, root_type, rack_ids, rack_weights)
    map_.bucket_names.setdefault("default", root.id)
    return root


def add_simple_rule(
    map_: CrushMap,
    root_id: int,
    failure_domain_type: int,
    rule_type: int = 1,
    mode: str = "firstn",
    rule_id: int | None = None,
    num: int = 0,
) -> int:
    """CrushWrapper::add_simple_rule: take root; chooseleaf <mode> <num>
    <failure-domain>; emit.  ``num=0`` selects pool-size items;
    ``mode='indep'`` with rule_type=3 is the shape EC profiles create
    (ErasureCode.cc:76-100)."""
    if rule_id is None:
        rule_id = max(map_.rules.keys(), default=-1) + 1
    steps = []
    if mode == "indep":
        steps.append(RuleStep(RuleOp.SET_CHOOSELEAF_TRIES, 5, 0))
    steps.append(RuleStep(RuleOp.TAKE, root_id, 0))
    op = RuleOp.CHOOSELEAF_FIRSTN if mode == "firstn" else RuleOp.CHOOSELEAF_INDEP
    if failure_domain_type == 0:
        op = RuleOp.CHOOSE_FIRSTN if mode == "firstn" else RuleOp.CHOOSE_INDEP
    steps.append(RuleStep(op, num, failure_domain_type))
    steps.append(RuleStep(RuleOp.EMIT, 0, 0))
    map_.rules[rule_id] = Rule(rule_type=rule_type, steps=steps)
    return rule_id


def set_device_class(map_: CrushMap, osd: int, device_class: str) -> None:
    """Tag an OSD with a device class (CrushWrapper class_map analogue);
    class-restricted rules select only matching OSDs."""
    map_.device_classes[osd] = device_class


def create_ec_rule(
    map_: CrushMap,
    name: str,
    root_name: str = "default",
    failure_domain: str = "host",
    num_failure_domains: int = 0,
    osds_per_failure_domain: int = 0,
    device_class: str | None = None,
    mode: str = "indep",
) -> int:
    """Name-resolving EC rule creation — the seam
    ErasureCode::create_rule drives (reference ErasureCode.cc:70-102 →
    CrushWrapper::add_simple_rule / add_indep_multi_osd_per_failure_
    domain_rule).  Returns the new rule id; registers ``name``.

    ``device_class`` restricts choice to OSDs of that class.  The
    reference materializes per-class shadow hierarchies
    (CrushWrapper::populate_classes); here class filtering is applied by
    the mapper via per-device class membership (same resulting OSD set).
    """
    if name in map_.rule_names:
        raise ValueError(f"rule {name!r} already exists")
    if root_name not in map_.bucket_names:
        raise LookupError(f"root item {root_name!r} does not exist")
    root_id = map_.bucket_names[root_name]
    try:
        fd_type = map_.type_id(failure_domain)
    except KeyError:
        raise LookupError(f"unknown type {failure_domain!r}") from None
    if osds_per_failure_domain <= 1:
        rid = add_simple_rule(
            map_, root_id, fd_type,
            rule_type=3, mode=mode, num=num_failure_domains,
        )
    else:
        rid = add_osd_multi_per_domain_rule(
            map_, root_id, fd_type,
            num_per_domain=osds_per_failure_domain,
            num_domains=num_failure_domains,
        )
    if device_class:
        map_.rules[rid].device_class = device_class
    map_.rule_names[name] = rid
    return rid


def add_two_level_indep_rule(
    map_: CrushMap,
    root_id: int,
    failure_domain_type: int,
    num_per_domain: int,
    rule_type: int = 3,
    rule_id: int | None = None,
    num_domains: int = 0,
) -> int:
    """Classic (pre-MSR) two-level indep rule: choose indep
    <num_domains> domains then chooseleaf indep <num_per_domain> osds —
    kept for LRC layer rules and the reference-pinned golden vectors;
    EC profiles with crush-osds-per-failure-domain now get the MSR rule
    (add_osd_multi_per_domain_rule), as the reference does."""
    if rule_id is None:
        rule_id = max(map_.rules.keys(), default=-1) + 1
    map_.rules[rule_id] = Rule(rule_type=rule_type, steps=[
        RuleStep(RuleOp.SET_CHOOSELEAF_TRIES, 5, 0),
        RuleStep(RuleOp.TAKE, root_id, 0),
        RuleStep(RuleOp.CHOOSE_INDEP, num_domains, failure_domain_type),
        RuleStep(RuleOp.CHOOSELEAF_INDEP, num_per_domain, 0),
        RuleStep(RuleOp.EMIT, 0, 0),
    ])
    return rule_id


def add_osd_multi_per_domain_rule(
    map_: CrushMap,
    root_id: int,
    failure_domain_type: int,
    num_per_domain: int,
    rule_type: int | None = None,
    rule_id: int | None = None,
    num_domains: int = 0,
) -> int:
    """CrushWrapper::add_indep_multi_osd_per_failure_domain_rule
    (CrushWrapper.cc:2376,2466): an MSR rule — take root; choosemsr
    <num_domains> <failure-domain>; choosemsr <num_per_domain> osd;
    emit.  MSR descent retries the whole path on a rejected leaf, so
    an out OSD can remap to ANOTHER failure domain even with several
    OSDs per domain (wide EC on small clusters, mapper.c:1633-1720)."""
    if rule_type is None:
        rule_type = RULE_TYPE_MSR_INDEP
    if rule_id is None:
        rule_id = max(map_.rules.keys(), default=-1) + 1
    map_.rules[rule_id] = Rule(rule_type=rule_type, steps=[
        RuleStep(RuleOp.TAKE, root_id, 0),
        RuleStep(RuleOp.CHOOSE_MSR, num_domains, failure_domain_type),
        RuleStep(RuleOp.CHOOSE_MSR, num_per_domain, 0),
        RuleStep(RuleOp.EMIT, 0, 0),
    ])
    return rule_id


def _refresh_aux(b: Bucket) -> None:
    """Recompute the per-alg auxiliary arrays after an items change
    (make_bucket derivations, builder.c crush_bucket_add/remove_item)."""
    if b.alg == BucketAlg.LIST:
        total = 0
        b.sum_weights = []
        for w in b.item_weights:
            total += w
            b.sum_weights.append(total)
    elif b.alg == BucketAlg.TREE:
        b.node_weights = _tree_node_weights(b.items, b.item_weights)
    elif b.alg == BucketAlg.UNIFORM:
        if b.item_weights:
            b.item_weights = [b.item_weights[0]] * len(b.items)


def add_bucket(
    map_: CrushMap, name: str, type_name: str,
    alg: BucketAlg = BucketAlg.STRAW2,
) -> Bucket:
    """CrushWrapper::add_bucket + set_item_name: a new EMPTY named
    bucket, unattached until `osd crush move` places it."""
    if name in map_.bucket_names:
        return map_.buckets[map_.bucket_names[name]]
    b = make_bucket(map_, alg, map_.type_id(type_name), [], [])
    map_.bucket_names[name] = b.id
    return b


def detach_item(map_: CrushMap, item: int) -> int:
    """Unlink ``item`` from whichever bucket holds it (builder.c
    crush_bucket_remove_item), propagating the weight loss up.
    Returns the weight it had (16.16), or -1 if unattached."""
    for b in map_.buckets.values():
        for i, it in enumerate(b.items):
            if it == item:
                w = b.item_weights[i]
                del b.items[i]
                del b.item_weights[i]
                _refresh_aux(b)
                if w:
                    _propagate_weight(map_, b.id, -w)
                return w
    return -1


def attach_item(
    map_: CrushMap, item: int, parent: int, weight: int,
) -> None:
    """Link ``item`` under bucket ``parent`` at ``weight``
    (builder.c crush_bucket_add_item)."""
    b = map_.buckets[parent]
    b.items.append(item)
    b.item_weights.append(weight)
    _refresh_aux(b)
    if weight:
        _propagate_weight(map_, b.id, weight)
    if item >= 0:
        map_.max_devices = max(map_.max_devices, item + 1)


def would_cycle(map_: CrushMap, item: int, parent: int) -> bool:
    """True when linking bucket ``item`` under ``parent`` would create
    a cycle (parent is item or sits inside item's subtree)."""
    if item >= 0:
        return False
    seen = set()
    cur = parent
    while cur is not None and cur not in seen:
        if cur == item:
            return True
        seen.add(cur)
        cur = next(
            (b.id for b in map_.buckets.values() if cur in b.items),
            None,
        )
    return False


def move_item(
    map_: CrushMap, item: int, parent: int, weight: int | None = None,
) -> bool:
    """CrushWrapper::move_bucket / create-or-move semantics: unlink
    from the current parent (keeping the weight unless overridden) and
    relink under ``parent``.  Refuses a move that would create a cycle
    (moving a bucket under its own subtree).  Returns False on cycle."""
    if would_cycle(map_, item, parent):
        return False
    old_w = detach_item(map_, item)
    if weight is None:
        weight = old_w if old_w >= 0 else (
            map_.buckets[item].weight if item < 0 else 0x10000)
    attach_item(map_, item, parent, weight)
    return True


def remove_item(map_: CrushMap, item: int) -> bool:
    """CrushWrapper::remove_item: unlink everywhere; a bucket is also
    deleted from the map (caller enforces emptiness)."""
    found = detach_item(map_, item) >= 0
    if item < 0 and item in map_.buckets:
        del map_.buckets[item]
        for name, bid in list(map_.bucket_names.items()):
            if bid == item:
                del map_.bucket_names[name]
        found = True
    return found


def reweight_item(map_: CrushMap, item: int, weight: int) -> bool:
    """CrushWrapper::adjust_item_weightf: set an item's CRUSH weight
    (16.16 fixed) wherever it appears, propagating the delta up through
    ancestor buckets.  Returns True when the item was found."""
    found = False
    for b in map_.buckets.values():
        for i, it in enumerate(b.items):
            if it == item:
                delta = weight - b.item_weights[i]
                b.item_weights[i] = weight
                found = True
                if delta:
                    _propagate_weight(map_, b.id, delta)
    return found


def _propagate_weight(map_: CrushMap, child: int, delta: int) -> None:
    for b in map_.buckets.values():
        for i, it in enumerate(b.items):
            if it == child:
                b.item_weights[i] += delta
                _propagate_weight(map_, b.id, delta)
                return
