"""CRUSH map (de)compiler: a readable on-disk text form.

The reference compiles a text grammar to the binary map and back
(src/crush/CrushCompiler.cc, grammar.h; `crushtool -c/-d`).  Here the
text form is JSON with the same vocabulary (devices, types, buckets
with alg/hash/items, rules with step programs, tunables), which keeps
maps diffable and hand-editable while staying trivially parseable.
"""

from __future__ import annotations

import json

from ceph_tpu.crush.types import (
    Bucket,
    BucketAlg,
    ChooseArg,
    CrushMap,
    Rule,
    RuleOp,
    RuleStep,
    Tunables,
)


def decompile(m: CrushMap) -> str:
    """CrushCompiler::decompile: map -> text."""
    doc = {
        "tunables": {
            "choose_local_tries": m.tunables.choose_local_tries,
            "choose_local_fallback_tries": m.tunables.choose_local_fallback_tries,
            "choose_total_tries": m.tunables.choose_total_tries,
            "chooseleaf_descend_once": m.tunables.chooseleaf_descend_once,
            "chooseleaf_vary_r": m.tunables.chooseleaf_vary_r,
            "chooseleaf_stable": m.tunables.chooseleaf_stable,
            "msr_descents": m.tunables.msr_descents,
            "msr_collision_tries": m.tunables.msr_collision_tries,
        },
        "types": {str(tid): name for tid, name in sorted(m.types.items())},
        "devices": [
            {"id": osd, "class": m.device_classes.get(osd)}
            for osd in range(m.max_devices)
        ],
        "buckets": [
            {
                "id": b.id,
                "name": next(
                    (n for n, i in m.bucket_names.items() if i == b.id), None
                ),
                "type": b.type,
                "alg": b.alg.name.lower(),
                "hash": b.hash,
                "items": [
                    {"id": it, "weight": w}
                    for it, w in zip(b.items, b.item_weights)
                ],
            }
            for b in sorted(m.buckets.values(), key=lambda b: -b.id)
        ],
        "rules": [
            {
                "id": rid,
                "name": next(
                    (n for n, i in m.rule_names.items() if i == rid), None
                ),
                "type": r.rule_type,
                "device_class": r.device_class,
                "steps": [
                    {"op": s.op.name.lower(), "arg1": s.arg1, "arg2": s.arg2}
                    for s in r.steps
                ],
            }
            for rid, r in sorted(m.rules.items())
        ],
        "choose_args": {
            str(bid): {
                "weight_set": arg.weight_set,
                "ids": arg.ids,
            }
            for bid, arg in sorted(m.choose_args.items())
        },
    }
    return json.dumps(doc, indent=2)


def compile_text(text: str) -> CrushMap:
    """CrushCompiler::compile: text -> map (with sanity checks)."""
    doc = json.loads(text)
    m = CrushMap(types={})
    t = doc.get("tunables", {})
    m.tunables = Tunables(**{
        k: int(v) for k, v in t.items()
        if k in Tunables.__dataclass_fields__
    })
    for tid, name in doc.get("types", {}).items():
        m.types[int(tid)] = name
    for dev in doc.get("devices", []):
        m.max_devices = max(m.max_devices, int(dev["id"]) + 1)
        if dev.get("class"):
            m.device_classes[int(dev["id"])] = dev["class"]
    for b in doc.get("buckets", []):
        bid = int(b["id"])
        if bid >= 0:
            raise ValueError(f"bucket id {bid} must be negative")
        bucket = Bucket(
            id=bid,
            type=int(b["type"]),
            alg=BucketAlg[b.get("alg", "straw2").upper()],
            hash=int(b.get("hash", 0)),
            items=[int(i["id"]) for i in b.get("items", [])],
            item_weights=[int(i["weight"]) for i in b.get("items", [])],
        )
        m.buckets[bid] = bucket
        if b.get("name"):
            m.bucket_names[b["name"]] = bid
        for i in bucket.items:
            if i >= 0:
                m.max_devices = max(m.max_devices, i + 1)
    for r in doc.get("rules", []):
        steps = [
            RuleStep(
                RuleOp[s["op"].upper()], int(s.get("arg1", 0)),
                int(s.get("arg2", 0)),
            )
            for s in r.get("steps", [])
        ]
        rid = int(r["id"])
        m.rules[rid] = Rule(
            rule_type=int(r.get("type", 1)), steps=steps,
            device_class=r.get("device_class"),
        )
        if r.get("name"):
            m.rule_names[r["name"]] = rid
    for bid, arg in doc.get("choose_args", {}).items():
        m.choose_args[int(bid)] = ChooseArg(
            int(bid), weight_set=arg.get("weight_set"), ids=arg.get("ids"),
        )
    # sanity: referenced children must exist (compiler sanity checks)
    for b in m.buckets.values():
        for it in b.items:
            if it < 0 and it not in m.buckets:
                raise ValueError(f"bucket {b.id} references unknown {it}")
    return m
