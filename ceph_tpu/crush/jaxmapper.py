"""Batched CRUSH placement engine — jit/vmap over placement seeds.

The TPU twin of the scalar rule interpreter (ceph_tpu/crush/mapper.py,
itself a bit-exact twin of reference src/crush/mapper.c): one compiled
XLA program maps a whole batch of placement seeds (pps values — every PG
of a pool at once) through TAKE/CHOOSE/EMIT rule programs.  This is the
engine behind the whole-cluster remap (ceph_tpu/osd/remap.py), the
batched analogue of the reference's thread-pooled ParallelPGMapper
(src/osd/OSDMapMapping.h:18-114).

Design notes (SURVEY.md §7 hard-part 4):

- The reference's rejection-retry control flow (crush_choose_firstn
  mapper.c:441-629, crush_choose_indep mapper.c:636-824) is
  data-dependent, so it is expressed as masked ``lax.while_loop`` state
  machines with the same bounded trip counts the C code has
  (choose_total_tries); ``vmap`` batches the machines over seeds.
- straw2 draws (mapper.c:315-365) need 64-bit fixed-point: the module
  runs its jitted programs under ``jax.experimental.enable_x64`` and is
  explicit about dtypes so the rest of the framework stays in default
  32-bit mode.
- The map compiles to dense padded arrays (items/weights/child tables);
  bucket descent becomes gathers + argmax, exactly mirroring the scalar
  semantics including first-index-wins tie breaking.

Supported surface (validated at compile; callers fall back to the
scalar mapper otherwise): straw2 buckets, rjenkins1 hash,
choose_local_fallback_tries == 0 (the modern "jewel+" tunable profiles —
the fallback path needs the stateful uniform-bucket permutation cache,
which is inherently sequential).  All rule step kinds, chooseleaf
recursion, vary_r/stable tunables, device classes, choose_args
weight-set overrides and reweights are implemented.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ceph_tpu.crush._ln_tables import LL_TBL, RH_LH_TBL
from ceph_tpu.crush.types import (
    CRUSH_HASH_RJENKINS1,
    CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF,
    BucketAlg,
    ChooseArg,
    CrushMap,
    Rule,
    RuleOp,
)

# while-loop statuses
_RUN, _PLACED, _SKIP = 0, 1, 2
# indep descent outcomes
_OUT_BREAK, _OUT_PLACE, _OUT_NONE = 0, 1, 2


class UnsupportedMap(NotImplementedError):
    """Map or rule uses a feature outside the batched engine's surface."""


@dataclasses.dataclass
class CompiledCrush:
    """Dense-array form of a CrushMap (+ one choose_args set)."""

    items: np.ndarray     # [NB, M] int32, padded with 0
    child: np.ndarray     # [NB, M] int32: dense idx of sub-bucket, -1 if device/unknown
    argids: np.ndarray    # [NB, M] int32: choose_args ids override (default items)
    weights: np.ndarray   # [NB, P, M] int64: per-position weights (16.16)
    npos: np.ndarray      # [NB] int32: valid weight positions per bucket
    size: np.ndarray      # [NB] int32
    btype: np.ndarray     # [NB] int32
    idx_of_arr: np.ndarray  # [K] int32: (-1 - bucket_id) -> dense idx, -1 unknown
    idx_of: dict          # bucket id -> dense idx
    max_devices: int
    max_depth: int
    tunables: object
    rules: dict
    device_classes: dict


def compile_map(
    cmap: CrushMap, choose_args: dict[int, ChooseArg] | None = None
) -> CompiledCrush:
    """Flatten a CrushMap into gather-friendly arrays.

    ``choose_args`` (balancer weight-set overrides) are baked in; pass a
    different set to get a different compiled map, mirroring how the
    reference snapshots choose_args per crush_do_rule call
    (mapper.c:290-307).
    """
    # first-compile latency on a cold process is the remap path's whole
    # startup cost (193 s measured on the chip for the 10k-PG map):
    # persist XLA executables across processes
    from ceph_tpu.ops.compile_cache import ensure_persistent_cache

    ensure_persistent_cache()
    ids = sorted(cmap.buckets.keys(), reverse=True)  # -1, -2, ...
    for bid in ids:
        b = cmap.buckets[bid]
        if b.alg != BucketAlg.STRAW2:
            raise UnsupportedMap(f"bucket {bid}: alg {b.alg!r} not batched")
        if b.hash != CRUSH_HASH_RJENKINS1:
            raise UnsupportedMap(f"bucket {bid}: hash {b.hash}")
    nb = max(len(ids), 1)
    m = max((cmap.buckets[i].size for i in ids), default=0)
    m = max(m, 1)
    idx_of = {bid: i for i, bid in enumerate(ids)}
    npos_all = 1
    if choose_args:
        for arg in choose_args.values():
            if arg.weight_set:
                npos_all = max(npos_all, len(arg.weight_set))

    items = np.zeros((nb, m), np.int32)
    child = np.full((nb, m), -1, np.int32)
    argids = np.zeros((nb, m), np.int32)
    weights = np.zeros((nb, npos_all, m), np.int64)
    npos = np.ones(nb, np.int32)
    size = np.zeros(nb, np.int32)
    btype = np.zeros(nb, np.int32)
    for bid in ids:
        i = idx_of[bid]
        b = cmap.buckets[bid]
        n = b.size
        size[i] = n
        btype[i] = b.type
        items[i, :n] = b.items
        argids[i, :n] = b.items
        for j, it in enumerate(b.items):
            if it < 0 and it in idx_of:
                child[i, j] = idx_of[it]
        weights[i, :, :n] = np.asarray(b.item_weights, np.int64)[None, :]
        arg = (choose_args or {}).get(bid)
        if arg is not None:
            if arg.ids is not None:
                argids[i, :n] = arg.ids
            if arg.weight_set:
                p = len(arg.weight_set)
                npos[i] = p
                for pi in range(p):
                    weights[i, pi, :n] = np.asarray(arg.weight_set[pi], np.int64)
                # positions beyond the set clamp to the last one
                for pi in range(p, npos_all):
                    weights[i, pi, :n] = weights[i, p - 1, :n]

    # depth bound for descent loops (and DAG check)
    depth: dict[int, int] = {}

    def _depth(bid: int, stack: frozenset) -> int:
        if bid in stack:
            raise UnsupportedMap("cycle in bucket graph")
        if bid in depth:
            return depth[bid]
        b = cmap.buckets[bid]
        d = 1 + max(
            (_depth(it, stack | {bid}) for it in b.items if it in cmap.buckets),
            default=0,
        )
        depth[bid] = d
        return d

    max_depth = max((_depth(bid, frozenset()) for bid in ids), default=1)

    k = max((-bid for bid in ids), default=0)
    idx_of_arr = np.full(max(k, 1), -1, np.int32)
    for bid in ids:
        idx_of_arr[-1 - bid] = idx_of[bid]

    return CompiledCrush(
        items=items, child=child, argids=argids, weights=weights,
        npos=npos, size=size, btype=btype,
        idx_of_arr=idx_of_arr, idx_of=idx_of,
        max_devices=cmap.max_devices, max_depth=max_depth,
        tunables=cmap.tunables, rules=cmap.rules,
        device_classes=dict(cmap.device_classes),
    )


def _jm_for(cc: CompiledCrush) -> "_Jm":
    """One shared device-side view per compiled map (the arrays are
    immutable after compile, so every rule mapper can reuse them)."""
    jm = getattr(cc, "_jm_cache", None)
    if jm is None:
        jm = _Jm(cc)
        cc._jm_cache = jm
    return jm


class _Jm:
    """Device-side (traced-constant) view of a CompiledCrush."""

    def __init__(self, cc: CompiledCrush):
        import jax.numpy as jnp

        self.items = jnp.asarray(cc.items)
        self.child = jnp.asarray(cc.child)
        self.argids = jnp.asarray(cc.argids)
        self.weights = jnp.asarray(cc.weights)
        self.npos = jnp.asarray(cc.npos)
        self.size = jnp.asarray(cc.size)
        self.btype = jnp.asarray(cc.btype)
        self.idx_of_arr = jnp.asarray(cc.idx_of_arr)
        self.rh_lh = jnp.asarray(RH_LH_TBL)
        self.ll = jnp.asarray(LL_TBL)
        self.nb = cc.items.shape[0]
        self.m = cc.items.shape[1]
        self.max_devices = cc.max_devices


def _crush_ln_j(jm: _Jm, u):
    """crush_ln (mapper.c:229-271) on int32 lanes -> int64.

    ``u`` is in [0, 0xffff] (the masked hash), so x = u+1 <= 0x10000 and
    bit_length fits a 17-term comparison sum (no clz needed)."""
    import jax.numpy as jnp

    x = u.astype(jnp.int32) + 1
    bl = jnp.zeros_like(x)
    for i in range(17):
        bl = bl + (x >= (1 << i)).astype(jnp.int32)
    cond = (x & 0x18000) == 0
    bits = jnp.int32(16) - bl
    x2 = jnp.where(cond, x << jnp.where(cond, bits, 0), x)
    iexpon = jnp.where(cond, jnp.int32(15) - bits, jnp.int32(15))
    index1 = (x2 >> 8) << 1
    rh = jm.rh_lh[index1 - 256]
    lh = jm.rh_lh[index1 - 255]
    # U64 product wraparound exactly as the C code's (x << 1) * RH path
    xl64 = (x2.astype(jnp.uint64) * rh.astype(jnp.uint64)) >> 48
    index2 = (xl64 & 0xFF).astype(jnp.int32)
    lh2 = (lh + jm.ll[index2]) >> 4
    return (iexpon.astype(jnp.int64) << 44) + lh2


def _straw2_choose(jm: _Jm, rew, bidx, x, r, pos):
    """bucket_straw2_choose (mapper.c:342-365): exponential-minimum draw
    per item, first-max wins.  Returns (item, child_idx)."""
    import jax.numpy as jnp

    from ceph_tpu.ops.hashing import crush_hash32_3_jax

    ids = jm.argids[bidx]                      # [M] int32
    p = jnp.clip(pos, 0, jm.npos[bidx] - 1)
    w = jm.weights[bidx, p]                    # [M] int64
    u = crush_hash32_3_jax(x, ids, r) & 0xFFFF
    ln = _crush_ln_j(jm, u)                    # int64, <= 2^48
    num = (jnp.int64(1) << 44) * 16 - ln       # 2^48 - ln  >= 0
    s64min = jnp.int64(-(2**63))
    draw = jnp.where(w > 0, -(num // jnp.maximum(w, 1)), s64min)
    in_range = jnp.arange(jm.m) < jm.size[bidx]
    draw = jnp.where(in_range, draw, s64min)
    hi = jnp.argmax(draw).astype(jnp.int32)
    return jm.items[bidx, hi], jm.child[bidx, hi]


def _is_out_j(jm: _Jm, rew, item, x):
    """Reweight rejection, mapper.c:405-419 (is_out)."""
    import jax.numpy as jnp

    from ceph_tpu.ops.hashing import crush_hash32_2_jax

    it = jnp.clip(item, 0, max(jm.max_devices - 1, 0))
    w = rew[it] if jm.max_devices else jnp.int32(0)
    h = crush_hash32_2_jax(x, item) & 0xFFFF
    return ~(w >= 0x10000) & ((w == 0) | (h >= w))


def _classify(jm: _Jm, item, cidx, type_):
    """Shared item classification: (is_dev, known, want, descend, skip)."""
    import jax.numpy as jnp

    too_big = item >= jm.max_devices
    is_dev = item >= 0
    known = is_dev | (cidx >= 0)
    ityp = jnp.where(
        is_dev | ~known, jnp.int32(0), jm.btype[jnp.clip(cidx, 0, jm.nb - 1)]
    )
    mismatch = ~known | (ityp != type_)
    want = ~too_big & ~mismatch
    descend = ~too_big & mismatch & known & ~is_dev
    skip = too_big | (mismatch & (is_dev | ~known))
    return is_dev, want, descend, skip


def _firstn_attempt(
    jm, rew, x, root, rep, parent_r, outpos, coll_buf, out2_buf, cap, *,
    type_, tries, local_retries, recurse, recurse_tries, vary_r, stable,
):
    """One replica attempt of crush_choose_firstn (mapper.c:441-629):
    the retry_descent/retry_bucket machinery as a while_loop state
    machine.  Returns (placed, item, leaf)."""
    import jax.numpy as jnp
    from jax import lax

    i32 = jnp.int32

    def cond(st):
        return st[0] == _RUN

    def body(st):
        status, in_idx, flocal, ftotal, item0, leaf0 = st
        size = jm.size[in_idx]
        r = rep + parent_r + ftotal
        item, cidx = _straw2_choose(jm, rew, in_idx, x, r, outpos)
        empty = size == 0
        is_dev, want, descend, skip_now = _classify(jm, item, cidx, type_)
        want = want & ~empty
        descend = descend & ~empty
        skip_now = skip_now & ~empty
        collide = want & jnp.any((jnp.arange(cap) < outpos) & (coll_buf == item))
        if recurse:
            sub_root = jnp.where(cidx >= 0, cidx, in_idx)
            sub_rep = i32(0) if stable else outpos
            sub_parent_r = (r >> (vary_r - 1)) if vary_r else i32(0)
            leaf_ok, leaf_item, _ = _firstn_attempt(
                jm, rew, x, sub_root, sub_rep, sub_parent_r, outpos,
                out2_buf, out2_buf, cap,
                type_=0, tries=recurse_tries, local_retries=local_retries,
                recurse=False, recurse_tries=0, vary_r=vary_r, stable=stable,
            )
            do_rec = want & ~collide & ~is_dev
            leaf_reject = do_rec & ~leaf_ok
            leaf_val = jnp.where(is_dev, item, leaf_item)
        else:
            leaf_reject = jnp.bool_(False)
            leaf_val = item
        if type_ == 0:
            out_rej = (
                want & ~collide & ~leaf_reject & is_dev
                & _is_out_j(jm, rew, item, x)
            )
        else:
            out_rej = jnp.bool_(False)
        fail = empty | (want & (collide | leaf_reject | out_rej))
        place = want & ~collide & ~leaf_reject & ~out_rej
        ftotal2 = ftotal + fail.astype(i32)
        flocal2 = flocal + fail.astype(i32)
        retry_same = fail & collide & (flocal2 <= local_retries)
        retry_root = fail & ~retry_same & (ftotal2 < tries)
        give_up = fail & ~retry_same & ~retry_root
        new_status = jnp.where(
            place, i32(_PLACED),
            jnp.where(skip_now | give_up, i32(_SKIP), i32(_RUN)),
        )
        new_in = jnp.where(
            descend, jnp.clip(cidx, 0, jm.nb - 1),
            jnp.where(retry_root, root, in_idx),
        )
        new_flocal = jnp.where(retry_root, i32(0), flocal2)
        return (
            new_status, new_in, new_flocal, ftotal2,
            jnp.where(place, item, item0), jnp.where(place, leaf_val, leaf0),
        )

    st0 = (i32(_RUN), root, i32(0), i32(0), i32(0), i32(0))
    st = lax.while_loop(cond, body, st0)
    return st[0] == _PLACED, st[4], st[5]


def _firstn_window(
    jm, rew, x, root, valid, numrep, out_size, cap, *,
    type_, tries, local_retries, recurse, recurse_tries, vary_r, stable,
):
    """One input bucket's output window of crush_choose_firstn: up to
    ``numrep`` attempts, placements bounded by ``out_size`` (avail).
    Returns (out[cap], out2[cap], n_placed)."""
    import jax.numpy as jnp

    i32 = jnp.int32
    undef = i32(CRUSH_ITEM_UNDEF)
    out = jnp.full((cap,), undef, jnp.int32)
    out2 = jnp.full((cap,), undef, jnp.int32)
    outpos = i32(0)
    for rep in range(numrep):
        active = valid & (outpos < out_size)
        placed, item, leaf = _firstn_attempt(
            jm, rew, x, root, i32(rep), i32(0), outpos, out, out2, cap,
            type_=type_, tries=tries, local_retries=local_retries,
            recurse=recurse, recurse_tries=recurse_tries,
            vary_r=vary_r, stable=stable,
        )
        commit = active & placed
        slot = jnp.arange(cap) == outpos
        out = jnp.where(slot & commit, item, out)
        out2 = jnp.where(slot & commit, leaf, out2)
        outpos = outpos + commit.astype(i32)
    return out, out2, outpos


def _indep_descent(
    jm, rew, x, root, rep, numrep, ftotal, parent_r, pos, out_buf, act, *,
    type_, recurse, recurse_tries,
):
    """One slot descent of crush_choose_indep (mapper.c:660-800 body).
    Returns (outcome, item, leaf)."""
    import jax.numpy as jnp
    from jax import lax

    i32 = jnp.int32

    def cond(st):
        return st[0] == _RUN

    def body(st):
        status, in_idx, oc0, item0, leaf0 = st
        size = jm.size[in_idx]
        r = rep + parent_r + numrep * ftotal
        item, cidx = _straw2_choose(jm, rew, in_idx, x, r, pos)
        empty = size == 0
        is_dev, want, descend, skip_now = _classify(jm, item, cidx, type_)
        want = want & ~empty
        descend = descend & ~empty
        place_none = skip_now & ~empty
        collide = want & jnp.any(act & (out_buf == item))
        if recurse:
            sub_root = jnp.where(cidx >= 0, cidx, in_idx)
            leaf_item = _indep_leaf(
                jm, rew, x, sub_root, rep, numrep, r,
                recurse_tries=recurse_tries,
            )
            do_rec = want & ~collide & ~is_dev
            leaf_fail = do_rec & (leaf_item == CRUSH_ITEM_NONE)
            leaf_val = jnp.where(is_dev, item, leaf_item)
        else:
            leaf_fail = jnp.bool_(False)
            leaf_val = item
        if type_ == 0:
            out_rej = (
                want & ~collide & ~leaf_fail & is_dev
                & _is_out_j(jm, rew, item, x)
            )
        else:
            out_rej = jnp.bool_(False)
        brk = empty | (want & (collide | leaf_fail | out_rej))
        place = want & ~collide & ~leaf_fail & ~out_rej
        outcome = jnp.where(
            place, i32(_OUT_PLACE), jnp.where(place_none, i32(_OUT_NONE), i32(_OUT_BREAK))
        )
        done = place | place_none | brk
        new_status = jnp.where(done, i32(1), i32(_RUN))
        new_in = jnp.where(descend, jnp.clip(cidx, 0, jm.nb - 1), in_idx)
        return (
            new_status, new_in,
            jnp.where(done, outcome, oc0),
            jnp.where(place, item, item0),
            jnp.where(place, leaf_val, leaf0),
        )

    st0 = (i32(_RUN), root, i32(_OUT_BREAK), i32(0), i32(0))
    st = lax.while_loop(cond, body, st0)
    return st[2], st[3], st[4]


def _indep_leaf(jm, rew, x, sub_root, rep, numrep, parent_r, *, recurse_tries):
    """The chooseleaf recursion of crush_choose_indep: a 1-slot indep
    window at type 0 with its own ftotal loop (tries=recurse_tries,
    choose-arg position = rep).  Returns the leaf item or NONE."""
    import jax.numpy as jnp
    from jax import lax

    i32 = jnp.int32
    undef = i32(CRUSH_ITEM_UNDEF)

    def cond(st):
        leaf, ftotal = st
        return (leaf == undef) & (ftotal < recurse_tries)

    def body(st):
        leaf, ftotal = st
        dummy = jnp.full((1,), undef, jnp.int32)
        oc, item, _ = _indep_descent(
            jm, rew, x, sub_root, rep, numrep, ftotal, parent_r, rep,
            dummy, jnp.zeros((1,), jnp.bool_),
            type_=0, recurse=False, recurse_tries=0,
        )
        leaf2 = jnp.where(
            oc == _OUT_PLACE, item,
            jnp.where(oc == _OUT_NONE, i32(CRUSH_ITEM_NONE), leaf),
        )
        return leaf2, ftotal + 1

    leaf, _ = lax.while_loop(cond, body, (undef, i32(0)))
    return jnp.where(leaf == undef, i32(CRUSH_ITEM_NONE), leaf)


def _indep_window(
    jm, rew, x, root, valid, numrep, left0, nw, *,
    type_, tries, recurse, recurse_tries,
):
    """crush_choose_indep over one window: positionally stable,
    breadth-first rounds bounded by ``tries``.  Returns (out[nw],
    out2[nw]) with NONE holes."""
    import jax.numpy as jnp
    from jax import lax

    i32 = jnp.int32
    undef = i32(CRUSH_ITEM_UNDEF)
    none = i32(CRUSH_ITEM_NONE)
    act = (jnp.arange(nw) < left0) & valid

    def cond(st):
        out, out2, ftotal = st
        return jnp.any(act & (out == undef)) & (ftotal < tries)

    def body(st):
        out, out2, ftotal = st
        for rep in range(nw):
            need = act[rep] & (out[rep] == undef)
            oc, item, leaf = _indep_descent(
                jm, rew, x, root, i32(rep), i32(numrep), ftotal, i32(0),
                i32(0), out, act,
                type_=type_, recurse=recurse, recurse_tries=recurse_tries,
            )
            place = need & (oc == _OUT_PLACE)
            pnone = need & (oc == _OUT_NONE)
            out = out.at[rep].set(
                jnp.where(place, item, jnp.where(pnone, none, out[rep]))
            )
            out2 = out2.at[rep].set(
                jnp.where(place, leaf, jnp.where(pnone, none, out2[rep]))
            )
        return out, out2, ftotal + 1

    out = jnp.full((nw,), undef, jnp.int32)
    out2 = jnp.full((nw,), undef, jnp.int32)
    out, out2, _ = lax.while_loop(cond, body, (out, out2, i32(0)))
    out = jnp.where(act & (out != undef), out, none)
    out2 = jnp.where(act & (out2 != undef), out2, none)
    return out, out2


def _msr_descend_j(jm, rew, x, bidx0, type_, r_value, pos, enabled):
    """crush_msr_descend twin (ceph_tpu/crush/mapper.py:433, reference
    mapper.c:1274) as a bounded while_loop over the dense bucket graph:
    draw at each level until a device or a bucket of ``type_``.
    Returns (item, child_idx) — item == CRUSH_ITEM_NONE encodes every
    map-integrity reject (empty bucket, dangling child, oversized
    device id), which the caller treats as a collision."""
    import jax.numpy as jnp
    from jax import lax

    i32 = jnp.int32
    none = i32(CRUSH_ITEM_NONE)

    def cond(st):
        depth, bidx, done, _it, _ci = st
        return ~done & (depth < jm.nb + 2)

    def body(st):
        depth, bidx, done, it, ci = st
        empty = jm.size[bidx] == 0
        item, cidx = _straw2_choose(jm, rew, bidx, x, r_value, pos)
        is_dev = item >= 0
        dev_ok = is_dev & (item < jm.max_devices)
        known = cidx >= 0
        btype = jm.btype[jnp.clip(cidx, 0, jm.nb - 1)]
        hit_type = ~is_dev & known & (btype == type_)
        stop = empty | is_dev | ~known | hit_type
        new_it = jnp.where(
            empty | (is_dev & ~dev_ok) | (~is_dev & ~known),
            none, item)
        return (depth + 1, jnp.where(stop, bidx, cidx), stop,
                jnp.where(stop, new_it, it),
                jnp.where(stop & hit_type, cidx, jnp.where(stop, i32(-1), ci)))

    _d, _b, _done, item, cidx = lax.while_loop(
        cond, body, (i32(0), bidx0, ~enabled, none, i32(-1)))
    return item, cidx


def _msr_window(idxs, lo, hi):
    return (idxs >= lo) & (idxs < hi)


def _msr_push_j(vec, s_lo, s_hi, cand, do):
    """crush_msr_push_used twin: set the first UNDEF slot in the
    stride window unless the candidate is already there.  Returns
    (vec, pushed)."""
    import jax.numpy as jnp

    idxs = jnp.arange(vec.shape[0], dtype=jnp.int32)
    win = _msr_window(idxs, s_lo, s_hi)
    present = jnp.any(win & (vec == cand))
    slots = win & (vec == CRUSH_ITEM_UNDEF)
    pos = jnp.argmax(slots).astype(jnp.int32)
    pushed = do & ~present & jnp.any(slots)
    return jnp.where(pushed, vec.at[pos].set(cand), vec), pushed


def _msr_pop_j(vec, s_lo, s_hi, cand, do):
    """crush_msr_pop_used twin: clear the last slot == cand in the
    stride window."""
    import jax.numpy as jnp

    rm = vec.shape[0]
    idxs = jnp.arange(rm, dtype=jnp.int32)
    eq = _msr_window(idxs, s_lo, s_hi) & (vec == cand)
    pos = (rm - 1 - jnp.argmax(eq[::-1])).astype(jnp.int32)
    return jnp.where(do & jnp.any(eq), vec.at[pos].set(CRUSH_ITEM_UNDEF), vec)


def _msr_valid_j(vec, seg_lo, seg_hi, s_lo, s_hi, cand):
    """crush_msr_valid_candidate twin: a candidate used elsewhere in
    the segment is invalid unless that use is inside our own stride."""
    import jax.numpy as jnp

    idxs = jnp.arange(vec.shape[0], dtype=jnp.int32)
    hit = _msr_window(idxs, seg_lo, seg_hi) & (vec == cand)
    return jnp.all(~hit | _msr_window(idxs, s_lo, s_hi))


def _append(acc, cnt, vals, n, rm):
    """result.extend(vals[:n]) with a dump slot at index rm."""
    import jax.numpy as jnp

    ln = vals.shape[0]
    idx = cnt + jnp.arange(ln)
    ok = (jnp.arange(ln) < n) & (idx < rm)
    tgt = jnp.where(ok, idx, rm)
    acc = acc.at[tgt].set(jnp.where(ok, vals, acc[rm]))
    cnt = jnp.minimum(cnt + jnp.maximum(n, 0), rm)
    return acc, cnt


class BatchedRuleMapper:
    """crush_do_rule over a batch of inputs, compiled once per
    (map, choose_args, rule, result_max)."""

    def __init__(self, cc: CompiledCrush, ruleno: int, result_max: int):
        if ruleno not in cc.rules:
            raise KeyError(f"no rule {ruleno}")
        self.cc = cc
        self.rule = cc.rules[ruleno]
        self.result_max = result_max
        self._validate()
        self._jitted = None

    def _validate(self):
        from ceph_tpu.crush.types import (
            RULE_TYPE_MSR_FIRSTN,
            RULE_TYPE_MSR_INDEP,
        )

        t = self.cc.tunables
        if t.choose_local_fallback_tries:
            raise UnsupportedMap("choose_local_fallback_tries > 0")
        if self.rule.rule_type in (RULE_TYPE_MSR_FIRSTN,
                                   RULE_TYPE_MSR_INDEP):
            # MSR rules take the dedicated lane (_msr_lane); only MSR
            # step kinds may appear (crush_msr_do_rule rejects others)
            for s in self.rule.steps:
                if s.op not in (
                    RuleOp.NOOP, RuleOp.TAKE, RuleOp.EMIT,
                    RuleOp.CHOOSE_MSR, RuleOp.SET_MSR_DESCENTS,
                    RuleOp.SET_MSR_COLLISION_TRIES,
                ):
                    raise UnsupportedMap(f"MSR rule op {s.op!r}")
            return
        for s in self.rule.steps:
            if s.op == RuleOp.SET_CHOOSE_LOCAL_FALLBACK_TRIES and s.arg1 > 0:
                raise UnsupportedMap("rule sets local_fallback_tries")
            if s.op in (RuleOp.CHOOSE_MSR, RuleOp.SET_MSR_DESCENTS,
                        RuleOp.SET_MSR_COLLISION_TRIES):
                raise UnsupportedMap(
                    "MSR step in a non-MSR rule")
            if s.op not in (
                RuleOp.NOOP, RuleOp.TAKE, RuleOp.EMIT,
                RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSE_INDEP,
                RuleOp.CHOOSELEAF_FIRSTN, RuleOp.CHOOSELEAF_INDEP,
                RuleOp.SET_CHOOSE_TRIES, RuleOp.SET_CHOOSELEAF_TRIES,
                RuleOp.SET_CHOOSE_LOCAL_TRIES,
                RuleOp.SET_CHOOSE_LOCAL_FALLBACK_TRIES,
                RuleOp.SET_CHOOSELEAF_VARY_R, RuleOp.SET_CHOOSELEAF_STABLE,
            ):
                raise UnsupportedMap(f"rule op {s.op!r}")

    # -- MSR lane (crush_msr_do_rule, mapper.c:1809) -------------------

    def _msr_lane(self, jm: _Jm, class_mask, x, rew):
        """Batched crush_msr_do_rule: the rule's stride tree is STATIC
        (stride boundaries derive from step arg1 counts and
        result_max), so the whole multi-step descent unrolls at trace
        time; the data-dependent parts — whole-descent retries
        (msr_descents), per-stride collision retries
        (msr_collision_tries) and the bucket-graph descent — run as
        bounded while_loops.  Statement-level twin of the scalar
        _msr_do_rule/_msr_choose (ceph_tpu/crush/mapper.py:519-680,
        reference mapper.c:1507,1809), pinned by the same golden
        vectors."""
        import jax.numpy as jnp
        from jax import lax

        from ceph_tpu.crush.mapper import (
            _msr_scan_config_steps,
            _msr_scan_next,
        )
        from ceph_tpu.crush.types import RULE_TYPE_MSR_FIRSTN

        cc = self.cc
        rm = self.result_max
        rule = self.rule
        i32 = jnp.int32
        none = i32(CRUSH_ITEM_NONE)
        undef = i32(CRUSH_ITEM_UNDEF)
        firstn = rule.rule_type == RULE_TYPE_MSR_FIRSTN

        if class_mask is not None:
            rew = jnp.where(class_mask, rew, 0)

        t = cc.tunables
        start_stepno, descents, collision_tries = _msr_scan_config_steps(rule)
        if descents is None:
            descents = t.msr_descents
        if collision_tries is None:
            collision_tries = t.msr_collision_tries

        out = jnp.full((rm + 1,), none, jnp.int32)
        returned = i32(0)

        def emit(out, returned, cand, position, do):
            pos = returned if firstn else i32(position)
            out = jnp.where(do, out.at[pos].set(cand), out)
            return out, returned + do

        def choose(vecs, out, returned, bidx, tryno, enabled,
                   lo, hi, total, stepno, seg_start_stepno, emit_stepno):
            """_msr_choose (mapper.c:1507): one level, strides
            unrolled.  ``total`` is the NOMINAL descendant count
            (stride boundaries use it; windows clip to ``hi`` exactly
            like the scalar's end_index).  The validity exclusion
            window is THIS invocation's [lo, hi) — recursed levels
            narrow it to the parent stride, exactly like the scalar's
            start_index/end_index threading.  Returns (vecs, out,
            returned, mapped)."""
            curstep = rule.steps[stepno]
            num_strides = curstep.arg1 if curstep.arg1 else rm
            if num_strides <= 0 or total % num_strides != 0:
                return vecs, out, returned, i32(0)  # malformed: skip
            length = total // num_strides
            if length <= 0:
                return vecs, out, returned, i32(0)
            level = stepno - seg_start_stepno
            leaf_level = emit_stepno - seg_start_stepno - 1
            is_leaf = curstep.arg2 == 0
            mapped = i32(0)
            undos: list = []
            idxs = jnp.arange(rm, dtype=jnp.int32)
            for sidx, s_lo in enumerate(range(lo, hi, length)):
                s_hi = min(s_lo + length, hi)
                filled = jnp.all(jnp.where(
                    _msr_window(idxs, s_lo, s_hi),
                    vecs[leaf_level] != undef, True))
                en = enabled & ~filled

                # collision loop: descend until a valid candidate
                def coll_cond(st):
                    lt, found, _c, _ci, _v = st
                    return ~found & (lt < collision_tries)

                def coll_body(st, _sidx=sidx, _s_lo=s_lo, _s_hi=s_hi,
                              _vec=vecs[level], _bidx=bidx):
                    lt, found, c, ci, v = st
                    r = (((tryno * rm) + _sidx) << 16) + lt
                    cand, cand_ci = _msr_descend_j(
                        jm, rew, x, _bidx, curstep.arg2, r,
                        i32(_sidx), jnp.bool_(True))
                    ok = cand != none
                    valid = ok & _msr_valid_j(
                        _vec, lo, hi, _s_lo, _s_hi, cand)
                    return (lt + 1, valid,
                            jnp.where(valid, cand, c),
                            jnp.where(valid, cand_ci, ci),
                            valid)

                _lt, found, cand, cand_ci, _v = lax.while_loop(
                    coll_cond, coll_body,
                    (i32(0), ~en, none, i32(-1), jnp.bool_(False)))
                found = found & en

                if is_leaf:
                    # leaf: stride_length must be 1 and this must be
                    # the last step (static malformed-rule guards)
                    if length != 1 or stepno + 1 != emit_stepno:
                        continue
                    do = found & ~_is_out_j(jm, rew, cand, x)
                    vec, pushed = _msr_push_j(
                        vecs[level], s_lo, s_hi, cand, do)
                    vecs = vecs[:level] + (vec,) + vecs[level + 1:]
                    out, returned = emit(out, returned, cand, s_lo, do)
                    mapped = mapped + do
                else:
                    if stepno + 1 >= emit_stepno:
                        continue  # malformed
                    en_child = found & (cand < 0)
                    vecs, out, returned, child_mapped = choose(
                        vecs, out, returned,
                        jnp.clip(cand_ci, 0, jm.nb - 1), tryno,
                        en_child, s_lo, s_hi, length, stepno + 1,
                        seg_start_stepno, emit_stepno)
                    vec, pushed = _msr_push_j(
                        vecs[level], s_lo, s_hi, cand, en_child)
                    vecs = vecs[:level] + (vec,) + vecs[level + 1:]
                    # a pushed interior candidate whose subtree mapped
                    # nothing is popped — but only AFTER every stride
                    # at this level ran (the scalar's undo array): the
                    # failed candidate must stay visible to later
                    # strides' validity checks within this pass
                    undos.append((s_lo, s_hi, cand,
                                  pushed & (child_mapped == 0)))
                    mapped = mapped + child_mapped
            for s_lo, s_hi, cand, flag in undos:
                vec = _msr_pop_j(vecs[level], s_lo, s_hi, cand, flag)
                vecs = vecs[:level] + (vec,) + vecs[level + 1:]
            return vecs, out, returned, mapped

        stepno = start_stepno
        start_index = 0
        while stepno < len(rule.steps):
            scan = _msr_scan_next(rule, rm, stepno)
            if scan is None:
                # invalid rule: "return whatever we have" (= none)
                return jnp.full((rm + 1,), none, jnp.int32), i32(0)
            total_children, emit_stepno = scan
            take_step = rule.steps[stepno]
            if take_step.arg1 >= 0:
                if stepno + 1 != emit_stepno:
                    return jnp.full((rm + 1,), none, jnp.int32), i32(0)
                # NB: the scalar twin does NOT advance start_index
                # after a raw-device take (mapper.py:639) — match it
                out, returned = emit(
                    out, returned, i32(take_step.arg1), start_index,
                    jnp.bool_(True))
            elif take_step.arg1 not in cc.idx_of:
                pass  # unknown root: nothing placed for this segment
            else:
                root = i32(cc.idx_of[take_step.arg1])
                seg_start = stepno + 1
                n_steps = emit_stepno - seg_start
                end_index = min(start_index + total_children, rm)
                vecs0 = tuple(
                    jnp.full((rm,), undef, jnp.int32)
                    for _ in range(n_steps))
                return_limit = returned + (end_index - start_index)

                def desc_cond(st):
                    tryno, _v, _o, ret = st
                    return (tryno < descents) & (ret < return_limit)

                def desc_body(st, _root=root, _seg=seg_start,
                              _emit=emit_stepno, _lo=start_index,
                              _hi=end_index, _tot=total_children):
                    tryno, vecs, out, ret = st
                    vecs, out, ret, _m = choose(
                        vecs, out, ret, _root, tryno, jnp.bool_(True),
                        _lo, _hi, _tot, _seg, _seg, _emit)
                    return (tryno + 1, vecs, out, ret)

                _t, _v, out, returned = lax.while_loop(
                    desc_cond, desc_body, (i32(0), vecs0, out, returned))
                start_index = end_index
            stepno = emit_stepno + 1

        if firstn:
            return out[:rm], returned
        return out[:rm], i32(rm)

    # -- trace-time interpreter (steps are static) --------------------

    def _lane(self, jm: _Jm, class_mask, x, rew):
        import jax.numpy as jnp

        from ceph_tpu.crush.types import (
            RULE_TYPE_MSR_FIRSTN,
            RULE_TYPE_MSR_INDEP,
        )

        if self.rule.rule_type in (RULE_TYPE_MSR_FIRSTN,
                                   RULE_TYPE_MSR_INDEP):
            return self._msr_lane(jm, class_mask, x, rew)

        cc = self.cc
        rm = self.result_max
        i32 = jnp.int32
        t = cc.tunables
        choose_tries = t.choose_total_tries + 1
        choose_leaf_tries = 0
        local_retries = t.choose_local_tries
        vary_r = t.chooseleaf_vary_r
        stable = t.chooseleaf_stable

        if class_mask is not None:
            rew = jnp.where(class_mask, rew, 0)

        res = jnp.full((rm + 1,), CRUSH_ITEM_NONE, jnp.int32)
        res_cnt = i32(0)
        w: tuple = ("empty",)

        for step in self.rule.steps:
            op = step.op
            if op == RuleOp.TAKE:
                ok = (0 <= step.arg1 < cc.max_devices) or step.arg1 in cc.idx_of
                w = ("static", step.arg1) if ok else ("empty",)
            elif op == RuleOp.SET_CHOOSE_TRIES:
                if step.arg1 > 0:
                    choose_tries = step.arg1
            elif op == RuleOp.SET_CHOOSELEAF_TRIES:
                if step.arg1 > 0:
                    choose_leaf_tries = step.arg1
            elif op == RuleOp.SET_CHOOSE_LOCAL_TRIES:
                if step.arg1 >= 0:
                    local_retries = step.arg1
            elif op == RuleOp.SET_CHOOSELEAF_VARY_R:
                if step.arg1 >= 0:
                    vary_r = step.arg1
            elif op == RuleOp.SET_CHOOSELEAF_STABLE:
                if step.arg1 >= 0:
                    stable = step.arg1
            elif op in (
                RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSELEAF_FIRSTN,
                RuleOp.CHOOSE_INDEP, RuleOp.CHOOSELEAF_INDEP,
            ):
                if w[0] == "empty":
                    continue
                firstn = op in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSELEAF_FIRSTN)
                leafy = op in (RuleOp.CHOOSELEAF_FIRSTN, RuleOp.CHOOSELEAF_INDEP)
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif t.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                else:
                    recurse_tries = choose_leaf_tries if choose_leaf_tries else 1

                # windows: (root_idx, valid) sources from w
                if w[0] == "static":
                    wi = w[1]
                    if wi >= 0 or wi not in cc.idx_of:
                        sources = []
                    else:
                        sources = [(i32(cc.idx_of[wi]), jnp.bool_(True))]
                else:
                    vals, cnt = w[1], w[2]
                    sources = []
                    for j in range(rm):
                        wi = vals[j]
                        key = jnp.clip(-1 - wi, 0, jm.idx_of_arr.shape[0] - 1)
                        cidx = jm.idx_of_arr[key]
                        valid = (j < cnt) & (wi < 0) & (cidx >= 0)
                        sources.append((jnp.clip(cidx, 0, jm.nb - 1), valid))

                o = jnp.full((rm + 1,), CRUSH_ITEM_NONE, jnp.int32)
                o_cnt = i32(0)
                for root, valid in sources:
                    numrep = step.arg1
                    if numrep <= 0:
                        numrep += rm
                        if numrep <= 0:
                            continue
                    avail = rm - o_cnt
                    nw = min(numrep, rm)
                    if firstn:
                        out, out2, n = _firstn_window(
                            jm, rew, x, root, valid, numrep,
                            jnp.minimum(avail, numrep), nw,
                            type_=step.arg2, tries=choose_tries,
                            local_retries=local_retries, recurse=leafy,
                            recurse_tries=recurse_tries,
                            vary_r=vary_r, stable=stable,
                        )
                    else:
                        left0 = jnp.clip(jnp.minimum(avail, numrep), 0, nw)
                        out, out2 = _indep_window(
                            jm, rew, x, root, valid, numrep, left0, nw,
                            type_=step.arg2, tries=choose_tries,
                            recurse=leafy, recurse_tries=recurse_tries,
                        )
                        n = left0
                    vals_use = out2 if leafy else out
                    n = jnp.where(valid, n, 0)
                    o, o_cnt = _append(o, o_cnt, vals_use, n, rm)
                w = ("traced", o[:rm], o_cnt)
            elif op == RuleOp.EMIT:
                if w[0] == "static":
                    res, res_cnt = _append(
                        res, res_cnt,
                        jnp.full((1,), w[1], jnp.int32), i32(1), rm,
                    )
                elif w[0] == "traced":
                    res, res_cnt = _append(res, res_cnt, w[1], w[2], rm)
                w = ("empty",)
        return res[:rm], res_cnt

    def _build(self):
        import jax
        import jax.numpy as jnp

        cc = self.cc
        jm = _jm_for(cc)
        if self.rule.device_class is not None:
            mask = np.zeros(max(cc.max_devices, 1), bool)
            for osd, cls in cc.device_classes.items():
                if cls == self.rule.device_class and osd < cc.max_devices:
                    mask[osd] = True
            class_mask = jnp.asarray(mask)
        else:
            class_mask = None

        def lane(x, rew):
            return self._lane(jm, class_mask, x, rew)

        return jax.jit(jax.vmap(lane, in_axes=(0, None)))

    def __call__(self, xs, reweights=None):
        """Map a batch of placement seeds.

        Returns (vals [B, result_max] int32 with CRUSH_ITEM_NONE
        padding/holes, counts [B] int32): per lane the rule result is
        vals[i, :counts[i]], exactly crush_do_rule's output."""
        import jax

        cc = self.cc
        xs = np.asarray(xs, np.uint32).astype(np.int32)
        if reweights is None:
            rew = np.full(max(cc.max_devices, 1), 0x10000, np.int32)
        else:
            rew = np.zeros(max(cc.max_devices, 1), np.int32)
            rw = np.asarray(reweights, np.int64)
            rew[: len(rw)] = rw[: len(rew)]
        try:  # renamed from jax.experimental across jax releases
            _enable_x64 = jax.enable_x64
        except AttributeError:
            from jax.experimental import enable_x64 as _enable_x64
        with _enable_x64(True):
            if self._jitted is None:
                self._jitted = self._build()
            # explicit transfer discipline (ctlint device-host-sink):
            # the two inputs ride one device_put each and the mapping
            # result comes back in ONE device_get — the by-design host
            # exit (placements feed the host-side OSDMap/peering code)
            vals, cnt = self._jitted(
                jax.device_put(xs), jax.device_put(rew))
            return jax.device_get(vals), jax.device_get(cnt)
