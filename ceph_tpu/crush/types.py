"""CRUSH map data model.

Behavioral twin of the reference map model (src/crush/crush.h: struct
crush_map / crush_bucket_* / crush_rule), re-expressed as plain Python
dataclasses (host control plane) that compile to dense arrays for the
batched TPU engine (ceph_tpu/crush/jaxmapper.py).

Weights are 16.16 fixed point (0x10000 == 1.0) exactly as in the
reference; bucket ids are negative, devices non-negative.
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass, field


class BucketAlg(enum.IntEnum):
    # values match crush.h CRUSH_BUCKET_*
    UNIFORM = 1
    LIST = 2
    TREE = 3
    STRAW = 4
    STRAW2 = 5


class RuleOp(enum.IntEnum):
    # values match crush.h CRUSH_RULE_* step opcodes
    NOOP = 0
    TAKE = 1
    CHOOSE_FIRSTN = 2
    CHOOSE_INDEP = 3
    EMIT = 4
    CHOOSELEAF_FIRSTN = 6
    CHOOSELEAF_INDEP = 7
    SET_CHOOSE_TRIES = 8
    SET_CHOOSELEAF_TRIES = 9
    SET_CHOOSE_LOCAL_TRIES = 10
    SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
    SET_CHOOSELEAF_VARY_R = 12
    SET_CHOOSELEAF_STABLE = 13
    SET_MSR_DESCENTS = 14
    SET_MSR_COLLISION_TRIES = 15
    CHOOSE_MSR = 16


# rule types (crush.h crush_rule_type): 1/3 are the classic
# replicated/erasure interpreter rules; 4/5 are multi-step-retry rules
# served by crush_msr_do_rule (mapper.c:1809)
RULE_TYPE_REPLICATED = 1
RULE_TYPE_ERASURE = 3
RULE_TYPE_MSR_FIRSTN = 4
RULE_TYPE_MSR_INDEP = 5

CRUSH_ITEM_UNDEF = 0x7FFFFFFE  # mid-choose reservation (crush.h)
CRUSH_ITEM_NONE = 0x7FFFFFFF   # permanent hole, EC positional
CRUSH_HASH_RJENKINS1 = 0


@dataclass
class Bucket:
    """One interior node.  ``weight``/``item_weights`` are 16.16 fixed."""

    id: int                      # negative
    type: int                    # user-defined type id (host/rack/root...)
    alg: BucketAlg = BucketAlg.STRAW2
    hash: int = CRUSH_HASH_RJENKINS1
    items: list[int] = field(default_factory=list)
    item_weights: list[int] = field(default_factory=list)
    # legacy-alg extras:
    sum_weights: list[int] = field(default_factory=list)   # LIST prefix sums
    node_weights: list[int] = field(default_factory=list)  # TREE heap array
    straws: list[int] = field(default_factory=list)        # STRAW scaled draws

    @property
    def size(self) -> int:
        return len(self.items)

    @property
    def weight(self) -> int:
        return sum(self.item_weights)


@dataclass
class RuleStep:
    op: RuleOp
    arg1: int = 0
    arg2: int = 0


@dataclass
class Rule:
    rule_type: int               # pg_pool type: 1 replicated / 3 erasure
    steps: list[RuleStep] = field(default_factory=list)
    # restrict selection to OSDs of this device class (the reference
    # rewrites TAKE args to per-class shadow buckets; we filter by class
    # membership in the mapper — same resulting OSD set)
    device_class: str | None = None


@dataclass
class Tunables:
    """Defaults == the reference's "jewel" optimal profile, the modern
    default (src/crush/crush.c set_optimal_crush_map / CrushWrapper
    set_tunables_jewel)."""

    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    choose_total_tries: int = 50
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1
    # MSR rule tunables (crush.h msr_descents/msr_collision_tries;
    # defaults CrushWrapper::set_default_msr_tunables)
    msr_descents: int = 100
    msr_collision_tries: int = 100


@dataclass
class ChooseArg:
    """Per-bucket weight_set/ids overrides (pg-upmap balancer machinery,
    src/crush/crush.h struct crush_choose_arg)."""

    bucket_id: int
    weight_set: list[list[int]] | None = None  # [position][item] 16.16
    ids: list[int] | None = None


@dataclass
class CrushMap:
    buckets: dict[int, Bucket] = field(default_factory=dict)  # by id (negative)
    rules: dict[int, Rule] = field(default_factory=dict)
    types: dict[int, str] = field(
        default_factory=lambda: {0: "osd", 1: "host", 3: "rack", 10: "root"})
    max_devices: int = 0
    tunables: Tunables = field(default_factory=Tunables)
    choose_args: dict[int, ChooseArg] = field(default_factory=dict)
    # name tables (CrushWrapper name_map/rule_name_map, class_map)
    bucket_names: dict[str, int] = field(default_factory=dict)
    rule_names: dict[str, int] = field(default_factory=dict)
    device_classes: dict[int, str] = field(default_factory=dict)  # osd -> class

    def bucket(self, bid: int) -> Bucket:
        return self.buckets[bid]

    def type_id(self, name: str) -> int:
        for tid, tname in self.types.items():
            if tname == name:
                return tid
        raise KeyError(f"unknown CRUSH type {name!r}")

    def copy(self) -> "CrushMap":
        return dataclasses.replace(
            self,
            buckets={k: dataclasses.replace(
                v,
                items=list(v.items), item_weights=list(v.item_weights),
                sum_weights=list(v.sum_weights),
                node_weights=list(v.node_weights), straws=list(v.straws),
            ) for k, v in self.buckets.items()},
            rules={k: Rule(v.rule_type, [dataclasses.replace(s) for s in v.steps],
                           v.device_class)
                   for k, v in self.rules.items()},
            types=dict(self.types),
            tunables=dataclasses.replace(self.tunables),
            choose_args=dict(self.choose_args),
            bucket_names=dict(self.bucket_names),
            rule_names=dict(self.rule_names),
            device_classes=dict(self.device_classes),
        )
