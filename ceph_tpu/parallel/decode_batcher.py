"""DecodeAggregator: batched recovery-decode dispatch with fixed shapes.

Recovery reconstructs objects one at a time (`RecoveryMixin`
`_reconcile_object` -> `ecutil.decode_shards_async`), so the decode
stage of a degraded PG is a stream of small per-object GF matmuls —
exactly the launch-bound regime "Repair Pipelining for Erasure-Coded
Storage" (arxiv 1908.01527) shows is won by batching repair traffic,
and whose launch/shape overheads arxiv 2108.02692 attacks around the
kernel.  This module is that layer for the TPU path:

- concurrent in-flight decodes that share an **erasure signature**
  (same decode matrix — k, m, missing-shard pattern and sub-chunk
  layout all feed the matrix, so matrix identity IS the signature)
  are collected during a short coalescing window;
- each request's stripe payload is padded into a **fixed power-of-two
  width bucket** (payloads wider than the tile cap split into
  fixed-width column lanes — the GF matmul is column-independent), the
  group is stacked into a (B, k, W) batch, and ONE batched launch per
  (signature, bucket) reconstructs every lane in the group;
- compiled-program shapes are therefore drawn from a tiny fixed set
  (#erasure-counts x #width-buckets x #batch-buckets), all of which
  :meth:`prewarm` compiles at daemon warmup — after warmup no XLA
  compile can occur inside the recovery I/O path, and the
  ``cold_launches`` counter proves it;
- decode matrices per erasure pattern come precomputed from the
  plugin's LRU cache (``MatrixErasureCode.decode_matrix``, the
  ErasureCodeIsaTableCache twin) and the compiled executables persist
  across processes via ops/compile_cache.py.

Padding is exact: the decode matrix applied to zero columns yields
zero columns, so slicing the first S columns of each lane returns the
bit-identical per-object ``decode_shards`` result (pinned by
tests/test_decode_batcher.py).
"""

from __future__ import annotations

import asyncio
import collections
import threading

import numpy as np

from ceph_tpu.common.metrics import BucketCounters

#: padded widths below this stay in one bucket — tiny decodes all share
#: one shape instead of minting pow2 shapes per small size
DEFAULT_MIN_BUCKET = 4096

#: widest bucket; payloads wider than this split into TILE_CAP-wide
#: lanes (the GF matmul is column-independent), so the launch-shape set
#: is CLOSED: every possible payload lands in one of the
#: log2(TILE_CAP/MIN_BUCKET)+1 buckets and prewarm covers them all
DEFAULT_TILE_CAP = 1 << 16

#: ceiling on the batch dimension of one launch; larger groups split
#: into several full launches (shapes stay fixed either way)
DEFAULT_MAX_BATCH = 8

_BITS_CACHE_SIZE = 64


def pow2_bucket(n: int, floor: int = DEFAULT_MIN_BUCKET) -> int:
    """Smallest power-of-two >= max(n, floor)."""
    n = max(n, floor, 1)
    return 1 << (n - 1).bit_length()


class DecodeAggregator:
    """Coalesces concurrent ``D @ rows`` decode matmuls into fixed-shape
    batched launches.

    Device-agnostic: the batched kernel is the jitted XLA path
    (``ops.rs_kernels.gf_bitmatmul``) which runs bit-exactly on CPU and
    TPU; any dispatch failure answers every waiter from the numpy host
    path, so behavior is always identical to per-object decode.
    """

    def __init__(self, *, window_s: float = 0.002,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 tile_cap: int = DEFAULT_TILE_CAP):
        self.window_s = window_s
        self.max_batch = max_batch
        self.min_bucket = min_bucket
        self.tile_cap = tile_cap
        self._pending: dict[bytes, list[tuple]] = {}
        self._flush_handle = None
        self._bits_cache: collections.OrderedDict = collections.OrderedDict()
        #: (matrix shape, B, k, W) shapes already compiled (by prewarm or
        #: a previous launch); a launch outside this set is a cold
        #: compile — zero of those must happen after daemon warmup
        self._warm: set[tuple] = set()
        # _warm_lock guards ONLY the warm/claimed sets — never hold it
        # across a compile/launch (device-sync-under-lock): prewarm
        # claims missing shapes under the lock, compiles outside it,
        # and concurrent prewarmers wait on the condition for claims
        # they skipped to resolve
        self._warm_lock = threading.Lock()
        self._warm_cv = threading.Condition(self._warm_lock)
        self._warm_claimed: set[tuple] = set()
        self.stats = collections.Counter()
        self.metrics = BucketCounters("recovery_decode_batch")

    # -- gating --------------------------------------------------------

    def active(self) -> bool:
        return True

    # -- request side --------------------------------------------------

    async def apply(self, D: np.ndarray, rows: np.ndarray) -> np.ndarray:
        """``D @ rows`` over GF(2^8), batched with concurrent callers
        that share the decode matrix.

        D is an (out, k) byte matrix (the plugin's cached decode matrix
        for one erasure signature); rows is (k, S) uint8.  Returns
        (out, S) uint8, bit-identical to ``gf_matmul(D, rows)``.
        """
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        key = D.shape[0].to_bytes(2, "little") + D.tobytes()
        self._pending.setdefault(key, []).append((D, rows, fut))
        self.stats["requests"] += 1
        if self._flush_handle is None:
            self._flush_handle = loop.call_later(self.window_s, self._flush)
        return await fut

    # -- dispatch side -------------------------------------------------

    def _bits(self, D: np.ndarray):
        import jax.numpy as jnp

        from ceph_tpu.ops.gf256 import gf_matrix_to_bitmatrix

        key = D.shape[0].to_bytes(2, "little") + D.tobytes()
        hit = self._bits_cache.get(key)
        if hit is None:
            from ceph_tpu.ops.compile_cache import ensure_persistent_cache

            ensure_persistent_cache()
            hit = jnp.asarray(gf_matrix_to_bitmatrix(D))
            self._bits_cache[key] = hit
            if len(self._bits_cache) > _BITS_CACHE_SIZE:
                self._bits_cache.popitem(last=False)
        else:
            self._bits_cache.move_to_end(key)
        return hit

    def _flush(self) -> None:
        """call_later callback: hand every pending signature group to a
        worker thread — the JAX dispatch (and any cold compile) must not
        run on the event loop."""
        self._flush_handle = None
        pending, self._pending = self._pending, {}
        loop = asyncio.get_running_loop()
        for group in pending.values():
            loop.create_task(self._dispatch_group(group))

    async def _dispatch_group(self, group: list[tuple]) -> None:
        try:
            outs = await asyncio.to_thread(self._run_group, group)
        except Exception:
            from ceph_tpu.ops.gf256 import gf_matmul

            self.stats["fallbacks"] += 1
            outs = await asyncio.to_thread(
                lambda: [gf_matmul(D, rows) for D, rows, _ in group])
        for (_, _, fut), out in zip(group, outs):
            if not fut.done():
                fut.set_result(out)

    def _bucket_plan(
        self, group: list[tuple]
    ) -> dict[int, list[tuple[int, int, int]]]:
        """Bucket width -> [(group index, column offset, width), ...].

        Payloads wider than ``tile_cap`` split into tile_cap-wide
        column lanes (the GF matmul is column-independent, so slicing
        columns is exact); narrower payloads pad up to their pow2
        bucket.  Every lane therefore lands in the CLOSED ladder
        [min_bucket .. tile_cap] that prewarm compiles in full."""
        plan: dict[int, list[tuple[int, int, int]]] = {}
        for i, (_, rows, _) in enumerate(group):
            s = rows.shape[1]
            if s <= self.tile_cap:
                w = pow2_bucket(s, self.min_bucket)
                plan.setdefault(w, []).append((i, 0, s))
            else:
                for off in range(0, s, self.tile_cap):
                    plan.setdefault(self.tile_cap, []).append(
                        (i, off, min(self.tile_cap, s - off)))
        return plan

    def _run_group(self, group: list[tuple]) -> list[np.ndarray]:
        """Worker-thread body: one batched launch per (signature,
        bucket, max_batch lanes); returns per-request outputs in
        request order."""
        import jax

        from ceph_tpu.ops.rs_kernels import gf_bitmatmul

        D = group[0][0]
        bits = self._bits(D)
        k = group[0][1].shape[0]
        out_rows = bits.shape[0] // 8
        outs = [
            np.empty((out_rows, rows.shape[1]), np.uint8)
            for _, rows, _ in group
        ]
        for w, lanes in self._bucket_plan(group).items():
            for at in range(0, len(lanes), self.max_batch):
                chunk = lanes[at:at + self.max_batch]
                b_real = len(chunk)
                # two batch shapes only (1 and max): every multi-lane
                # launch shares ONE compiled program per bucket, so the
                # warmup set stays tiny even on a slow-compile backend
                b = 1 if b_real == 1 else self.max_batch
                batch = np.zeros((b, k, w), np.uint8)
                for j, (gi, off, width) in enumerate(chunk):
                    batch[j, :, :width] = group[gi][1][:, off:off + width]
                shape_key = (bits.shape, b, k, w)
                cold = shape_key not in self._warm
                if cold:
                    self._warm.add(shape_key)
                    self.stats["cold_launches"] += 1
                    self.metrics.inc("cold_launches", w=w, b=b)
                # device-launch profiling span: bucket shape, lane
                # occupancy and block-until-ready time, per launch —
                # padding waste becomes visible in `ceph trace`/mgr
                from ceph_tpu.common.tracing import device_tracer
                from ceph_tpu.common.transfer_guard import (
                    no_implicit_transfers,
                )

                # transfers are EXPLICIT by construction: device_put
                # uploads the padded batch, device_get gathers the
                # whole launch result once (the by-design host exit —
                # rebuilt shards persist to the store); the guard
                # turns any implicit transfer sneaking in between
                # into a counted violation + host fallback
                with device_tracer().span(
                    "xla_launch", stage="device", kind="decode_batch",
                    w=w, b=b, b_real=b_real,
                    occupancy=round(b_real / b, 3), cold=cold,
                ) as _dsp, no_implicit_transfers("decode_batch"):
                    out = jax.device_get(jax.block_until_ready(
                        gf_bitmatmul(bits, jax.device_put(batch))))
                self.stats["launches"] += 1
                self.stats["batched_requests"] += b_real
                self.metrics.inc("launches", w=w, b=b)
                self.metrics.inc("occupied_lanes", w=w, b=b, by=b_real)
                self.metrics.inc("padded_lanes", w=w, b=b, by=b)
                real = sum(width for _, _, width in chunk)
                self.metrics.inc("occupied_bytes", w=w, b=b, by=real * k)
                self.metrics.inc("padded_bytes", w=w, b=b, by=b * k * w)
                for j, (gi, off, width) in enumerate(chunk):
                    outs[gi][:, off:off + width] = out[j, :, :width]
        return outs

    # -- warmup --------------------------------------------------------

    def prewarm(self, ec_impl, widths=None, *, erasure_counts=(1, 2),
                batches=None) -> int:
        """Compile every (signature-shape, batch, bucket) combination
        this aggregator can launch for ``ec_impl``'s code, so no XLA
        compile happens in the recovery path afterwards.  Blocking —
        call from daemon warmup (or via to_thread), never the I/O path.

        The bucket ladder [min_bucket .. tile_cap] is CLOSED (wider
        payloads split into tile_cap lanes), so warming the whole
        ladder covers every payload size this aggregator can ever see;
        ``widths`` is accepted as a hint for extra buckets but is not
        required.  ``erasure_counts`` covers the missing-shard
        multiplicities to warm (the decode matrix SHAPE — all XLA
        cares about — depends only on the count).  Returns the number
        of programs compiled.
        """
        import jax
        import jax.numpy as jnp

        from ceph_tpu.ops.compile_cache import ensure_persistent_cache
        from ceph_tpu.ops.rs_kernels import gf_bitmatmul

        # warmed executables persist to the on-disk XLA cache: a daemon
        # restart warm-starts from disk instead of recompiling
        ensure_persistent_cache()
        k = ec_impl.get_data_chunk_count()
        r = getattr(ec_impl, "rows_per_chunk", 1)
        if batches is None:
            batches = [1, self.max_batch]
        buckets = set()
        w = pow2_bucket(self.min_bucket, 1)
        while w <= self.tile_cap:
            buckets.add(w)
            w <<= 1
        for x in widths or ():
            buckets.add(pow2_bucket(min(x, self.tile_cap),
                                    self.min_bucket))
        n = 0
        wanted: list[tuple] = []   # every shape this call must see warm
        todo: list[tuple] = []     # the subset THIS thread compiles
        with self._warm_cv:
            for e in erasure_counts:
                if e > ec_impl.get_chunk_count() - k:
                    # impossible signature: more erasures than parity
                    continue
                bits_shape = (8 * e * r, 8 * k * r)
                for w in sorted(buckets):
                    for b in batches:
                        shape_key = (bits_shape, b, k * r, w)
                        wanted.append(shape_key)
                        if (shape_key in self._warm
                                or shape_key in self._warm_claimed):
                            continue
                        self._warm_claimed.add(shape_key)
                        todo.append(shape_key)
        try:
            for shape_key in todo:
                bits_shape, b, kr, w = shape_key
                jax.block_until_ready(gf_bitmatmul(
                    jnp.zeros(bits_shape, np.uint8),
                    jnp.zeros((b, kr, w), np.uint8)))
                with self._warm_cv:
                    self._warm.add(shape_key)
                    self._warm_cv.notify_all()
                n += 1
        finally:
            with self._warm_cv:
                self._warm_claimed.difference_update(todo)
                self._warm_cv.notify_all()
        # shapes another prewarm thread claimed first: wait for them —
        # callers rely on "prewarm returned => no cold launch"
        with self._warm_cv:
            self._warm_cv.wait_for(lambda: all(
                key in self._warm or key not in self._warm_claimed
                for key in wanted), timeout=120.0)
        self.stats["prewarmed_shapes"] += n
        self.metrics.inc("prewarmed_shapes", by=n)
        return n


_shared: DecodeAggregator | None = None


def shared() -> DecodeAggregator:
    """Process-wide aggregator (one compiled-shape set per process)."""
    global _shared
    if _shared is None:
        _shared = DecodeAggregator()
    return _shared


def reset_shared() -> None:
    """Test hook: drop the process-wide aggregator."""
    global _shared
    _shared = None
