"""Device-mesh parallelism for the storage data plane.

Maps Ceph's parallelism strategies (SURVEY.md §2.9) onto a
``jax.sharding.Mesh``:

- stripe-batch data parallelism (many objects/stripes at once) —
  the analogue of Ceph's per-PG sharded op queues and
  ``ParallelPGMapper`` thread fan-out;
- chunk sharding with psum-combined partial GF sums — the analogue of
  EC shard fan-out (``MOSDECSubOpWrite`` to k+m OSDs, reference
  src/osd/ECBackend.cc:943) when shard owners are co-located on one
  pod slice: the XOR combine rides ICI collectives instead of TCP.
"""

from ceph_tpu.parallel.decode_batcher import DecodeAggregator  # noqa: F401
from ceph_tpu.parallel.encode_farm import (  # noqa: F401
    batch_encode_dp,
    sharded_encode_tp,
)
