"""EncodeService: the in-daemon microbatching bridge onto the encode farm.

This is the production wiring of the multi-chip shardings
(ceph_tpu/parallel/encode_farm.py) into the I/O path: OSD write/recovery
ops running as concurrent asyncio tasks enqueue their GF(2^8) matrix
applications here; requests that land within one coalescing window and
share a matrix are padded into a single (B, k, S) batch and dispatched
through :func:`batch_encode_dp` over the device mesh.  A lone large
request takes the chunk-sharded :func:`sharded_encode_tp` path instead
(partial GF sums psum-combined over ICI).

This is the seam the reference implements as the ECSubWrite fan-out /
per-op `ECUtil::encode` loop (reference src/osd/ECCommon.cc:749
generate_transactions -> ECTransaction.cc:37 encode_and_write, and
src/osd/OSDMapMapping.h:18 ParallelPGMapper for the batch-parallel
pattern): independent per-PG ops become one batched TPU computation.

Single-device processes (or payloads under ``min_bytes``) fall back to
the caller's host/1-chip path — the service is then inactive and
``apply`` is never awaited (callers check :meth:`active`).
"""

from __future__ import annotations

import asyncio
import collections

import numpy as np

from ceph_tpu.common.metrics import BucketCounters
from ceph_tpu.parallel.decode_batcher import pow2_bucket

#: payloads smaller than this stay on the caller's local path — TPU/mesh
#: dispatch overhead dwarfs the math (SURVEY.md §7 hard part 3)
DEFAULT_MIN_BYTES = 32768

_BITS_CACHE_SIZE = 64


class EncodeService:
    """Coalesces concurrent GF matrix applications onto a device mesh.

    ``mesh`` must have a ``pg`` axis (stripe-batch data parallelism) and
    may have a ``shard`` axis (chunk sharding for the tp path).  With
    ``mesh=None`` the service is inactive and callers use their local
    path.
    """

    def __init__(self, mesh=None, *, device=None,
                 min_bytes: int = DEFAULT_MIN_BYTES,
                 window_s: float = 0.001):
        self.mesh = mesh
        # single-device mode (round-3 weak #8 closed): with one
        # accelerator and no mesh, the microbatching window still
        # coalesces concurrent per-PG ops into ONE dispatch — the
        # relay-amortization insight from PERF_LAB applied to the
        # production I/O path.  Requests concatenate along S (GF
        # matmul is column-independent), so no batch padding at all.
        self.device = device
        self.min_bytes = min_bytes
        self.window_s = window_s
        self._pending: dict[bytes, list[tuple]] = {}
        self._flush_handle = None
        self._bits_cache: collections.OrderedDict = collections.OrderedDict()
        self.stats = collections.Counter()
        #: compiled dispatch shapes (by prewarm or earlier launches); a
        #: launch outside this set pays an XLA compile — the warmup
        #: discipline (daemon map-time prewarm) keeps this at zero
        #: inside the I/O path
        self._warm: set[tuple] = set()
        self.metrics = BucketCounters("encode_farm")

    # -- gating --------------------------------------------------------

    def active(self) -> bool:
        return self.mesh is not None or self.device is not None

    def usable(self, rows: np.ndarray) -> bool:
        return self.active() and rows.size >= self.min_bytes

    # -- request side --------------------------------------------------

    async def apply(self, M: np.ndarray, rows: np.ndarray) -> np.ndarray:
        """``M @ rows`` over GF(2^8), batched with concurrent callers.

        M is an (out, k) byte matrix (coding or cached decode matrix);
        rows is (k, S) uint8.  Returns (out, S) uint8.
        """
        assert self.active()
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        key = M.shape[0].to_bytes(2, "little") + M.tobytes()
        self._pending.setdefault(key, []).append((M, rows, fut))
        if self._flush_handle is None:
            self._flush_handle = loop.call_later(self.window_s, self._flush)
        return await fut

    # -- dispatch side -------------------------------------------------

    def _bits(self, M: np.ndarray):
        import jax

        from ceph_tpu.ops.gf256 import gf_matrix_to_bitmatrix

        key = M.shape[0].to_bytes(2, "little") + M.tobytes()
        hit = self._bits_cache.get(key)
        if hit is None:
            bits = gf_matrix_to_bitmatrix(M)
            if self.mesh is not None:
                # replicate across the mesh at cache-fill time so no
                # launch pays a per-dispatch reshard of the matrix
                from ceph_tpu.parallel.encode_farm import (
                    replicated_sharding,
                )

                hit = jax.device_put(bits, replicated_sharding(self.mesh))
            else:
                hit = jax.device_put(bits)
            self._bits_cache[key] = hit
            if len(self._bits_cache) > _BITS_CACHE_SIZE:
                self._bits_cache.popitem(last=False)
        else:
            self._bits_cache.move_to_end(key)
        return hit

    def _flush(self) -> None:
        """call_later callback: hand every pending group to a worker
        thread.  The JAX dispatch (and any first-use XLA compile) must
        NOT run on the event loop — it would stall heartbeats and op
        processing for every daemon in the process."""
        self._flush_handle = None
        pending, self._pending = self._pending, {}
        loop = asyncio.get_running_loop()
        for group in pending.values():
            loop.create_task(self._dispatch_group(group))

    async def _dispatch_group(self, group: list[tuple]) -> None:
        try:
            outs = await asyncio.to_thread(self._run_group, group)
        except Exception:
            # farm failure: answer every waiter from the host path
            # (always correct), don't fail client ops
            from ceph_tpu.ops.gf256 import gf_matmul

            self.stats["fallbacks"] += 1
            outs = await asyncio.to_thread(
                lambda: [gf_matmul(M, rows) for M, rows, _ in group])
        for (_, _, fut), out in zip(group, outs):
            if not fut.done():
                fut.set_result(out)

    def _run_group(self, group: list[tuple]) -> list[np.ndarray]:
        """Worker-thread body: one farm dispatch for the whole group;
        returns per-request outputs in order."""
        import jax

        from ceph_tpu.parallel.encode_farm import (
            batch_encode_dp,
            sharded_encode_tp,
        )

        # NOTE on guard coverage: the mesh (shard_map) dispatches below
        # are NOT wrapped in no_implicit_transfers — XLA's multi-device
        # execution path ships tiny internal scalar constants
        # (observed: replicated uint8[] avals) host->device on every
        # dispatch, which the guard cannot tell apart from real payload
        # round-trips.  Payload transfers here are explicit and
        # mesh-sharded at source (device_put with NamedSharding, no
        # reshard hop); the single-device paths — where the
        # batched-vs-host gap actually lives — run fully guarded
        # (_run_group_single, decode/scrub batchers, mgr analytics).

        M = group[0][0]
        bits = self._bits(M)
        k = M.shape[1]

        if self.mesh is None:
            return self._run_group_single(group, bits, k)

        if len(group) == 1 and "shard" in self.mesh.shape:
            _, rows, _fut = group[0]
            nsh = self.mesh.shape["shard"]
            if nsh > 1 and k % nsh == 0:
                # same fixed-bucket discipline as the dp path: pad S to
                # its pow2 bucket so the tp program shape set is bounded
                S = pow2_bucket(rows.shape[1], 1)
                if S != rows.shape[1]:
                    padded = np.zeros((rows.shape[0], S), np.uint8)
                    padded[:, : rows.shape[1]] = rows
                else:
                    padded = rows
                from ceph_tpu.parallel.encode_farm import (
                    tp_data_sharding,
                )

                with self._note_shape(("tp", bits.shape, k, S), w=S):
                    out = jax.device_get(sharded_encode_tp(
                        self.mesh, bits, jax.device_put(
                            padded, tp_data_sharding(self.mesh))))
                self.stats["tp_dispatches"] += 1
                self.metrics.inc("launches", w=S)
                return [np.ascontiguousarray(out[:, : rows.shape[1]])]

        # data-parallel batch: pad each request's S to a fixed
        # power-of-two width bucket and the batch dim to a power-of-two
        # multiple of the device count, one sharded dispatch — launch
        # shapes come from a tiny fixed set, so every compile happens
        # at prewarm, never mid-I/O
        ndev = 1
        for ax in self.mesh.shape.values():
            ndev *= ax
        widths = [rows.shape[1] for _, rows, _ in group]
        S = pow2_bucket(max(widths), 1)
        B = ndev * pow2_bucket(-(-len(group) // ndev), 1)
        batch = np.zeros((B, k, S), np.uint8)
        for i, (_, rows, _) in enumerate(group):
            batch[i, :, : rows.shape[1]] = rows
        axes = tuple(a for a in ("pg", "shard") if a in self.mesh.shape)
        from ceph_tpu.parallel.encode_farm import dp_batch_sharding

        with self._note_shape(("dp", bits.shape, B, k, S), w=S, b=B,
                              b_real=len(group)):
            out = jax.device_get(batch_encode_dp(
                self.mesh, bits, jax.device_put(
                    batch, dp_batch_sharding(self.mesh, axes)),
                axis=axes))
        self.stats["dp_dispatches"] += 1
        self.stats["coalesced"] += len(group)
        self.metrics.inc("launches", w=S, b=B)
        self.metrics.inc("occupied_lanes", w=S, b=B, by=len(group))
        self.metrics.inc("padded_lanes", w=S, b=B, by=B)
        self.metrics.inc("occupied_bytes", w=S, b=B, by=sum(widths) * k)
        self.metrics.inc("padded_bytes", w=S, b=B, by=B * k * S)
        return [
            np.ascontiguousarray(out[i, :, : rows.shape[1]])
            for i, (_, rows, _) in enumerate(group)
        ]

    def _note_shape(self, shape_key: tuple, *, w: int, b: int = 1,
                    b_real: int = 1):
        """Track whether a launch shape was already compiled (a miss is
        a cold in-path compile the warmup should have covered) and
        return the device-launch profiling span wrapping the launch."""
        cold = shape_key not in self._warm
        if cold:
            self._warm.add(shape_key)
            self.stats["cold_launches"] += 1
            self.metrics.inc("cold_launches", w=w, b=b)
        from ceph_tpu.common.tracing import device_tracer

        return device_tracer().span(
            "xla_launch", stage="device",
            kind=f"encode_{shape_key[0]}", w=w, b=b, b_real=b_real,
            occupancy=round(b_real / max(b, 1), 3), cold=cold,
        )


    def _run_group_single(self, group: list[tuple], bits, k) -> list[np.ndarray]:
        """Single-device dispatch: concatenate every request's rows
        along S (column-independent GF matmul), pad to a power-of-two
        width so jit shapes stay bounded, ONE kernel launch for the
        whole window."""
        import jax

        from ceph_tpu.common.transfer_guard import no_implicit_transfers
        from ceph_tpu.ops.rs_kernels import BitmatrixCodec

        widths = [rows.shape[1] for _, rows, _ in group]
        total = sum(widths)
        S = pow2_bucket(total, 1)  # fixed pow2 width bucket
        big = np.zeros((k, S), np.uint8)
        off = 0
        for (_, rows, _), w in zip(group, widths):
            big[:, off:off + w] = rows
            off += w
        with self._note_shape(("single", bits.shape, k, S), w=S,
                              b_real=len(group)), \
                no_implicit_transfers("encode_single"):
            out = jax.device_get(BitmatrixCodec._apply(
                bits, jax.device_put(big), None))
        self.stats["single_dispatches"] += 1
        self.stats["coalesced"] += len(group)
        self.metrics.inc("launches", w=S)
        self.metrics.inc("occupied_bytes", w=S, by=total * k)
        self.metrics.inc("padded_bytes", w=S, by=k * S)
        outs = []
        off = 0
        for w in widths:
            outs.append(np.ascontiguousarray(out[:, off:off + w]))
            off += w
        return outs

    # -- warmup --------------------------------------------------------

    def prewarm(self, M: np.ndarray, widths, *, coalesce: int = 16) -> int:
        """Compile the fixed-bucket launch shapes this service can hit
        for matrix ``M`` and per-request payload widths ``widths``
        (coalescing concatenates/batches up to ``coalesce`` concurrent
        requests).  Blocking — run at daemon warmup, never in the I/O
        path.  Returns the number of programs compiled."""
        if not self.active():
            return 0
        import jax
        import jax.numpy as jnp

        from ceph_tpu.ops.compile_cache import ensure_persistent_cache
        from ceph_tpu.ops.rs_kernels import BitmatrixCodec
        from ceph_tpu.parallel.encode_farm import batch_encode_dp

        ensure_persistent_cache()  # warmed programs persist across runs

        bits = self._bits(np.asarray(M, np.uint8))
        k = M.shape[1]
        buckets: set[int] = set()
        for w in widths:
            f = 1
            while f <= coalesce:
                buckets.add(pow2_bucket(w * f, 1))
                f <<= 1
        n = 0
        if self.mesh is not None:
            from ceph_tpu.parallel.encode_farm import dp_batch_sharding

            ndev = 1
            for ax in self.mesh.shape.values():
                ndev *= ax
            axes = tuple(
                a for a in ("pg", "shard") if a in self.mesh.shape)
            bbs = sorted({
                ndev * pow2_bucket(-(-g // ndev), 1)
                for g in range(1, coalesce + 1)
            })
            # warm with the SAME input shardings the dispatch path
            # uses (executables are keyed by sharding, not just shape)
            dp_spec = dp_batch_sharding(self.mesh, axes)
            for S in sorted(pow2_bucket(w, 1) for w in widths):
                for B in bbs:
                    key = ("dp", bits.shape, B, k, S)
                    if key in self._warm:
                        continue
                    jax.block_until_ready(batch_encode_dp(
                        self.mesh, bits,
                        jax.device_put(
                            np.zeros((B, k, S), np.uint8), dp_spec),
                        axis=axes))
                    self._warm.add(key)
                    n += 1
            nsh = self.mesh.shape.get("shard", 1)
            if nsh > 1 and k % nsh == 0:
                from ceph_tpu.parallel.encode_farm import (
                    sharded_encode_tp,
                    tp_data_sharding,
                )

                tp_spec = tp_data_sharding(self.mesh)
                for S in sorted(pow2_bucket(w, 1) for w in widths):
                    key = ("tp", bits.shape, k, S)
                    if key in self._warm:
                        continue
                    jax.block_until_ready(sharded_encode_tp(
                        self.mesh, bits, jax.device_put(
                            np.zeros((k, S), np.uint8), tp_spec)))
                    self._warm.add(key)
                    n += 1
        else:
            for S in sorted(buckets):
                key = ("single", bits.shape, k, S)
                if key in self._warm:
                    continue
                jax.block_until_ready(BitmatrixCodec._apply(
                    bits, jnp.zeros((k, S), np.uint8), None))
                self._warm.add(key)
                n += 1
        self.stats["prewarmed_shapes"] += n
        self.metrics.inc("prewarmed_shapes", by=n)
        return n


_shared: EncodeService | None = None


def shared() -> EncodeService:
    """Process-wide service; builds a mesh over all local devices on
    first use.  A single ACCELERATOR device gets single-device
    coalescing mode (cpu-only processes stay inactive so host paths
    keep their exact semantics/costs)."""
    global _shared
    if _shared is None:
        mesh = None
        device = None
        try:
            import jax
            from jax.sharding import Mesh

            devs = jax.devices()
            if len(devs) > 1:
                nsh = 2 if len(devs) % 2 == 0 else 1
                devgrid = np.asarray(devs).reshape(len(devs) // nsh, nsh)
                mesh = Mesh(devgrid, ("pg", "shard"))
            elif devs and jax.default_backend() not in ("cpu",):
                device = devs[0]
        except Exception:
            mesh = None
        _shared = EncodeService(mesh, device=device)
    return _shared


def reset_shared() -> None:
    """Test hook: drop the process-wide service."""
    global _shared
    _shared = None
