"""ScrubVerifier: batched deep-scrub verification with fixed shapes.

Deep scrub was the last per-object host loop in the EC data plane:
`ScrubMixin._scrub_object` verified one object at a time with host
`native.crc32c` and re-encoded parity per object (when it checked
parity at all).  Scrub chunks are a stream of small independent
checks — the same launch-bound regime the recovery-decode aggregator
(`parallel/decode_batcher.py`) batches, per the repair-pipelining
discipline (arxiv 1908.01527) and program-shaped XOR verification
(arxiv 2108.02692).  This module is that layer for scrub:

- concurrent in-flight scrub checks — across objects AND across PGs
  (the verifier is process-wide, so co-scheduled PG scrubs sharing an
  EC profile coalesce) — are collected during a short window;
- every shard payload splits into the CLOSED power-of-two bucket
  ladder (`ecutil.bucket_lanes`: pad to pow2 below the 64 KiB tile
  cap, fixed tile_cap column lanes above it), and two kinds of fixed
  -shape launches cover a whole group:

  1. **batched crc32c**: a (B, W) stack of payload lanes is ONE
     GF(2) bit-matmul (`ops.hashing.batched_crc32c_device`) — crc32c
     is GF(2)-linear, so the device returns every lane's crc
     contribution at once; host-side folding via native
     ``crc32c_zeros`` / ``crc32c_unadvance`` recovers the exact
     per-shard crc32c (bit-identical to the per-object host loop);
  2. **RS re-encode compare**: (B, k, W) data-shard lanes re-encode
     through the profile's bit-matrix and compare against the stored
     (B, m, W) parity lanes on device (`ops.rs_kernels.
     gf_encode_compare`), returning only a (B, m) mismatch mask —
     parity never materializes off-device.  This catches silent
     parity divergence that per-shard crc chains cannot see.

- launch shapes come from the tiny fixed set (#width-buckets x
  #batch-buckets [x #profiles for the compare kernel]), all compiled
  by :meth:`prewarm` at daemon map-install — after warmup no XLA
  compile can occur inside the scrub path, proven by the
  ``cold_launches`` counter.

Padding is exact in both kernels: encode of zero columns is zero
columns, and crc of a zero-padded lane is the injective linear
advance of the true crc — so batched results are bit-identical to the
per-object host path (pinned by tests/test_scrub_batcher.py).
"""

from __future__ import annotations

import asyncio
import collections
import threading

import numpy as np

from ceph_tpu.common.metrics import BucketCounters
from ceph_tpu.parallel.decode_batcher import (
    DEFAULT_MAX_BATCH,
    DEFAULT_MIN_BUCKET,
    DEFAULT_TILE_CAP,
)

#: ceiling on the lane dimension of one batched crc launch (crc lanes
#: are single shard payloads, so many more fit per launch than the
#: (k, W) re-encode items)
DEFAULT_CRC_LANES = 32

_SEED = 0xFFFFFFFF
_BITS_CACHE_SIZE = 64


class ObjectCheck:
    """One object's batched verification result.

    ``crcs`` maps shard id -> crc32c of the shard payload (seed -1,
    reference ceph_crc32c semantics — bit-identical to the host
    ``native.crc32c`` loop).  ``parity_bad`` is the set of shard ids
    whose stored parity disagrees with a re-encode of the data shards,
    or None when the parity check was not applicable (caller falls
    back to the host re-encode path)."""

    __slots__ = ("crcs", "parity_bad")

    def __init__(self, crcs: dict[int, int],
                 parity_bad: frozenset[int] | None):
        self.crcs = crcs
        self.parity_bad = parity_bad


class ScrubVerifier:
    """Coalesces concurrent deep-scrub checks into fixed-shape batched
    crc32c + re-encode-compare launches.

    Device-agnostic: both kernels are jitted XLA paths that run
    bit-exactly on CPU and TPU; any dispatch failure answers the
    affected lanes from the native host path, so behavior is always
    identical to per-object verification.
    """

    def __init__(self, *, window_s: float = 0.002,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 crc_lanes: int = DEFAULT_CRC_LANES,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 tile_cap: int = DEFAULT_TILE_CAP):
        self.window_s = window_s
        self.max_batch = max_batch
        self.crc_lanes = crc_lanes
        self.min_bucket = min_bucket
        self.tile_cap = tile_cap
        #: bucket width -> [(lane view, width, fut)] awaiting a crc
        self._crc_pending: dict[int, list[tuple]] = {}
        #: (matrix signature, bucket) -> [(C, data, parity, fut)]
        self._enc_pending: dict[tuple, list[tuple]] = {}
        self._flush_handle = None
        self._bits_cache: collections.OrderedDict = collections.OrderedDict()
        self._warm: set[tuple] = set()
        # guards ONLY the warm/claimed sets — never held across a
        # compile (device-sync-under-lock); see decode_batcher for the
        # claim/compile/notify pattern
        self._warm_lock = threading.Lock()
        self._warm_cv = threading.Condition(self._warm_lock)
        self._warm_claimed: set[tuple] = set()
        self.stats = collections.Counter()
        self.metrics = BucketCounters("scrub_verify_batch")

    # -- gating --------------------------------------------------------

    def active(self) -> bool:
        return True

    @staticmethod
    def _parity_eligible(ec_impl, payloads) -> bool:
        """The re-encode compare covers plain matrix codes with every
        shard present at one length; anything else answers
        ``parity_bad=None`` and the scrubber keeps its host path."""
        from ceph_tpu.ec.plugins.matrix_base import MatrixErasureCode

        if not isinstance(ec_impl, MatrixErasureCode):
            return False
        if ec_impl.rows_per_chunk != 1 or ec_impl.get_sub_chunk_count() != 1:
            return False
        n = ec_impl.get_chunk_count()
        shards = {ec_impl.chunk_index(c) for c in range(n)}
        if set(payloads) != shards:
            return False
        sizes = {len(p) for p in payloads.values()}
        return len(sizes) == 1 and sizes.pop() > 0

    # -- request side --------------------------------------------------

    async def verify_object(
        self, ec_impl, payloads: dict[int, np.ndarray]
    ) -> ObjectCheck | None:
        """Verify one object's shard payloads, coalescing the device
        work with every other concurrent caller.  Returns None when the
        whole check could not run batched (callers then take the
        per-object host path verbatim)."""
        from ceph_tpu.osd.ecutil import bucket_lanes

        loop = asyncio.get_running_loop()
        arrs = {
            s: (np.frombuffer(bytes(p), dtype=np.uint8)
                if isinstance(p, (bytes, bytearray, memoryview))
                else np.ascontiguousarray(
                    np.asarray(p, dtype=np.uint8).reshape(-1)))
            for s, p in payloads.items()
        }
        crc_futs: dict[int, list[tuple[int, int, asyncio.Future]]] = {}
        for s, arr in arrs.items():
            lanes = bucket_lanes(
                arr.nbytes, min_bucket=self.min_bucket,
                tile_cap=self.tile_cap)
            futs = []
            for off, width, bucket in lanes:
                fut = loop.create_future()
                self._crc_pending.setdefault(bucket, []).append(
                    (arr[off:off + width], width, fut))
                futs.append((width, bucket, fut))
            crc_futs[s] = futs

        enc_futs: list[asyncio.Future] | None = None
        k = m = 0
        if ec_impl is not None and self._parity_eligible(ec_impl, arrs):
            k = ec_impl.get_data_chunk_count()
            m = ec_impl.get_chunk_count() - k
            C = np.asarray(ec_impl.coding_matrix, dtype=np.uint8)
            sig = C.shape[0].to_bytes(2, "little") + C.tobytes()
            size = len(next(iter(arrs.values())))
            enc_futs = []
            for off, width, bucket in bucket_lanes(
                    size, min_bucket=self.min_bucket,
                    tile_cap=self.tile_cap):
                fut = loop.create_future()
                data = np.stack([
                    arrs[ec_impl.chunk_index(c)][off:off + width]
                    for c in range(k)
                ])
                parity = np.stack([
                    arrs[ec_impl.chunk_index(k + j)][off:off + width]
                    for j in range(m)
                ])
                self._enc_pending.setdefault((sig, bucket), []).append(
                    (C, data, parity, fut))
                enc_futs.append(fut)

        self.stats["objects"] += 1
        if self._flush_handle is None and (
                self._crc_pending or self._enc_pending):
            self._flush_handle = loop.call_later(self.window_s, self._flush)

        from ceph_tpu.native import crc32c_zeros

        from ceph_tpu.ops.hashing import crc32c_unadvance

        try:
            crcs: dict[int, int] = {}
            for s, futs in crc_futs.items():
                c = _SEED
                pad = 0
                for width, bucket, fut in futs:
                    c = crc32c_zeros(bucket, c) ^ await fut
                    pad = bucket - width
                crcs[s] = crc32c_unadvance(c, pad)
            parity_bad: frozenset[int] | None = None
            if enc_futs is not None:
                bad: set[int] = set()
                for fut in enc_futs:
                    mask = await fut
                    bad.update(
                        ec_impl.chunk_index(k + j)
                        for j in range(m) if mask[j]
                    )
                parity_bad = frozenset(bad)
            return ObjectCheck(crcs, parity_bad)
        except Exception:
            self.stats["fallbacks"] += 1
            return None

    # -- dispatch side -------------------------------------------------

    def _flush(self) -> None:
        """call_later callback: hand pending groups to worker threads —
        JAX dispatch must not run on the event loop."""
        self._flush_handle = None
        crc_pending, self._crc_pending = self._crc_pending, {}
        enc_pending, self._enc_pending = self._enc_pending, {}
        loop = asyncio.get_running_loop()
        for bucket, group in crc_pending.items():
            loop.create_task(self._dispatch(
                group, lambda g, w=bucket: self._run_crc_group(w, g),
                lambda g, w=bucket: self._host_crc_group(w, g)))
        for (_sig, bucket), group in enc_pending.items():
            loop.create_task(self._dispatch(
                group, lambda g, w=bucket: self._run_enc_group(w, g),
                self._host_enc_group))

    async def _dispatch(self, group, run, host_fallback) -> None:
        try:
            outs = await asyncio.to_thread(run, group)
        except Exception:
            self.stats["dispatch_fallbacks"] += 1
            outs = await asyncio.to_thread(host_fallback, group)
        for item, out in zip(group, outs):
            fut = item[-1]
            if not fut.done():
                fut.set_result(out)

    def _crc_mat(self, bucket: int):
        import jax.numpy as jnp

        from ceph_tpu.ops.compile_cache import ensure_persistent_cache
        from ceph_tpu.ops.hashing import crc32c_matrix

        key = ("crc", bucket)
        hit = self._bits_cache.get(key)
        if hit is None:
            ensure_persistent_cache()
            hit = jnp.asarray(crc32c_matrix(bucket))
            self._bits_cache[key] = hit
            if len(self._bits_cache) > _BITS_CACHE_SIZE:
                self._bits_cache.popitem(last=False)
        else:
            self._bits_cache.move_to_end(key)
        return hit

    def _enc_bits(self, C: np.ndarray):
        import jax.numpy as jnp

        from ceph_tpu.ops.compile_cache import ensure_persistent_cache
        from ceph_tpu.ops.gf256 import gf_matrix_to_bitmatrix

        key = ("enc", C.shape[0].to_bytes(2, "little") + C.tobytes())
        hit = self._bits_cache.get(key)
        if hit is None:
            ensure_persistent_cache()
            hit = jnp.asarray(gf_matrix_to_bitmatrix(C))
            self._bits_cache[key] = hit
            if len(self._bits_cache) > _BITS_CACHE_SIZE:
                self._bits_cache.popitem(last=False)
        else:
            self._bits_cache.move_to_end(key)
        return hit

    def _note_launch(self, shape_key, kind, w, b, b_real,
                     real_bytes, padded_bytes):
        cold = shape_key not in self._warm
        if cold:
            self._warm.add(shape_key)
            self.stats["cold_launches"] += 1
            self.metrics.inc("cold_launches", w=w, b=b, k=kind)
        self.stats["launches"] += 1
        self.stats[f"{kind}_launches"] += 1
        self.stats["batched_lanes"] += b_real
        self.metrics.inc("launches", w=w, b=b, k=kind)
        self.metrics.inc("occupied_lanes", w=w, b=b, k=kind, by=b_real)
        self.metrics.inc("padded_lanes", w=w, b=b, k=kind, by=b)
        self.metrics.inc("occupied_bytes", w=w, b=b, k=kind, by=real_bytes)
        self.metrics.inc("padded_bytes", w=w, b=b, k=kind, by=padded_bytes)
        # device-launch profiling span (common/tracing.device_tracer):
        # wraps the launch via the returned context manager, tagged
        # with bucket shape, occupancy and cold-compile verdict
        from ceph_tpu.common.tracing import device_tracer

        return device_tracer().span(
            "xla_launch", stage="device", kind=f"scrub_{kind}",
            w=w, b=b, b_real=b_real, occupancy=round(b_real / b, 3),
            cold=cold,
        )

    def _run_crc_group(self, w: int, group: list[tuple]) -> list[int]:
        """Worker-thread body: batched crc32c launches over one bucket;
        returns each lane's raw device crc (L_W of the padded lane)."""
        import jax

        from ceph_tpu.common.transfer_guard import no_implicit_transfers
        from ceph_tpu.ops.hashing import batched_crc32c_device

        mat = self._crc_mat(w)
        outs: list[int] = [0] * len(group)
        for at in range(0, len(group), self.crc_lanes):
            chunk = group[at:at + self.crc_lanes]
            b_real = len(chunk)
            # two batch shapes only (1 and max): one compiled program
            # per bucket regardless of how many lanes coalesced
            b = 1 if b_real == 1 else self.crc_lanes
            batch = np.zeros((b, w), np.uint8)
            for j, (arr, width, _f) in enumerate(chunk):
                batch[j, :width] = arr
            # explicit put/get only: one upload of the lane batch, one
            # (B,)-word gather of the crc contributions (the by-design
            # host exit — crcs fold host-side via crc32c_zeros algebra)
            with self._note_launch(
                ("crc", b, w), "crc", w, b, b_real,
                sum(width for _, width, _ in chunk), b * w,
            ), no_implicit_transfers("scrub_crc"):
                out = jax.device_get(jax.block_until_ready(
                    batched_crc32c_device(mat, jax.device_put(batch))))
            for j in range(b_real):
                outs[at + j] = int(out[j])
        return outs

    @staticmethod
    def _host_crc_group(w: int, group: list[tuple]) -> list[int]:
        from ceph_tpu.native import crc32c, crc32c_zeros

        # L_W of the padded lane == advance of the seed-0 crc through
        # the pad, so the host answer folds identically downstream
        return [
            crc32c_zeros(w - width, crc32c(arr, 0))
            for arr, width, _f in group
        ]

    def _run_enc_group(self, w: int, group: list[tuple]) -> list[np.ndarray]:
        """Worker-thread body: batched re-encode-compare launches for
        one (profile, bucket); returns each item's (m,) mismatch mask."""
        import jax

        from ceph_tpu.common.transfer_guard import no_implicit_transfers
        from ceph_tpu.ops.rs_kernels import gf_encode_compare

        C = group[0][0]
        bits = self._enc_bits(C)
        m, k = C.shape
        outs: list[np.ndarray] = [None] * len(group)
        for at in range(0, len(group), self.max_batch):
            chunk = group[at:at + self.max_batch]
            b_real = len(chunk)
            b = 1 if b_real == 1 else self.max_batch
            data = np.zeros((b, k, w), np.uint8)
            parity = np.zeros((b, m, w), np.uint8)
            for j, (_C, d, p, _f) in enumerate(chunk):
                data[j, :, :d.shape[1]] = d
                parity[j, :, :p.shape[1]] = p
            # explicit put/get only; the gather is the tiny (B, m)
            # mismatch mask — parity itself never leaves the device
            with self._note_launch(
                (bits.shape, b, k, w), "enc", w, b, b_real,
                sum((k + m) * d.shape[1] for _C, d, _p, _f in chunk),
                b * (k + m) * w,
            ), no_implicit_transfers("scrub_enc"):
                out = jax.device_get(jax.block_until_ready(
                    gf_encode_compare(bits, jax.device_put(data),
                                      jax.device_put(parity))))
            for j in range(b_real):
                outs[at + j] = out[j]
        return outs

    @staticmethod
    def _host_enc_group(group: list[tuple]) -> list[np.ndarray]:
        from ceph_tpu.ops.gf256 import gf_matmul

        return [
            np.any(gf_matmul(C, d) != p, axis=-1)
            for C, d, p, _f in group
        ]

    # -- warmup --------------------------------------------------------

    def prewarm(self, ec_impl=None, widths=None, *, batches=None) -> int:
        """Compile every launch shape this verifier can dispatch: the
        crc kernel over the full bucket ladder, plus the re-encode
        compare for ``ec_impl``'s code when given.  Blocking — call
        from daemon warmup (map install), never the scrub path.
        Returns the number of programs compiled."""
        import jax
        import jax.numpy as jnp

        from ceph_tpu.ops.compile_cache import ensure_persistent_cache
        from ceph_tpu.ops.hashing import batched_crc32c_device
        from ceph_tpu.ops.rs_kernels import gf_encode_compare

        ensure_persistent_cache()
        buckets = set()
        w = self.min_bucket
        while w <= self.tile_cap:
            buckets.add(w)
            w <<= 1
        for x in widths or ():
            x = max(min(x, self.tile_cap), self.min_bucket, 1)
            buckets.add(1 << (x - 1).bit_length())
        n = 0
        wanted: list[tuple] = []
        todo: list[tuple] = []  # (key, compile thunk) claimed by US
        ec_bits = None
        if ec_impl is not None and getattr(
                ec_impl, "rows_per_chunk", 1) == 1 and hasattr(
                ec_impl, "coding_matrix"):
            C = np.asarray(ec_impl.coding_matrix, dtype=np.uint8)
            ec_m, ec_k = C.shape
            ec_bits = self._enc_bits(C)
        with self._warm_cv:
            for w in sorted(buckets):
                for b in (1, self.crc_lanes):
                    key = ("crc", b, w)
                    wanted.append(key)
                    if key in self._warm or key in self._warm_claimed:
                        continue
                    self._warm_claimed.add(key)
                    todo.append(key)
            if ec_bits is not None:
                for w in sorted(buckets):
                    for b in (batches or (1, self.max_batch)):
                        key = (ec_bits.shape, b, ec_k, w)
                        wanted.append(key)
                        if key in self._warm or key in self._warm_claimed:
                            continue
                        self._warm_claimed.add(key)
                        todo.append(key)
        try:
            for key in todo:
                if key[0] == "crc":
                    _, b, w = key
                    jax.block_until_ready(batched_crc32c_device(
                        self._crc_mat(w), jnp.zeros((b, w), np.uint8)))
                else:
                    _, b, k_, w = key
                    jax.block_until_ready(gf_encode_compare(
                        ec_bits, jnp.zeros((b, k_, w), np.uint8),
                        jnp.zeros((b, ec_m, w), np.uint8)))
                with self._warm_cv:
                    self._warm.add(key)
                    self._warm_cv.notify_all()
                n += 1
        finally:
            with self._warm_cv:
                self._warm_claimed.difference_update(todo)
                self._warm_cv.notify_all()
        with self._warm_cv:
            self._warm_cv.wait_for(lambda: all(
                key in self._warm or key not in self._warm_claimed
                for key in wanted), timeout=120.0)
        self.stats["prewarmed_shapes"] += n
        self.metrics.inc("prewarmed_shapes", by=n)
        return n


_shared: ScrubVerifier | None = None


def shared() -> ScrubVerifier:
    """Process-wide verifier (one compiled-shape set per process, so
    co-hosted daemons' scrubs coalesce across PGs)."""
    global _shared
    if _shared is None:
        _shared = ScrubVerifier()
    return _shared


def reset_shared() -> None:
    """Test hook: drop the process-wide verifier."""
    global _shared
    _shared = None
