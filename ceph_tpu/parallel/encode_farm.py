"""Multi-chip erasure-encode farms over a jax.sharding.Mesh.

Two sharding strategies, composable on a 2-D mesh ('pg', 'shard'):

- **Data parallel over stripes** (:func:`batch_encode_dp`): a batch of
  independent stripes (B, k, S) is sharded on B; every device encodes
  its stripes locally, no communication.  This is the TPU analogue of
  Ceph farming independent PG writes across OSD worker shards
  (reference: src/osd/OSD.cc op_shardedwq, src/osd/OSDMapMapping.h:18
  ParallelPGMapper).

- **Chunk-sharded ("tensor parallel") encode**
  (:func:`sharded_encode_tp`): the k data chunks of one huge object are
  sharded across devices; each device computes the partial GF(2)
  bit-matmul for its chunk slice and the partial int32 accumulators are
  combined with ``psum`` over ICI before the mod-2 — GF(2^8) addition is
  XOR, and XOR == integer-sum mod 2, so the collective is a plain psum.
  This is the seam where Ceph's ECSubWrite shard fan-out over TCP
  (src/osd/ECBackend.cc:943, ECCommon.cc:749) becomes an XLA collective
  when shard owners live on one slice.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # jax >= 0.5: top-level shard_map, replication check kw is check_vma
    from jax import shard_map as _shard_map_fn

    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x: experimental module, kw is check_rep
    from jax.experimental.shard_map import shard_map as _shard_map_fn

    _CHECK_KW = "check_rep"
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ceph_tpu.ops.rs_kernels import pack_bits, unpack_bits


def shard_map(f=None, **kw):
    """Version-compat facade over jax's shard_map (the replication-check
    keyword was renamed across releases)."""
    if "check_vma" in kw and _CHECK_KW != "check_vma":
        kw[_CHECK_KW] = kw.pop("check_vma")
    if f is None:
        return functools.partial(_shard_map_fn, **kw)
    return _shard_map_fn(f, **kw)


# -- input shardings --------------------------------------------------------
#
# Callers must device_put operands with THESE shardings (ctlint's
# transfer discipline: explicit, correctly-placed uploads — an
# unsharded put costs a reshard hop on every dispatch, and compiled
# executables are keyed by input sharding, so prewarm and dispatch
# must agree).  Single-homed here, beside the in_specs they mirror.

def dp_batch_sharding(mesh: Mesh, axis="pg") -> NamedSharding:
    """Sharding for :func:`batch_encode_dp`'s (B, k, S) stripe batch."""
    return NamedSharding(mesh, P(axis, None, None))


def tp_data_sharding(mesh: Mesh, axis: str = "shard") -> NamedSharding:
    """Sharding for :func:`sharded_encode_tp`'s (k, S) chunk rows."""
    return NamedSharding(mesh, P(axis, None))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Full replication (the bit-matrix operand of the dp path)."""
    return NamedSharding(mesh, P())


def batch_encode_dp(mesh: Mesh, bitmat: jax.Array, batch: jax.Array, axis: str = "pg"):
    """Encode a (B, k, S) stripe batch sharded over ``axis``; returns
    (B, m, S) parity with the same batch sharding."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(axis, None, None)),
        out_specs=P(axis, None, None),
        check_vma=False,
    )
    def _encode(bm, local):
        bits = unpack_bits(local).astype(jnp.int8)
        acc = jnp.einsum(
            "pq,bqs->bps", bm.astype(jnp.int8), bits,
            preferred_element_type=jnp.int32,
        )
        return pack_bits(acc & 1)

    return _encode(bitmat, batch)


def sharded_encode_tp(mesh: Mesh, bitmat: jax.Array, data: jax.Array, axis: str = "shard"):
    """Encode (k, S) data whose chunk dimension k is sharded over
    ``axis``; partial int32 accumulators are psum-combined then reduced
    mod 2.  Returns replicated (m, S) parity."""
    n = mesh.shape[axis]
    k = data.shape[0]
    assert k % n == 0, "k (data chunk rows) must divide the shard axis size"

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(),
        check_vma=False,
    )
    def _encode(bm_cols, local_chunks):
        # bm_cols: (8m, 8k/n) — this device's columns of the bit-matrix.
        # local_chunks: (k/n, S).
        bits = unpack_bits(local_chunks).astype(jnp.int8)
        partial = jnp.einsum(
            "pq,qs->ps", bm_cols.astype(jnp.int8), bits,
            preferred_element_type=jnp.int32,
        )
        total = jax.lax.psum(partial, axis)   # XOR == sum mod 2
        return pack_bits(total & 1)

    return _encode(bitmat, data)
