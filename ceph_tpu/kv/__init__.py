"""KeyValueDB: ordered kv store with column families + transactions.

Behavioral twin of the reference's kv seam (src/kv/KeyValueDB.h, the
RocksDBStore wrapper at src/kv/RocksDBStore.h:78): named column
families ("prefixes"), atomic write batches (set/rmkey/rm_range),
ordered iterators (seek/lower_bound/upper_bound), and a durable
implementation.  BlueStore keeps its metadata here; our KStore keeps
whole objects here (src/os/kstore), and MonStore can ride it too.

Two engines:

- :class:`MemDB` — ordered in-RAM store (the rocksdb memtable role;
  also the test double);
- :class:`FileDB` — MemDB + crc-framed WAL with checkpoint compaction
  (the same durability contract FileStore provides for object data:
  every batch is fsync'd before apply returns; kill -9 replays).
"""

from __future__ import annotations

import bisect
import os
import struct
import threading

from ceph_tpu.native import crc32c

_MAGIC = 0x4B56


class WriteBatch:
    """KeyValueDB::Transaction (atomic batch of kv mutations)."""

    def __init__(self):
        self.ops: list[tuple] = []

    def set(self, prefix: str, key: str, value: bytes) -> "WriteBatch":
        self.ops.append(("set", prefix, key, bytes(value)))
        return self

    def rmkey(self, prefix: str, key: str) -> "WriteBatch":
        self.ops.append(("rm", prefix, key))
        return self

    def rm_range(self, prefix: str, start: str, end: str) -> "WriteBatch":
        """Remove keys in [start, end) (RocksDB DeleteRange)."""
        self.ops.append(("rmrange", prefix, start, end))
        return self

    def rm_prefix(self, prefix: str) -> "WriteBatch":
        self.ops.append(("rmprefix", prefix))
        return self

    # wal encoding ------------------------------------------------------

    def encode(self) -> bytes:
        out = [struct.pack("<I", len(self.ops))]
        for op in self.ops:
            kind = op[0]
            out.append(struct.pack("<B", {"set": 1, "rm": 2, "rmrange": 3,
                                          "rmprefix": 4}[kind]))
            for field in op[1:]:
                raw = field if isinstance(field, bytes) else field.encode()
                out.append(struct.pack("<I", len(raw)) + raw)
        return b"".join(out)

    @classmethod
    def decode(cls, raw: bytes) -> "WriteBatch":
        b = cls()
        (n,) = struct.unpack_from("<I", raw)
        off = 4

        def take():
            nonlocal off
            (ln,) = struct.unpack_from("<I", raw, off)
            off += 4
            v = raw[off : off + ln]
            off += ln
            return v

        for _ in range(n):
            kind = raw[off]
            off += 1
            if kind == 1:
                b.set(take().decode(), take().decode(), take())
            elif kind == 2:
                b.rmkey(take().decode(), take().decode())
            elif kind == 3:
                b.rm_range(take().decode(), take().decode(), take().decode())
            elif kind == 4:
                b.rm_prefix(take().decode())
        return b


class Iterator:
    """Ordered iterator over one prefix (KeyValueDB::WholeSpaceIterator
    scoped to a column family)."""

    def __init__(self, keys: list[str], data: dict[str, bytes]):
        self._keys = keys
        self._data = data
        self._pos = 0

    def seek_to_first(self) -> "Iterator":
        self._pos = 0
        return self

    def lower_bound(self, key: str) -> "Iterator":
        self._pos = bisect.bisect_left(self._keys, key)
        return self

    def upper_bound(self, key: str) -> "Iterator":
        self._pos = bisect.bisect_right(self._keys, key)
        return self

    def valid(self) -> bool:
        return 0 <= self._pos < len(self._keys)

    def next(self) -> None:
        self._pos += 1

    def key(self) -> str:
        return self._keys[self._pos]

    def value(self) -> bytes:
        return self._data[self._keys[self._pos]]


class MemDB:
    """Ordered in-RAM KeyValueDB."""

    def __init__(self):
        # prefix -> {key: value}; sorted key list derived on iteration
        self._cf: dict[str, dict[str, bytes]] = {}
        self._lock = threading.RLock()

    def submit(self, batch: WriteBatch, sync: bool = True) -> None:
        with self._lock:
            self._apply(batch)

    def _apply(self, batch: WriteBatch) -> None:
        for op in batch.ops:
            kind = op[0]
            if kind == "set":
                _, p, k, v = op
                self._cf.setdefault(p, {})[k] = v
            elif kind == "rm":
                _, p, k = op
                self._cf.get(p, {}).pop(k, None)
            elif kind == "rmrange":
                _, p, s, e = op
                cf = self._cf.get(p, {})
                for k in [k for k in cf if s <= k < e]:
                    del cf[k]
            elif kind == "rmprefix":
                self._cf.pop(op[1], None)

    def get(self, prefix: str, key: str) -> bytes | None:
        with self._lock:
            return self._cf.get(prefix, {}).get(key)

    def get_iterator(self, prefix: str) -> Iterator:
        with self._lock:
            cf = self._cf.get(prefix, {})
            return Iterator(sorted(cf), dict(cf))

    def prefixes(self) -> list[str]:
        with self._lock:
            return sorted(self._cf)


class FileDB(MemDB):
    """Durable KeyValueDB: WAL of encoded batches + checkpoint
    compaction (the rocksdb WAL+SST contract at FileStore fidelity)."""

    def __init__(self, path: str, checkpoint_bytes: int = 64 * 1024 * 1024):
        super().__init__()
        self.path = path
        self.checkpoint_bytes = checkpoint_bytes
        self._wal = None
        self._wal_size = 0
        # serializes WAL append+fsync+checkpoint; the memtable lock
        # (self._lock) is held only for _apply so readers on the event
        # loop never wait out an fsync
        self._commit_lock = threading.Lock()

    blocking_commit = True

    def mount(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        cp = os.path.join(self.path, "checkpoint")
        if os.path.exists(cp):
            with open(cp, "rb") as f:
                self._load_checkpoint(f.read())
        walfn = os.path.join(self.path, "wal.log")
        if os.path.exists(walfn):
            raw = open(walfn, "rb").read()
            off = 0
            while off + 10 <= len(raw):
                magic, ln = struct.unpack_from("<HI", raw, off)
                if magic != _MAGIC or off + 10 + ln > len(raw):
                    break  # torn tail
                (crc,) = struct.unpack_from("<I", raw, off + 6)
                body = raw[off + 10 : off + 10 + ln]
                if crc32c(body) != crc:
                    break
                self._apply(WriteBatch.decode(body))
                off += 10 + ln
            self._wal_size = off
        self._wal = open(walfn, "ab")
        if self._wal.tell() != self._wal_size:
            self._wal.close()
            with open(walfn, "r+b") as f:
                f.truncate(self._wal_size)
            self._wal = open(walfn, "ab")

    def umount(self) -> None:
        if self._wal is not None:
            self._checkpoint()
            self._wal.close()
            self._wal = None

    def submit(self, batch: WriteBatch, sync: bool = True) -> None:
        with self._commit_lock:
            body = batch.encode()
            rec = struct.pack("<HI", _MAGIC, len(body)) + struct.pack(
                "<I", crc32c(body)
            ) + body
            self._wal.write(rec)
            self._wal.flush()
            if sync:
                os.fsync(self._wal.fileno())
            self._wal_size += len(rec)
            with self._lock:
                self._apply(batch)
            if self._wal_size >= self.checkpoint_bytes:
                self._checkpoint()

    # checkpoint: the whole cf map as one framed blob ------------------

    def _checkpoint(self) -> None:
        out = [struct.pack("<I", len(self._cf))]
        for p in sorted(self._cf):
            cf = self._cf[p]
            penc = p.encode()
            out.append(struct.pack("<I", len(penc)) + penc)
            out.append(struct.pack("<I", len(cf)))
            for k in sorted(cf):
                kenc = k.encode()
                out.append(struct.pack("<I", len(kenc)) + kenc)
                out.append(struct.pack("<I", len(cf[k])) + cf[k])
        blob = b"".join(out)
        tmp = os.path.join(self.path, "checkpoint.tmp")
        with open(tmp, "wb") as f:
            f.write(struct.pack("<I", crc32c(blob)) + blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, "checkpoint"))
        walfn = os.path.join(self.path, "wal.log")
        self._wal.close()
        with open(walfn, "wb") as f:
            f.flush()
            os.fsync(f.fileno())
        self._wal = open(walfn, "ab")
        self._wal_size = 0

    def _load_checkpoint(self, raw: bytes) -> None:
        (crc,) = struct.unpack_from("<I", raw)
        blob = raw[4:]
        if crc32c(blob) != crc:
            return  # torn checkpoint: WAL replay has everything
        off = 0

        def take():
            nonlocal off
            (ln,) = struct.unpack_from("<I", blob, off)
            off += 4
            v = blob[off : off + ln]
            off += ln
            return v

        (ncf,) = struct.unpack_from("<I", blob, off)
        off += 4
        for _ in range(ncf):
            p = take().decode()
            (nk,) = struct.unpack_from("<I", blob, off)
            off += 4
            cf = self._cf.setdefault(p, {})
            for _ in range(nk):
                k = take().decode()
                cf[k] = bytes(take())
