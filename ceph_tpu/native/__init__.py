"""Native C++ host runtime, loaded via ctypes.

The reference keeps its data-plane utilities native (crc32c:
src/common/crc32c.cc + sctp_crc32.c; region XOR:
src/erasure-code/isa/xor_op.cc).  We do the same: a small C++ library
compiled on first use with g++ (no pip deps), with pure-Python
fallbacks so the package works before/without a toolchain.

Public API:
  crc32c(data, seed=-1)          -- reference ceph_crc32c semantics
  crc32c_zeros(length, seed=-1)  -- crc of `length` zero bytes
  xor_region(dst, src)           -- dst ^= src in place (uint8 arrays)
  available()                    -- True when the .so is loaded
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "_libceph_tpu_native.so")
_SRCS = ["crc32c.cc", "crush_hash.cc"]

_lib = None
_lock = threading.Lock()
_build_failed = False


def _load():
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        srcs = [os.path.join(_HERE, s) for s in _SRCS]
        try:
            if not os.path.exists(_SO) or any(
                os.path.getmtime(s) > os.path.getmtime(_SO) for s in srcs
            ):
                tmp = _SO + f".tmp.{os.getpid()}"
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp]
                    + srcs,
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp, _SO)
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.CalledProcessError):
            _build_failed = True
            return None
        lib.ceph_tpu_crc32c.restype = ctypes.c_uint32
        lib.ceph_tpu_crc32c.argtypes = [
            ctypes.c_uint32, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.ceph_tpu_xor_region.restype = None
        lib.ceph_tpu_xor_region.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ]
        u32 = ctypes.c_uint32
        for name, nargs in [("ceph_tpu_hash32", 1), ("ceph_tpu_hash32_2", 2),
                            ("ceph_tpu_hash32_3", 3), ("ceph_tpu_hash32_4", 4),
                            ("ceph_tpu_hash32_5", 5)]:
            fn = getattr(lib, name)
            fn.restype = u32
            fn.argtypes = [u32] * nargs
        lib.ceph_tpu_straw2_choose.restype = ctypes.c_int32
        lib.ceph_tpu_straw2_choose.argtypes = [
            u32, u32, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.ceph_tpu_set_ln_tables.restype = None
        lib.ceph_tpu_set_ln_tables.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        # inject the crush_ln LUTs (single table of truth lives in the
        # generated Python module)
        from ceph_tpu.crush._ln_tables import LL_TBL, RH_LH_TBL

        rh = np.ascontiguousarray(RH_LH_TBL, dtype=np.int64)
        ll = np.ascontiguousarray(LL_TBL, dtype=np.int64)
        assert rh.size == 258 and ll.size == 256
        lib.ceph_tpu_set_ln_tables(rh.ctypes.data, ll.ctypes.data)
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


# -- pure-python fallback ---------------------------------------------------

_PY_TABLE: np.ndarray | None = None


def _py_table() -> np.ndarray:
    global _PY_TABLE
    if _PY_TABLE is None:
        t = np.zeros(256, dtype=np.uint32)
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if (c & 1) else (c >> 1)
            t[i] = c
        _PY_TABLE = t
    return _PY_TABLE


def _py_crc32c(data: bytes, seed: int) -> int:
    t = _py_table()
    crc = seed & 0xFFFFFFFF
    for b in data:
        crc = int(t[(crc ^ b) & 0xFF]) ^ (crc >> 8)
    return crc


# -- public API -------------------------------------------------------------

def crc32c(data, seed: int = 0xFFFFFFFF) -> int:
    """Reference ceph_crc32c(seed, data, len): reflected CRC32C table
    update, no init/final inversion (sctp_crc32.c:update_crc32)."""
    arr = np.ascontiguousarray(
        np.frombuffer(data, dtype=np.uint8)
        if isinstance(data, (bytes, bytearray, memoryview))
        else np.asarray(data, dtype=np.uint8).reshape(-1)
    )
    lib = _load()
    if lib is not None:
        return lib.ceph_tpu_crc32c(
            seed & 0xFFFFFFFF, arr.ctypes.data, arr.nbytes
        )
    return _py_crc32c(arr.tobytes(), seed)


def crc32c_zeros(length: int, seed: int = 0xFFFFFFFF) -> int:
    """crc32c of `length` zero bytes (reference crc32c.cc:216)."""
    lib = _load()
    if lib is not None:
        return lib.ceph_tpu_crc32c(seed & 0xFFFFFFFF, None, length)
    t = _py_table()
    crc = seed & 0xFFFFFFFF
    for _ in range(length):
        if crc == 0:
            break
        crc = int(t[crc & 0xFF]) ^ (crc >> 8)
    return crc


def straw2_lib():
    """The raw ctypes lib if the native straw2 choose is usable (LUTs
    injected), else None.  mapper.py binds the per-bucket call itself
    to keep the hot path free of Python-level indirection."""
    lib = _load()
    if lib is not None and lib.ceph_tpu_ln_tables_ready():
        return lib
    return None


def xor_region(dst: np.ndarray, src: np.ndarray) -> None:
    """dst ^= src in place (both uint8, same length).  ``dst`` must be
    C-contiguous — a strided view would silently XOR into a copy."""
    assert dst.dtype == np.uint8 and src.dtype == np.uint8
    assert dst.flags.c_contiguous, "xor_region dst must be contiguous"
    assert dst.nbytes == src.nbytes
    lib = _load()
    if lib is not None:
        src = np.ascontiguousarray(src)
        lib.ceph_tpu_xor_region(dst.ctypes.data, src.ctypes.data, dst.nbytes)
    else:
        np.bitwise_xor(dst, src, out=dst)
