// Scalar CRUSH placement hot loop, native.
//
// The Python scalar mapper (ceph_tpu/crush/mapper.py) is the
// correctness oracle, but OSD daemons also use it for per-PG mapping
// on every epoch; in pure Python one straw2 draw costs ~25us which
// stalls daemon event loops (bench config 5).  This file moves the
// per-item draw loop — Jenkins hash, fixed-point crush_ln LUT lookup,
// weighted division, argmax — into C++ with one ctypes call per
// bucket level.  Semantics mirror mapper.py exactly (which is itself
// pinned bit-identical to the reference's src/crush/mapper.c by
// golden vectors); the crush_ln LUTs are injected at load time from
// ceph_tpu/crush/_ln_tables.py so there is a single table of truth.

#include <cstdint>
#include <cstring>

extern "C" {

static uint32_t SEED = 1315423911u;
static const uint32_t XPAD = 231232u;
static const uint32_t YPAD = 1232u;

#define MIX(a, b, c)     \
  do {                   \
    a = a - b; a = a - c; a = a ^ (c >> 13); \
    b = b - c; b = b - a; b = b ^ (a << 8);  \
    c = c - a; c = c - b; c = c ^ (b >> 13); \
    a = a - b; a = a - c; a = a ^ (c >> 12); \
    b = b - c; b = b - a; b = b ^ (a << 16); \
    c = c - a; c = c - b; c = c ^ (b >> 5);  \
    a = a - b; a = a - c; a = a ^ (c >> 3);  \
    b = b - c; b = b - a; b = b ^ (a << 10); \
    c = c - a; c = c - b; c = c ^ (b >> 15); \
  } while (0)

uint32_t ceph_tpu_hash32(uint32_t a) {
  uint32_t h = SEED ^ a, b = a, x = XPAD, y = YPAD;
  MIX(b, x, h);
  MIX(y, a, h);
  return h;
}

uint32_t ceph_tpu_hash32_2(uint32_t a, uint32_t b) {
  uint32_t h = SEED ^ a ^ b, x = XPAD, y = YPAD;
  MIX(a, b, h);
  MIX(x, a, h);
  MIX(b, y, h);
  return h;
}

uint32_t ceph_tpu_hash32_3(uint32_t a, uint32_t b, uint32_t c) {
  uint32_t h = SEED ^ a ^ b ^ c, x = XPAD, y = YPAD;
  MIX(a, b, h);
  MIX(c, x, h);
  MIX(y, a, h);
  MIX(b, x, h);
  MIX(y, c, h);
  return h;
}

uint32_t ceph_tpu_hash32_4(uint32_t a, uint32_t b, uint32_t c, uint32_t d) {
  uint32_t h = SEED ^ a ^ b ^ c ^ d, x = XPAD, y = YPAD;
  MIX(a, b, h);
  MIX(c, d, h);
  MIX(a, x, h);
  MIX(y, b, h);
  MIX(c, x, h);
  MIX(y, d, h);
  return h;
}

uint32_t ceph_tpu_hash32_5(uint32_t a, uint32_t b, uint32_t c, uint32_t d,
                           uint32_t e) {
  uint32_t h = SEED ^ a ^ b ^ c ^ d ^ e, x = XPAD, y = YPAD;
  MIX(a, b, h);
  MIX(c, d, h);
  MIX(e, x, h);
  MIX(y, a, h);
  MIX(b, x, h);
  MIX(y, c, h);
  MIX(d, x, h);
  MIX(y, e, h);
  return h;
}

// crush_ln fixed-point LUTs, injected once from Python (the generated
// tables in ceph_tpu/crush/_ln_tables.py).  RH_LH has 258 entries
// (index1 in [256, 512] step 2 maps to [0, 257] after the -256 bias),
// LL has 256.
static int64_t RH_LH[258];
static int64_t LL[256];
static int tables_ready = 0;

void ceph_tpu_set_ln_tables(const int64_t* rh_lh, const int64_t* ll) {
  memcpy(RH_LH, rh_lh, sizeof(RH_LH));
  memcpy(LL, ll, sizeof(LL));
  tables_ready = 1;
}

int ceph_tpu_ln_tables_ready(void) { return tables_ready; }

// 2^44 * log2(xin + 1) — twin of mapper.py crush_ln
static int64_t crush_ln_fp(uint32_t xin) {
  uint32_t x = (xin + 1u);
  int iexpon = 15;
  if (!(x & 0x18000u)) {
    int bits = 0;
    uint32_t v = x & 0x1FFFFu;
    // 16 - bit_length(v); v >= 1 because of the +1 above
    while (v < 0x8000u) { v <<= 1; ++bits; }
    x <<= bits;
    iexpon = 15 - bits;
  }
  uint32_t index1 = (x >> 8) << 1;
  int64_t rh = RH_LH[index1 - 256];
  int64_t lh = RH_LH[index1 + 1 - 256];
  uint64_t xl64 = ((uint64_t)x * (uint64_t)rh) >> 48;
  int64_t result = (int64_t)iexpon << 44;
  int64_t llv = LL[xl64 & 0xFF];
  lh += llv;
  lh >>= (48 - 12 - 32);
  return result + lh;
}

// One straw2 draw: generate_exponential_distribution semantics
// (mapper.py straw2_draw).  C's int64 division truncates toward zero,
// matching the Python _div64 helper.
static int64_t straw2_draw_c(uint32_t x, int32_t item, uint32_t r,
                             uint32_t weight) {
  uint32_t u = ceph_tpu_hash32_3(x, (uint32_t)item, r) & 0xFFFFu;
  int64_t ln = crush_ln_fp(u) - 0x1000000000000LL;
  return ln / (int64_t)weight;  // ln <= 0, weight > 0
}

// Whole straw2 bucket choose: returns the ARG INDEX (not item id) of
// the winner — first index wins ties, draw == S64_MIN for zero
// weights — mirroring bucket_straw2_choose in mapper.py.
int32_t ceph_tpu_straw2_choose(uint32_t x, uint32_t r, const int32_t* ids,
                               const uint32_t* weights, int32_t n) {
  int32_t high = 0;
  int64_t high_draw = 0;
  for (int32_t i = 0; i < n; ++i) {
    int64_t draw;
    if (weights[i]) {
      draw = straw2_draw_c(x, ids[i], r, weights[i]);
    } else {
      draw = INT64_MIN;
    }
    if (i == 0 || draw > high_draw) {
      high = i;
      high_draw = draw;
    }
  }
  return high;
}

}  // extern "C"
