// crc32c host kernel (Castagnoli, reflected poly 0x82F63B78).
//
// Behavioral twin of the reference's ceph_crc32c family
// (reference src/common/sctp_crc32.c:update_crc32 — plain reflected
// table update, caller passes the seed, no init/final inversion;
// reference src/common/crc32c.cc:216 ceph_crc32c_zeros for the
// null-buffer "crc of zeros" path).  Slice-by-8 for throughput; the
// build wires SSE4.2/ARMv8 hardware CRC when -march allows, matching
// the reference's runtime-dispatch intent without the asm files.
#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

struct Tables {
  uint32_t t[8][256];
  Tables() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = t[0][i];
      for (int s = 1; s < 8; s++) {
        c = t[0][c & 0xff] ^ (c >> 8);
        t[s][i] = c;
      }
    }
  }
};
const Tables kT;

}  // namespace

extern "C" {

// Matches ceph_crc32c(seed, data, len); data may be null (= zeros).
uint32_t ceph_tpu_crc32c(uint32_t crc, const uint8_t* data, size_t len) {
  if (data == nullptr) {
    // crc of `len` zero bytes: the byte step degenerates to
    // crc = T[crc & 0xff] ^ (crc >> 8); once crc hits 0 it stays 0.
    while (len >= 1 && crc != 0) {
      crc = kT.t[0][crc & 0xff] ^ (crc >> 8);
      len--;
    }
    return crc;
  }
  while (len && (reinterpret_cast<uintptr_t>(data) & 7)) {
    crc = kT.t[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
    len--;
  }
  while (len >= 8) {
    uint64_t v;
    std::memcpy(&v, data, 8);
    v ^= crc;
    crc = kT.t[7][v & 0xff] ^ kT.t[6][(v >> 8) & 0xff] ^
          kT.t[5][(v >> 16) & 0xff] ^ kT.t[4][(v >> 24) & 0xff] ^
          kT.t[3][(v >> 32) & 0xff] ^ kT.t[2][(v >> 40) & 0xff] ^
          kT.t[1][(v >> 48) & 0xff] ^ kT.t[0][(v >> 56) & 0xff];
    data += 8;
    len -= 8;
  }
  while (len--) crc = kT.t[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
  return crc;
}

// XOR-accumulate src into dst (region parity; reference
// src/erasure-code/isa/xor_op.cc semantics, compiler-vectorized).
void ceph_tpu_xor_region(uint8_t* dst, const uint8_t* src, size_t len) {
  size_t i = 0;
  for (; i + 8 <= len; i += 8) {
    uint64_t a, b;
    std::memcpy(&a, dst + i, 8);
    std::memcpy(&b, src + i, 8);
    a ^= b;
    std::memcpy(dst + i, &a, 8);
  }
  for (; i < len; i++) dst[i] ^= src[i];
}

}  // extern "C"
