"""ceph_tpu — a TPU-native distributed object-storage framework.

A from-scratch re-design of Ceph's capability surface (reference:
yanggogo/ceph, Ceph v19 "Squid" dev tree) whose performance-critical
data-plane math — GF(2^8) Reed-Solomon / Cauchy erasure coding over
object-stripe batches, and batched CRUSH straw2 placement over whole
OSDMaps — executes on TPU via JAX (jit/vmap/shard_map/pallas).

Package layout (mirrors the reference's layer map, SURVEY.md §1, but
TPU-first):

- ``ceph_tpu.ops``      — field math + kernels: GF(2^8), bit-matrices,
                          RS/Cauchy matrix constructions, CRUSH hash,
                          crc32c.  (reference: jerasure/gf-complete,
                          src/crush/hash.c, src/common/crc32c*)
- ``ceph_tpu.crush``    — CRUSH map model, scalar twin interpreter and
                          the batched JAX placement engine.
                          (reference: src/crush/)
- ``ceph_tpu.osdmap``   — OSDMap, pools, pg→up/acting pipeline, batched
                          whole-cluster remap.  (reference: src/osd/OSDMap.*)
- ``ceph_tpu.ec``       — erasure-code plugin framework + plugins.
                          (reference: src/erasure-code/)
- ``ceph_tpu.models``   — the code-family "models": RS-Vandermonde,
                          Cauchy, CLAY, SHEC, LRC constructions as pure
                          math over GF(2^8).
- ``ceph_tpu.parallel`` — device mesh / sharding helpers; multi-chip
                          encode farms and remap sharding.
- ``ceph_tpu.msg``      — framed async transport (msgr2 analogue).
- ``ceph_tpu.store``    — object store (MemStore analogue + WAL).
- ``ceph_tpu.osd``      — OSD daemon: PG state, EC backend I/O paths.
- ``ceph_tpu.mon``      — cluster map authority / control plane.
- ``ceph_tpu.client``   — librados-analogue client library.
- ``ceph_tpu.cli``      — admin tools (crushtool/osdmaptool analogues).
- ``ceph_tpu.utils``    — config options, logging, profiles.
"""

__version__ = "0.1.0"
