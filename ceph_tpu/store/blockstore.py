"""BlockStore: the BlueStore-grade engine — raw block space, extent
maps, checksums at rest, copy-on-write blobs.

Behavioral twin of the reference's production store
(src/os/bluestore/BlueStore.cc): object data lives as **blobs** in a
raw block file carved by an allocator; per-object **extent maps** map
logical ranges onto blobs; every blob carries a **crc32c checksum
verified on every read** (checksum-at-rest — a flipped bit on disk
surfaces as EIO, which deep scrub turns into a repairable
inconsistency); metadata (extent maps, xattrs, omap, blob refcounts)
rides a KeyValueDB (ceph_tpu/kv FileDB — the RocksDB role) whose WAL
makes every transaction atomic and durable.

Mapping of BlueStore's moving parts:

- allocator (Avl/Bitmap/...): a free-extent list over ``min_alloc``
  units, rebuilt at mount from the live blob set (the FreelistManager
  role); torn writes can only leak space, never corrupt — leaked blobs
  are reclaimed by the mount-time sweep (fsck-lite);
- deferred small writes: payloads under ``inline_max`` are stored
  INLINE in the kv (committed by the kv WAL — one durable write instead
  of block write + fsync + kv commit), the same latency trade
  BlueStore's deferred-write policy makes for small I/O;
- big writes are COW: fresh extents are allocated, written and fsync'd
  BEFORE the kv batch commits the new extent map, so a crash leaves
  either the old object or the new one, never a tear;
- clone: extent maps are copied and blob refcounts bumped (the
  SharedBlob role) — snapshots share unmodified data at rest;
- checksums: one crc32c per blob, checked on read and by fsck.

Write ordering invariant: block-file data is durable before the kv
batch that references it commits; the kv batch is the commit point.
"""

from __future__ import annotations

import json
import os
import struct
import threading

from ceph_tpu.common.fault_injector import (
    InjectedError,
    store_data_fault,
    store_fault_check,
)
from ceph_tpu.kv import FileDB, MemDB, WriteBatch
from ceph_tpu.native import crc32c
from ceph_tpu.store.kstore import (
    _TxnView,
    _ckey,
    _okey,
    _parse_okey,
    _prefix_end,
)
from ceph_tpu.store.objectstore import (
    ObjectStore,
    Transaction,
    TxOp,
    coll_t,
    ghobject_t,
)

SEP = "\x01"
MIN_ALLOC = 65536        # min_alloc_size: block allocation unit
INLINE_MAX = 4096        # small writes stay in kv (deferred-write role)


class BlobError(OSError):
    pass


class _Allocator:
    """Free-extent allocator over MIN_ALLOC units (the Bitmap/Avl
    allocator role, unit granularity)."""

    def __init__(self):
        self._free: list[tuple[int, int]] = []  # (unit_off, units), sorted
        self.end_units = 0  # high-water mark (file grows on demand)

    def init_from_used(self, used: set[int], end_units: int) -> None:
        self.end_units = end_units
        self._free = []
        run_start = None
        for u in range(end_units):
            if u in used:
                if run_start is not None:
                    self._free.append((run_start, u - run_start))
                    run_start = None
            elif run_start is None:
                run_start = u
        if run_start is not None:
            self._free.append((run_start, end_units - run_start))

    def alloc(self, units: int) -> int:
        """First-fit; grows the device when no run is large enough."""
        for i, (off, n) in enumerate(self._free):
            if n >= units:
                if n == units:
                    self._free.pop(i)
                else:
                    self._free[i] = (off + units, n - units)
                return off
        off = self.end_units
        self.end_units += units
        return off

    def free(self, off: int, units: int) -> None:
        self._free.append((off, units))
        self._free.sort()
        # coalesce neighbours
        merged: list[tuple[int, int]] = []
        for o, n in self._free:
            if merged and merged[-1][0] + merged[-1][1] == o:
                merged[-1] = (merged[-1][0], merged[-1][1] + n)
            else:
                merged.append((o, n))
        self._free = merged

    def free_units(self) -> int:
        return sum(n for _o, n in self._free)


class _BitmapAllocator:
    """Bit-per-unit allocator (the BitmapAllocator role,
    src/os/bluestore/BitmapAllocator.cc): same interface as the
    first-fit extent list, different structure — O(1) free, scan
    alloc with a rolling cursor so sequential workloads don't rescan
    the device head every time."""

    def __init__(self):
        self._bits = bytearray()  # 1 = used
        self.end_units = 0
        self._cursor = 0

    def _used(self, u: int) -> bool:
        return bool(self._bits[u >> 3] & (1 << (u & 7)))

    def _set(self, u: int, used: bool) -> None:
        if used:
            self._bits[u >> 3] |= 1 << (u & 7)
        else:
            self._bits[u >> 3] &= ~(1 << (u & 7))

    def init_from_used(self, used: set[int], end_units: int) -> None:
        self.end_units = end_units
        self._bits = bytearray((end_units + 7) // 8)
        for u in used:
            self._set(u, True)
        self._cursor = 0

    def _grow(self, end: int) -> None:
        if len(self._bits) * 8 < end:
            self._bits.extend(b"\0" * ((end + 7) // 8 - len(self._bits)))
        self.end_units = max(self.end_units, end)

    def alloc(self, units: int) -> int:
        for base in (self._cursor, 0):
            run = 0
            for u in range(base, self.end_units):
                if self._used(u):
                    run = 0
                    continue
                run += 1
                if run == units:
                    start = u - units + 1
                    self._grow(u + 1)
                    for v in range(start, u + 1):
                        self._set(v, True)
                    self._cursor = u + 1
                    return start
            if base == 0:
                break
        start = self.end_units
        self._grow(start + units)
        for v in range(start, start + units):
            self._set(v, True)
        self._cursor = start + units
        return start

    def free(self, off: int, units: int) -> None:
        for u in range(off, off + units):
            self._set(u, False)
        self._cursor = min(self._cursor, off)

    def free_units(self) -> int:
        return sum(
            1 for u in range(self.end_units) if not self._used(u))


class BlockStore(ObjectStore):
    """ObjectStore over raw block space + a KeyValueDB (BlueStore role).

    kv column families: C collections, O object meta (size + extent
    map), X xattrs, M omap, R blob refcounts.  Object meta value is
    json: ``{"size": N, "extents": [[logical_off, blob_id, length], ...],
    "inline": {"off": hex-bytes, ...}}``; blob id "unit:units:crc" or,
    compressed at rest, "unit:units:crc:alg:stored_len" (crc over the
    STORED bytes — verify before decompress, like BlueStore's
    csum-then-decompress order).

    ``compression``: a compressor plugin name ("zlib", ...) enables
    transparent at-rest compression of non-inline blobs; a blob is
    stored compressed only when it shrinks below
    ``compression_required_ratio`` of the raw size (BlueStore's
    bluestore_compression_required_ratio gate).  ``allocator`` selects
    "first-fit" (extent list, Avl role) or "bitmap".
    """

    def __init__(self, path: str, db=None, compression: str = "none",
                 compression_required_ratio: float = 0.875,
                 allocator: str = "first-fit",
                 capacity_bytes: int = 1 << 40):
        from ceph_tpu.store.bluefs import BlueFSLite

        self.path = path
        # advertised device size for statfs (the block file itself
        # grows on demand up to this)
        self.capacity_bytes = capacity_bytes
        os.makedirs(path, exist_ok=True)
        # default: BlueFS-lite — the KV (WAL + checkpoints) lives on
        # the SAME device under the SAME allocator (the BlueStore raw-
        # device model, src/os/bluestore/BlueFS.cc); pass an external
        # db (e.g. FileDB) to split metadata out instead
        if db is None and os.path.isdir(os.path.join(path, "kv")):
            # legacy layout: a pre-BlueFS store keeps its KV in the
            # kv/ sidecar directory and its device units 0-1 hold BLOB
            # DATA, not superblocks — mounting it as BlueFS would read
            # garbage superblocks, come up with an empty KV, and
            # allocate the WAL over live blobs.  Keep such stores on
            # FileDB (their on-disk contract) instead.
            import logging

            logging.getLogger("ceph_tpu.store").warning(
                "blockstore %s: legacy kv/ sidecar layout detected; "
                "staying on FileDB (create a fresh store to migrate "
                "to the BlueFS-lite co-located KV)", path)
            db = FileDB(os.path.join(path, "kv"))
        self.db = db if db is not None else BlueFSLite()
        self._block_path = os.path.join(path, "block")
        self._fd: int | None = None
        self._alloc = (
            _BitmapAllocator() if allocator == "bitmap" else _Allocator())
        self._txn_lock = threading.Lock()
        self._compressor = None
        if compression and compression != "none":
            from ceph_tpu import compressor as _comp

            self._compressor = _comp.create(compression)
            self._comp_alg = compression
        self._comp_ratio = compression_required_ratio

    blocking_commit = True

    # -- lifecycle -----------------------------------------------------

    def statfs(self) -> dict:
        used_units = self._alloc.end_units - self._alloc.free_units()
        used = used_units * MIN_ALLOC
        return {
            "total": self.capacity_bytes,
            "used": used,
            "available": max(0, self.capacity_bytes - used),
        }

    def mount(self) -> None:
        from ceph_tpu.store.bluefs import BlueFSLite

        store_fault_check("mount", self.fault_domain)
        self._fd = os.open(
            self._block_path, os.O_RDWR | os.O_CREAT, 0o644)
        bluefs = isinstance(self.db, BlueFSLite)
        if bluefs:
            # the KV lives on OUR device: superblock + chains first,
            # then the blob sweep below can read its metadata
            self.db.attach(self._fd)
            self.db.mount()
        elif hasattr(self.db, "mount"):
            self.db.mount()
        # rebuild the allocator from the live blob set (FreelistManager
        # role); anything on disk not referenced by a committed extent
        # map is garbage from a torn write -> reclaimed here (fsck-lite)
        used: set[int] = set()
        end = 0
        it = self.db.get_iterator("O").seek_to_first()
        while it.valid():
            meta = json.loads(it.value())
            for _lo, blob, _ln in meta.get("extents", []):
                unit, units = _parse_blob(blob)[:2]
                used.update(range(unit, unit + units))
                end = max(end, unit + units)
            it.next()
        if bluefs:
            kv_units = self.db.used_units()
            used |= kv_units
            end = max(end, max(kv_units) + 1)
        self._alloc.init_from_used(used, end)
        if bluefs:
            # allocator live: the WAL may now grow and checkpoints run
            self.db.activate(self._alloc)

    def umount(self) -> None:
        # KV first: BlueFS's final checkpoint writes through our fd
        if hasattr(self.db, "umount"):
            self.db.umount()
        if self._fd is not None:
            os.fsync(self._fd)
            os.close(self._fd)
            self._fd = None

    def fsck(self) -> list[dict]:
        """Verify every blob's checksum at rest (BlueStore fsck role),
        plus the co-located KV's own metadata (superblock generations +
        WAL frames) when BlueFS hosts it."""
        bad: list[dict] = []
        db_fsck = getattr(self.db, "fsck", None)
        if callable(db_fsck):
            bad.extend(db_fsck())
        it = self.db.get_iterator("O").seek_to_first()
        while it.valid():
            meta = json.loads(it.value())
            for lo, blob, ln in meta.get("extents", []):
                try:
                    self._read_blob(blob, ln)
                except BlobError:
                    bad.append({"okey": it.key(), "logical_off": lo,
                                "blob": blob})
            it.next()
        return bad

    # -- object meta ---------------------------------------------------

    def _meta(self, c: coll_t, o: ghobject_t, view=None) -> dict | None:
        get = view.get if view is not None else self.db.get
        raw = get("O", _okey(c, o))
        return None if raw is None else json.loads(raw)

    def _require(self, c: coll_t, o: ghobject_t) -> dict:
        if not self.collection_exists(c):
            raise FileNotFoundError(f"collection {c}")
        meta = self._meta(c, o)
        if meta is None:
            raise FileNotFoundError(f"{c}/{o}")
        return meta

    # -- reads ---------------------------------------------------------

    def read(self, c, o, off=0, length=None):
        store_fault_check("read", self.fault_domain)
        if store_data_fault("read", self.fault_domain, peek=True):
            self._maybe_flip_bit(c, o)
        # writers commit on a worker thread and may free+reuse a blob's
        # units between our meta load and the pread; a checksum failure
        # with a CHANGED meta is that benign race — reload and retry.
        # A failure with the SAME committed meta is genuine bit rot.
        last = None
        for _ in range(3):
            meta = self._require(c, o)
            if meta == last:
                break
            try:
                return self._read_with_meta(c, o, meta, off, length)
            except BlobError:
                last = meta
        raise BlobError(5, f"checksum mismatch in {c}/{o}")

    def _maybe_flip_bit(self, c, o) -> None:
        """Armed bitflip data fault: corrupt one stored byte of this
        object's first blob AT REST, so the normal read path's
        checksum-at-rest verification surfaces it as EIO (the
        BlueStore bit-rot model).  Objects with no blob (inline-only,
        absent) leave the fault armed for the next eligible read."""
        meta = self._meta(c, o)
        if not meta or not meta.get("extents"):
            return
        spec = store_data_fault("read", self.fault_domain)
        if spec is None or not spec.get("bitflip"):
            return
        unit = _parse_blob(meta["extents"][0][1])[0]
        pos = unit * MIN_ALLOC
        byte = os.pread(self._fd, 1, pos)
        if byte:
            os.pwrite(self._fd, bytes([byte[0] ^ 0x40]), pos)

    def _read_with_meta(self, c, o, meta, off=0, length=None):
        size = meta["size"]
        end = size if length is None else min(off + length, size)
        if off >= end:
            return b""
        out = bytearray(end - off)
        for lo, blob, ln in meta.get("extents", []):
            hi = lo + ln
            s, e = max(off, lo), min(end, hi)
            if s >= e:
                continue
            try:
                data = self._read_blob(blob, ln)
            except BlobError:
                # checksum-at-rest violation (or a benign stale-meta
                # race the caller's retry loop disambiguates)
                raise BlobError(5, f"checksum mismatch in {c}/{o} @ {lo}")
            out[s - off : e - off] = data[s - lo : e - lo]
        for hoff, hexdata in meta.get("inline", {}).items():
            lo = int(hoff)
            data = bytes.fromhex(hexdata)
            hi = lo + len(data)
            s, e = max(off, lo), min(end, hi)
            if s < e:
                out[s - off : e - off] = data[s - lo : e - lo]
        return bytes(out)

    def stat(self, c, o):
        return self._require(c, o)["size"]

    def exists(self, c, o):
        return self.collection_exists(c) and self._meta(c, o) is not None

    def getattr(self, c, o, name):
        self._require(c, o)
        raw = self.db.get("X", _okey(c, o) + SEP + name)
        if raw is None:
            raise KeyError(name)
        return raw

    def getattrs(self, c, o):
        self._require(c, o)
        return self._prefix_dict("X", _okey(c, o) + SEP)

    def omap_get(self, c, o):
        self._require(c, o)
        return self._prefix_dict("M", _okey(c, o) + SEP)

    def omap_get_values(self, c, o, keys):
        self._require(c, o)
        base = _okey(c, o) + SEP
        out = {}
        for k in keys:
            v = self.db.get("M", base + k)
            if v is not None:
                out[k] = v
        return out

    def _prefix_dict(self, prefix: str, base: str) -> dict[str, bytes]:
        it = self.db.get_iterator(prefix).lower_bound(base)
        out = {}
        while it.valid() and it.key().startswith(base):
            out[it.key()[len(base):]] = it.value()
            it.next()
        return out

    def list_collections(self):
        it = self.db.get_iterator("C").seek_to_first()
        out = []
        while it.valid():
            pool, ps, shard = it.key().split(".")
            out.append(coll_t(int(pool), int(ps), int(shard)))
            it.next()
        return sorted(out)

    def collection_exists(self, c):
        return self.db.get("C", _ckey(c)) is not None

    def collection_list(self, c):
        if not self.collection_exists(c):
            raise FileNotFoundError(f"collection {c}")
        base = _ckey(c) + SEP
        it = self.db.get_iterator("O").lower_bound(base)
        out = []
        while it.valid() and it.key().startswith(base):
            out.append(_parse_okey(it.key())[1])
            it.next()
        return sorted(out)

    # -- transactions --------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        store_fault_check("write", self.fault_domain)
        with self._txn_lock:
            self._validate(txn)
            batch = WriteBatch()
            view = _TxnView(self.db, batch)
            freed: list[str] = []     # blobs to free AFTER commit
            wrote_block = False
            for op in txn.ops:
                wrote_block |= self._translate(op, view, freed)
            if wrote_block:
                # ordering invariant: blob data durable BEFORE the kv
                # commit that references it
                os.fsync(self._fd)
            tear = store_data_fault("write", self.fault_domain)
            if tear is not None and tear.get("torn"):
                # torn write: blob data hit the platter but the kv
                # batch — the commit point — never lands.  This is
                # BlockStore's REAL crash shape: the object keeps its
                # old committed state and the orphaned blobs are
                # reclaimed by the next mount's fsck-lite sweep.
                raise InjectedError(
                    5, "injected torn write (kv commit dropped)")
            store_fault_check("commit", self.fault_domain)
            self.db.submit(batch)
            for blob in freed:
                self._deref_blob(blob)
        for cb in txn.on_applied:
            cb()
        for cb in txn.on_commit:
            cb()

    # blob helpers ------------------------------------------------------

    def _write_blob(self, data: bytes) -> str:
        stored = data
        tag = ""
        if self._compressor is not None and len(data) > INLINE_MAX:
            comp = self._compressor.compress(data)
            if len(comp) <= len(data) * self._comp_ratio:
                stored = comp
                tag = f":{self._comp_alg}:{len(comp)}"
        units = max(1, -(-len(stored) // MIN_ALLOC))
        unit = self._alloc.alloc(units)
        os.pwrite(self._fd, stored, unit * MIN_ALLOC)
        return f"{unit}:{units}:{crc32c(stored)}{tag}"

    def _read_blob(self, blob: str, ln: int) -> bytes:
        """pread + crc-verify (+ decompress) one blob; ``ln`` is the
        logical (uncompressed) length the extent map records."""
        unit, _units, crc, alg, stored_len = _parse_blob(blob)
        data = os.pread(self._fd, stored_len if alg else ln,
                        unit * MIN_ALLOC)
        if crc32c(data) != crc:
            raise BlobError(5, f"checksum mismatch in blob {blob}")
        if alg:
            if self._compressor is not None and alg == self._comp_alg:
                data = self._compressor.decompress(data)
            else:  # legacy blob from a differently-configured mount
                from ceph_tpu import compressor as _comp

                data = _comp.create(alg).decompress(data)
        return data

    def _bump_blob(self, view: _TxnView, blob: str, by: int = 1) -> None:
        raw = view.get("R", blob)
        refs = (struct.unpack("<I", raw)[0] if raw else 0) + by
        view.set("R", blob, struct.pack("<I", refs))

    def _deref_blob_in_view(self, view: _TxnView, blob: str,
                            freed: list[str]) -> None:
        raw = view.get("R", blob)
        refs = struct.unpack("<I", raw)[0] if raw else 1
        if refs <= 1:
            view.rmkey("R", blob)
            freed.append(blob)
        else:
            view.set("R", blob, struct.pack("<I", refs - 1))

    def _deref_blob(self, blob: str) -> None:
        unit, units = _parse_blob(blob)[:2]
        self._alloc.free(unit, units)

    # translation -------------------------------------------------------

    def _translate(self, op, view: _TxnView, freed: list[str]) -> bool:
        """Apply one TxOp into the view; returns True when block data
        was written (the caller fsyncs once before commit)."""
        kind = op[0]
        wrote = False
        if kind == TxOp.MKCOLL:
            view.set("C", _ckey(op[1]), b"1")
        elif kind == TxOp.RMCOLL:
            view.rmkey("C", _ckey(op[1]))
        elif kind == TxOp.TOUCH:
            _, c, o = op
            if self._meta(c, o, view) is None:
                self._put_meta(view, c, o, _new_meta())
        elif kind == TxOp.WRITE:
            _, c, o, off, data = op
            meta = self._meta(c, o, view) or _new_meta()
            wrote = self._write_range(view, c, o, meta, off, bytes(data),
                                      freed)
        elif kind == TxOp.ZERO:
            # zeros need no storage: punch the range out of the extent
            # map — read() zero-fills gaps (BlueStore punch-hole zeroing)
            _, c, o, off, length = op
            meta = self._meta(c, o, view) or _new_meta()
            wrote = self._punch_hole(view, meta, off, off + length, freed)
            meta["size"] = max(meta.get("size", 0), off + length)
            self._put_meta(view, c, o, meta)
        elif kind == TxOp.TRUNCATE:
            _, c, o, size = op
            meta = self._meta(c, o, view) or _new_meta()
            wrote = self._truncate(view, c, o, meta, size, freed)
        elif kind == TxOp.REMOVE:
            _, c, o = op
            self._rm_object(view, c, o, freed)
        elif kind == TxOp.SETATTRS:
            _, c, o, attrs = op
            if self._meta(c, o, view) is None:
                self._put_meta(view, c, o, _new_meta())
            for k, v in attrs.items():
                view.set("X", _okey(c, o) + SEP + k, v)
        elif kind == TxOp.RMATTR:
            _, c, o, name = op
            view.rmkey("X", _okey(c, o) + SEP + name)
        elif kind == TxOp.OMAP_SETKEYS:
            _, c, o, kv = op
            if self._meta(c, o, view) is None:
                self._put_meta(view, c, o, _new_meta())
            for k, v in kv.items():
                view.set("M", _okey(c, o) + SEP + k, v)
        elif kind == TxOp.OMAP_RMKEYS:
            _, c, o, keys = op
            if self._meta(c, o, view) is None:
                self._put_meta(view, c, o, _new_meta())
            for k in keys:
                view.rmkey("M", _okey(c, o) + SEP + k)
        elif kind == TxOp.OMAP_CLEAR:
            _, c, o = op
            base = _okey(c, o) + SEP
            view.rm_range("M", base, _prefix_end(base))
            if self._meta(c, o, view) is None:
                self._put_meta(view, c, o, _new_meta())
        elif kind == TxOp.CLONE:
            _, c, src, dst = op
            wrote = self._clone(view, c, src, c, dst)
        elif kind == TxOp.COLL_MOVE_RENAME:
            _, src_c, src_o, dst_c, dst_o = op
            wrote = self._clone(view, src_c, src_o, dst_c, dst_o)
            self._rm_object(view, src_c, src_o, freed)
        else:  # pragma: no cover
            raise ValueError(f"unknown op {kind}")
        return wrote

    def _put_meta(self, view, c, o, meta: dict) -> None:
        view.set("O", _okey(c, o), json.dumps(meta).encode())

    def _write_range(self, view, c, o, meta, off, data, freed) -> bool:
        """COW write: large payloads get fresh blobs; small ones stay
        inline in kv (the deferred-write/small-blob policy)."""
        if not data:
            if self._meta(c, o, view) is None:
                self._put_meta(view, c, o, meta)
            return False
        end = off + len(data)
        # drop the overwritten range from existing state (edge blobs
        # written there count as block writes for the fsync ordering)
        wrote = self._punch_hole(view, meta, off, end, freed)
        if len(data) <= INLINE_MAX:
            meta.setdefault("inline", {})[str(off)] = data.hex()
            if len(meta["inline"]) > 64:
                # deferred-write flush: many small writes consolidate
                # into one blob so the meta value stays bounded
                wrote |= self._compact(view, meta, freed)
        else:
            blob = self._write_blob(data)
            self._bump_blob(view, blob)
            meta.setdefault("extents", []).append([off, blob, len(data)])
            meta["extents"].sort()
            wrote = True
        meta["size"] = max(meta.get("size", 0), end)
        self._put_meta(view, c, o, meta)
        return wrote

    def _punch_hole(self, view, meta, lo, hi, freed) -> bool:
        """Remove [lo, hi) from the extent map and inline set, keeping
        non-overlapped blob sub-ranges; returns True when edge blobs
        were written to the block file (caller must fsync before the
        kv commit — the durability-ordering invariant)."""
        wrote = False
        new_extents = []
        for elo, blob, ln in meta.get("extents", []):
            ehi = elo + ln
            if ehi <= lo or elo >= hi:
                new_extents.append([elo, blob, ln])
                continue
            # overlapped: re-read SURVIVING edges into inline/new blobs;
            # a fully-covered blob is never read, so overwriting (e.g.
            # pg repair force-pushing a reconstructed object) can
            # replace a blob whose checksum no longer verifies
            edges = [
                (s, e) for s, e in ((elo, min(lo, ehi)), (max(hi, elo), ehi))
                if s < e
            ]
            if edges:
                data = self._read_blob(blob, ln)
                for s, e in edges:
                    part = data[s - elo : e - elo]
                    if len(part) <= INLINE_MAX:
                        meta.setdefault("inline", {})[str(s)] = part.hex()
                    else:
                        nb = self._write_blob(part)
                        wrote = True
                        self._bump_blob(view, nb)
                        new_extents.append([s, nb, len(part)])
            self._deref_blob_in_view(view, blob, freed)
        new_extents.sort()
        meta["extents"] = new_extents
        inline = meta.get("inline", {})
        new_inline = {}
        for hoff, hexdata in inline.items():
            s = int(hoff)
            part = bytes.fromhex(hexdata)
            e = s + len(part)
            if e <= lo or s >= hi:
                new_inline[hoff] = hexdata
                continue
            if s < lo:
                new_inline[str(s)] = part[: lo - s].hex()
            if e > hi:
                new_inline[str(hi)] = part[hi - s:].hex()
        meta["inline"] = new_inline
        return wrote

    def _compact(self, view, meta, freed) -> bool:
        """Rewrite the object's content as one blob (the deferred
        small-write flush).  Caller holds the txn lock."""
        # the span covers everything recorded so far — the caller may
        # not have folded the current write into meta["size"] yet
        size = meta.get("size", 0)
        for lo, _blob, ln in meta.get("extents", []):
            size = max(size, lo + ln)
        for hoff, hexdata in meta.get("inline", {}).items():
            size = max(size, int(hoff) + len(hexdata) // 2)
        if size == 0:
            return False
        buf = bytearray(size)
        for lo, blob, ln in meta.get("extents", []):
            data = self._read_blob(blob, ln)
            buf[lo : lo + ln] = data
            self._deref_blob_in_view(view, blob, freed)
        for hoff, hexdata in meta.get("inline", {}).items():
            part = bytes.fromhex(hexdata)
            lo = int(hoff)
            buf[lo : lo + len(part)] = part
        nb = self._write_blob(bytes(buf))
        self._bump_blob(view, nb)
        meta["extents"] = [[0, nb, size]]
        meta["inline"] = {}
        return True

    def _truncate(self, view, c, o, meta, size, freed) -> bool:
        cur = meta.get("size", 0)
        wrote = False
        if size < cur:
            wrote = self._punch_hole(view, meta, size, cur, freed)
        meta["size"] = size
        self._put_meta(view, c, o, meta)
        return wrote

    def _rm_object(self, view, c, o, freed) -> None:
        meta = self._meta(c, o, view)
        if meta:
            for _lo, blob, _ln in meta.get("extents", []):
                self._deref_blob_in_view(view, blob, freed)
        view.rmkey("O", _okey(c, o))
        base = _okey(c, o) + SEP
        for prefix in ("X", "M"):
            view.rm_range(prefix, base, _prefix_end(base))

    def _clone(self, view, src_c, src_o, dst_c, dst_o) -> bool:
        """Share blobs with the destination (the SharedBlob role):
        refcounts bump, no data moves."""
        meta = self._meta(src_c, src_o, view)
        if meta is None:
            meta = _new_meta()
        dst = json.loads(json.dumps(meta))  # deep copy
        for _lo, blob, _ln in dst.get("extents", []):
            self._bump_blob(view, blob)
        self._put_meta(view, dst_c, dst_o, dst)
        sbase = _okey(src_c, src_o) + SEP
        dbase = _okey(dst_c, dst_o) + SEP
        for prefix in ("X", "M"):
            for key, val in view.items(prefix, sbase):
                view.set(prefix, dbase + key[len(sbase):], val)
        return False

    # -- validation (shared shape with KStore) -------------------------

    _validate = None  # assigned below


def _new_meta() -> dict:
    return {"size": 0, "extents": [], "inline": {}}


def _parse_blob(blob: str) -> tuple[int, int, int, str, int]:
    """(unit, units, crc, alg, stored_len); alg == "" for raw blobs
    (3-field legacy ids stay readable — stored_len falls back to the
    extent's logical length at the read site)."""
    parts = blob.split(":")
    unit, units, crc = int(parts[0]), int(parts[1]), int(parts[2])
    if len(parts) == 5:
        return unit, units, crc, parts[3], int(parts[4])
    return unit, units, crc, "", 0


# the structural validation rules are identical to KStore's
from ceph_tpu.store.kstore import KStore as _KStore  # noqa: E402

BlockStore._validate = _KStore._validate
