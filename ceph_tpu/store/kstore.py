"""KStore: an ObjectStore that keeps whole objects in a KeyValueDB.

Behavioral twin of the reference's kv-only store (src/os/kstore/
KStore.cc): object data is chunked into fixed stripes stored as kv
values, xattrs/omap ride dedicated column families, and every
ObjectStore transaction commits as ONE atomic WriteBatch — giving the
OSD the same all-or-nothing contract as MemStore/FileStore but with
the metadata layout BlueStore-family engines use (RocksDB column
families; here ceph_tpu.kv.FileDB's WAL+checkpoint provides the
durability).

Column families: C (collections), O (object sizes), D (data stripes),
X (xattrs), M (omap).  Keys join components with \\x01 so collection
scans are ordered prefix ranges; object names are escaped so a name
containing the separator cannot inject into another object's key space
(the reference KStore's append_escaped, src/os/kstore/KStore.cc).
"""

from __future__ import annotations

import struct
import threading

from ceph_tpu.kv import MemDB, WriteBatch
from ceph_tpu.store.objectstore import (
    ObjectStore,
    Transaction,
    TxOp,
    coll_t,
    ghobject_t,
)

SEP = "\x01"
ESC = "\x02"
STRIPE = 65536


def _esc(s: str) -> str:
    """Escape SEP/ESC out of a key component (reversible, SEP-free)."""
    return s.replace(ESC, ESC + "e").replace(SEP, ESC + "s")


def _unesc(s: str) -> str:
    return s.replace(ESC + "s", SEP).replace(ESC + "e", ESC)


def _prefix_end(prefix: str) -> str:
    """Exclusive upper bound covering every key that starts with
    ``prefix`` (bump the last non-maximal code point)."""
    i = len(prefix) - 1
    while i >= 0 and ord(prefix[i]) >= 0x10FFFF:
        i -= 1
    assert i >= 0, "degenerate prefix"
    return prefix[:i] + chr(ord(prefix[i]) + 1)


def _ckey(c: coll_t) -> str:
    return f"{c.pool}.{c.ps}.{c.shard}"


def _okey(c: coll_t, o: ghobject_t) -> str:
    return _ckey(c) + SEP + f"{_esc(o.name)}{SEP}{o.snap}{SEP}{o.gen}{SEP}{o.shard}"


def _parse_okey(key: str) -> tuple[str, ghobject_t]:
    ck, name, snap, gen, shard = key.split(SEP)
    return ck, ghobject_t(_unesc(name), int(snap), int(gen), int(shard))


class _TxnView:
    """One transaction's mutations mirrored over the committed db.

    Every mutation goes into the WriteBatch (the atomic commit unit)
    AND an in-memory overlay, so later ops in the same transaction read
    their predecessors' effects across ALL column families: a REMOVE
    hides committed keys from a following re-create, and CLONE sees
    same-txn writes of data, xattrs and omap alike.
    """

    def __init__(self, db, batch: WriteBatch):
        self.db = db
        self.batch = batch
        self._over: dict[str, dict[str, bytes | None]] = {}  # None = deleted
        self._dead: dict[str, list[tuple[str, str]]] = {}    # range tombstones

    def set(self, p: str, k: str, v: bytes) -> None:
        self.batch.set(p, k, v)
        self._over.setdefault(p, {})[k] = bytes(v)

    def rmkey(self, p: str, k: str) -> None:
        self.batch.rmkey(p, k)
        self._over.setdefault(p, {})[k] = None

    def rm_range(self, p: str, start: str, end: str) -> None:
        self.batch.rm_range(p, start, end)
        over = self._over.setdefault(p, {})
        for k in [k for k in over if start <= k < end]:
            del over[k]
        self._dead.setdefault(p, []).append((start, end))

    def get(self, p: str, k: str) -> bytes | None:
        over = self._over.get(p, {})
        if k in over:
            return over[k]
        if any(s <= k < e for s, e in self._dead.get(p, ())):
            return None
        return self.db.get(p, k)

    def items(self, p: str, prefix: str) -> list[tuple[str, bytes]]:
        """Sorted (key, value) pairs under ``prefix``, txn effects
        included (committed minus tombstones, then overlay wins)."""
        out: dict[str, bytes] = {}
        it = self.db.get_iterator(p).lower_bound(prefix)
        while it.valid() and it.key().startswith(prefix):
            out[it.key()] = it.value()
            it.next()
        for s, e in self._dead.get(p, ()):
            for k in [k for k in out if s <= k < e]:
                del out[k]
        for k, v in self._over.get(p, {}).items():
            if k.startswith(prefix):
                if v is None:
                    out.pop(k, None)
                else:
                    out[k] = v
        return sorted(out.items())


class KStore(ObjectStore):
    def __init__(self, db=None):
        self.db = db if db is not None else MemDB()
        # one txn translates+submits at a time: queue_transaction may run
        # on a worker thread (blocking_commit) while reads stay on the
        # event loop
        self._txn_lock = threading.Lock()

    @property
    def blocking_commit(self) -> bool:
        """Forward the backing DB's fsync behavior so the OSD/mon move
        commits off the event loop (FileDB fsyncs per batch)."""
        return bool(getattr(self.db, "blocking_commit", False))

    def statfs(self) -> dict:
        """Backing-fs truth when the kv store lives on disk (FileDB
        with a path), else a large virtual device."""
        import os as _os

        path = getattr(self.db, "path", None)
        if path and _os.path.isdir(_os.path.dirname(path) or path):
            st = _os.statvfs(_os.path.dirname(path) or path)
            total = st.f_frsize * st.f_blocks
            avail = st.f_frsize * st.f_bavail
            return {"total": total, "used": max(0, total - avail),
                    "available": avail}
        return {"total": 1 << 40, "used": 0, "available": 1 << 40}

    def mount(self) -> None:
        if hasattr(self.db, "mount"):
            self.db.mount()

    def umount(self) -> None:
        if hasattr(self.db, "umount"):
            self.db.umount()

    # -- reads ---------------------------------------------------------

    def _size_of(self, c: coll_t, o: ghobject_t) -> int | None:
        raw = self.db.get("O", _okey(c, o))
        return None if raw is None else struct.unpack("<Q", raw)[0]

    def _require(self, c: coll_t, o: ghobject_t) -> int:
        if not self.collection_exists(c):
            raise FileNotFoundError(f"collection {c}")
        size = self._size_of(c, o)
        if size is None:
            raise FileNotFoundError(f"{c}/{o}")
        return size

    def read(self, c, o, off=0, length=None):
        size = self._require(c, o)
        end = size if length is None else min(off + length, size)
        if off >= end:
            return b""
        out = bytearray(end - off)
        base = _okey(c, o) + SEP
        s0, s1 = off // STRIPE, (end - 1) // STRIPE
        for s in range(s0, s1 + 1):
            stripe = self.db.get("D", base + f"{s:08x}") or b""
            lo = max(off, s * STRIPE)
            hi = min(end, s * STRIPE + STRIPE)
            seg = stripe[lo - s * STRIPE : hi - s * STRIPE]
            out[lo - off : lo - off + len(seg)] = seg
        return bytes(out)

    def stat(self, c, o):
        return self._require(c, o)

    def exists(self, c, o):
        return self.collection_exists(c) and self._size_of(c, o) is not None

    def getattr(self, c, o, name):
        self._require(c, o)
        raw = self.db.get("X", _okey(c, o) + SEP + name)
        if raw is None:
            raise KeyError(name)
        return raw

    def getattrs(self, c, o):
        self._require(c, o)
        base = _okey(c, o) + SEP
        it = self.db.get_iterator("X").lower_bound(base)
        out = {}
        while it.valid() and it.key().startswith(base):
            out[it.key()[len(base):]] = it.value()
            it.next()
        return out

    def omap_get(self, c, o):
        self._require(c, o)
        base = _okey(c, o) + SEP
        it = self.db.get_iterator("M").lower_bound(base)
        out = {}
        while it.valid() and it.key().startswith(base):
            out[it.key()[len(base):]] = it.value()
            it.next()
        return out

    def omap_get_values(self, c, o, keys):
        self._require(c, o)
        base = _okey(c, o) + SEP
        out = {}
        for k in keys:
            v = self.db.get("M", base + k)
            if v is not None:
                out[k] = v
        return out

    def list_collections(self):
        it = self.db.get_iterator("C").seek_to_first()
        out = []
        while it.valid():
            pool, ps, shard = it.key().split(".")
            out.append(coll_t(int(pool), int(ps), int(shard)))
            it.next()
        return sorted(out)

    def collection_exists(self, c):
        return self.db.get("C", _ckey(c)) is not None

    def collection_list(self, c):
        if not self.collection_exists(c):
            raise FileNotFoundError(f"collection {c}")
        base = _ckey(c) + SEP
        it = self.db.get_iterator("O").lower_bound(base)
        out = []
        while it.valid() and it.key().startswith(base):
            out.append(_parse_okey(it.key())[1])
            it.next()
        return sorted(out)

    # -- transactions --------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        # validate against a shadow of existence state, then translate
        # to ONE atomic WriteBatch (the all-or-nothing contract); a
        # _TxnView overlays the batch's own mutations so later ops in
        # the same txn read their predecessors' effects
        with self._txn_lock:
            self._validate(txn)
            batch = WriteBatch()
            view = _TxnView(self.db, batch)
            for op in txn.ops:
                self._translate(op, view)
            self.db.submit(batch)
        for cb in txn.on_applied:
            cb()
        for cb in txn.on_commit:
            cb()

    @staticmethod
    def _size_of_view(view: "_TxnView", c: coll_t, o: ghobject_t) -> int | None:
        raw = view.get("O", _okey(c, o))
        return None if raw is None else struct.unpack("<Q", raw)[0]

    def _translate(self, op, view: "_TxnView") -> None:
        def size_of(c, o):
            return self._size_of_view(view, c, o)

        def set_size(c, o, n):
            view.set("O", _okey(c, o), struct.pack("<Q", n))

        def write_span(c, o, off, data):
            base = _okey(c, o) + SEP
            pos = 0
            while pos < len(data):
                s = (off + pos) // STRIPE
                s_off = (off + pos) % STRIPE
                n = min(STRIPE - s_off, len(data) - pos)
                old = view.get("D", base + f"{s:08x}") or b""
                buf = bytearray(max(len(old), s_off + n))
                buf[: len(old)] = old
                buf[s_off : s_off + n] = data[pos : pos + n]
                view.set("D", base + f"{s:08x}", bytes(buf))
                pos += n

        kind = op[0]
        if kind == TxOp.MKCOLL:
            view.set("C", _ckey(op[1]), b"1")
        elif kind == TxOp.RMCOLL:
            view.rmkey("C", _ckey(op[1]))
        elif kind == TxOp.TOUCH:
            _, c, o = op
            if size_of(c, o) is None:
                set_size(c, o, 0)
        elif kind == TxOp.WRITE:
            _, c, o, off, data = op
            cur = size_of(c, o) or 0
            write_span(c, o, off, data)
            if off + len(data) > cur or size_of(c, o) is None:
                set_size(c, o, max(cur, off + len(data)))
        elif kind == TxOp.ZERO:
            _, c, o, off, length = op
            cur = size_of(c, o) or 0
            write_span(c, o, off, b"\0" * length)
            set_size(c, o, max(cur, off + length))
        elif kind == TxOp.TRUNCATE:
            _, c, o, size = op
            cur = size_of(c, o) or 0
            if size < cur:
                base = _okey(c, o) + SEP
                last_keep = (size - 1) // STRIPE if size else -1
                for s in range(max(last_keep, 0), cur // STRIPE + 1):
                    if s > last_keep:
                        view.rmkey("D", base + f"{s:08x}")
                if size % STRIPE and size:
                    s = size // STRIPE
                    old = view.get("D", base + f"{s:08x}") or b""
                    view.set("D", base + f"{s:08x}", old[: size % STRIPE])
            set_size(c, o, size)
        elif kind == TxOp.REMOVE:
            _, c, o = op
            self._rm_object(view, c, o)
        elif kind == TxOp.SETATTRS:
            _, c, o, attrs = op
            if size_of(c, o) is None:
                set_size(c, o, 0)
            for k, v in attrs.items():
                view.set("X", _okey(c, o) + SEP + k, v)
        elif kind == TxOp.RMATTR:
            _, c, o, name = op
            view.rmkey("X", _okey(c, o) + SEP + name)
        elif kind == TxOp.OMAP_SETKEYS:
            _, c, o, kv = op
            if size_of(c, o) is None:
                set_size(c, o, 0)
            for k, v in kv.items():
                view.set("M", _okey(c, o) + SEP + k, v)
        elif kind == TxOp.OMAP_RMKEYS:
            _, c, o, keys = op
            if size_of(c, o) is None:
                set_size(c, o, 0)
            for k in keys:
                view.rmkey("M", _okey(c, o) + SEP + k)
        elif kind == TxOp.OMAP_CLEAR:
            _, c, o = op
            base = _okey(c, o) + SEP
            view.rm_range("M", base, _prefix_end(base))
            if size_of(c, o) is None:
                set_size(c, o, 0)
        elif kind == TxOp.CLONE:
            _, c, src, dst = op
            size = size_of(c, src)
            set_size(c, dst, size or 0)
            self._copy_object_keys(view, _okey(c, src) + SEP,
                                   _okey(c, dst) + SEP)
        elif kind == TxOp.COLL_MOVE_RENAME:
            _, src_c, src_o, dst_c, dst_o = op
            size = size_of(src_c, src_o)
            self._copy_object_keys(view, _okey(src_c, src_o) + SEP,
                                   _okey(dst_c, dst_o) + SEP)
            set_size(dst_c, dst_o, size or 0)
            self._rm_object(view, src_c, src_o)
        else:  # pragma: no cover
            raise ValueError(f"unknown op {kind}")

    @staticmethod
    def _copy_object_keys(view: "_TxnView", sbase: str, dbase: str) -> None:
        for prefix in ("D", "X", "M"):
            for key, val in view.items(prefix, sbase):
                view.set(prefix, dbase + key[len(sbase):], val)

    @staticmethod
    def _rm_object(view: "_TxnView", c: coll_t, o: ghobject_t) -> None:
        view.rmkey("O", _okey(c, o))
        base = _okey(c, o) + SEP
        for prefix in ("D", "X", "M"):
            view.rm_range(prefix, base, _prefix_end(base))

    # -- validation (MemStore-grade structural checks) -----------------

    def _validate(self, txn: Transaction) -> None:
        have_coll = {c for c in self.list_collections()}
        objs: dict[tuple, bool] = {}

        def obj_exists(c, o):
            key = (c, o)
            if key not in objs:
                objs[key] = self.exists(c, o)
            return objs[key]

        for op in txn.ops:
            kind = op[0]
            if kind == TxOp.MKCOLL:
                if op[1] in have_coll:
                    raise FileExistsError(f"collection {op[1]} exists")
                have_coll.add(op[1])
            elif kind == TxOp.RMCOLL:
                if op[1] not in have_coll:
                    raise FileNotFoundError(f"collection {op[1]}")
                # ENOTEMPTY semantics (MemStore parity): account for
                # objects created/removed earlier in this same txn
                residual = set()
                if self.collection_exists(op[1]):
                    residual = {(op[1], o) for o in self.collection_list(op[1])}
                for (oc, oo), alive in objs.items():
                    if oc == op[1]:
                        (residual.add if alive else residual.discard)((oc, oo))
                if residual:
                    raise OSError(f"collection {op[1]} not empty")
                have_coll.discard(op[1])
            elif kind == TxOp.COLL_MOVE_RENAME:
                _, src_c, src_o, dst_c, dst_o = op
                if src_c not in have_coll or not obj_exists(src_c, src_o):
                    raise FileNotFoundError(f"{src_c}/{src_o}")
                if dst_c not in have_coll:
                    raise FileNotFoundError(f"collection {dst_c}")
                if obj_exists(dst_c, dst_o):
                    raise FileExistsError(f"{dst_c}/{dst_o}")
                objs[(src_c, src_o)] = False
                objs[(dst_c, dst_o)] = True
            else:
                c = op[1]
                if c not in have_coll:
                    raise FileNotFoundError(f"collection {c}")
                if kind == TxOp.CLONE:
                    _, _, src, dst = op
                    if not obj_exists(c, src):
                        raise FileNotFoundError(f"{c}/{src}")
                    objs[(c, dst)] = True
                elif kind == TxOp.REMOVE:
                    _, _, o = op
                    if not obj_exists(c, o):
                        raise FileNotFoundError(f"{c}/{o}")
                    objs[(c, o)] = False
                elif kind == TxOp.RMATTR:
                    _, _, o, _name = op
                    if not obj_exists(c, o):
                        raise FileNotFoundError(f"{c}/{o}")
                else:
                    objs[(op[1], op[2])] = True
