"""KStore: an ObjectStore that keeps whole objects in a KeyValueDB.

Behavioral twin of the reference's kv-only store (src/os/kstore/
KStore.cc): object data is chunked into fixed stripes stored as kv
values, xattrs/omap ride dedicated column families, and every
ObjectStore transaction commits as ONE atomic WriteBatch — giving the
OSD the same all-or-nothing contract as MemStore/FileStore but with
the metadata layout BlueStore-family engines use (RocksDB column
families; here ceph_tpu.kv.FileDB's WAL+checkpoint provides the
durability).

Column families: C (collections), O (object sizes), D (data stripes),
X (xattrs), M (omap).  Keys join components with \\x01 so collection
scans are ordered prefix ranges.
"""

from __future__ import annotations

import struct

from ceph_tpu.kv import MemDB, WriteBatch
from ceph_tpu.store.objectstore import (
    ObjectStore,
    Transaction,
    TxOp,
    coll_t,
    ghobject_t,
)

SEP = "\x01"
STRIPE = 65536


def _ckey(c: coll_t) -> str:
    return f"{c.pool}.{c.ps}.{c.shard}"


def _okey(c: coll_t, o: ghobject_t) -> str:
    return _ckey(c) + SEP + f"{o.name}{SEP}{o.snap}{SEP}{o.gen}{SEP}{o.shard}"


def _parse_okey(key: str) -> tuple[str, ghobject_t]:
    ck, name, snap, gen, shard = key.split(SEP)
    return ck, ghobject_t(name, int(snap), int(gen), int(shard))


class KStore(ObjectStore):
    def __init__(self, db=None):
        self.db = db if db is not None else MemDB()

    def mount(self) -> None:
        if hasattr(self.db, "mount"):
            self.db.mount()

    def umount(self) -> None:
        if hasattr(self.db, "umount"):
            self.db.umount()

    # -- reads ---------------------------------------------------------

    def _size_of(self, c: coll_t, o: ghobject_t) -> int | None:
        raw = self.db.get("O", _okey(c, o))
        return None if raw is None else struct.unpack("<Q", raw)[0]

    def _require(self, c: coll_t, o: ghobject_t) -> int:
        if not self.collection_exists(c):
            raise FileNotFoundError(f"collection {c}")
        size = self._size_of(c, o)
        if size is None:
            raise FileNotFoundError(f"{c}/{o}")
        return size

    def read(self, c, o, off=0, length=None):
        size = self._require(c, o)
        end = size if length is None else min(off + length, size)
        if off >= end:
            return b""
        out = bytearray(end - off)
        base = _okey(c, o) + SEP
        s0, s1 = off // STRIPE, (end - 1) // STRIPE
        for s in range(s0, s1 + 1):
            stripe = self.db.get("D", base + f"{s:08x}") or b""
            lo = max(off, s * STRIPE)
            hi = min(end, s * STRIPE + STRIPE)
            seg = stripe[lo - s * STRIPE : hi - s * STRIPE]
            out[lo - off : lo - off + len(seg)] = seg
        return bytes(out)

    def stat(self, c, o):
        return self._require(c, o)

    def exists(self, c, o):
        return self.collection_exists(c) and self._size_of(c, o) is not None

    def getattr(self, c, o, name):
        self._require(c, o)
        raw = self.db.get("X", _okey(c, o) + SEP + name)
        if raw is None:
            raise KeyError(name)
        return raw

    def getattrs(self, c, o):
        self._require(c, o)
        base = _okey(c, o) + SEP
        it = self.db.get_iterator("X").lower_bound(base)
        out = {}
        while it.valid() and it.key().startswith(base):
            out[it.key()[len(base):]] = it.value()
            it.next()
        return out

    def omap_get(self, c, o):
        self._require(c, o)
        base = _okey(c, o) + SEP
        it = self.db.get_iterator("M").lower_bound(base)
        out = {}
        while it.valid() and it.key().startswith(base):
            out[it.key()[len(base):]] = it.value()
            it.next()
        return out

    def omap_get_values(self, c, o, keys):
        self._require(c, o)
        base = _okey(c, o) + SEP
        out = {}
        for k in keys:
            v = self.db.get("M", base + k)
            if v is not None:
                out[k] = v
        return out

    def list_collections(self):
        it = self.db.get_iterator("C").seek_to_first()
        out = []
        while it.valid():
            pool, ps, shard = it.key().split(".")
            out.append(coll_t(int(pool), int(ps), int(shard)))
            it.next()
        return sorted(out)

    def collection_exists(self, c):
        return self.db.get("C", _ckey(c)) is not None

    def collection_list(self, c):
        if not self.collection_exists(c):
            raise FileNotFoundError(f"collection {c}")
        base = _ckey(c) + SEP
        it = self.db.get_iterator("O").lower_bound(base)
        out = []
        while it.valid() and it.key().startswith(base):
            out.append(_parse_okey(it.key())[1])
            it.next()
        return sorted(out)

    # -- transactions --------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        # validate against a shadow of existence state, then translate
        # to ONE atomic WriteBatch (the all-or-nothing contract)
        self._validate(txn)
        batch = WriteBatch()
        # data mutations need read-modify-write of stripes; sizes track
        # through the txn so later ops in the same txn see earlier ones
        sizes: dict[tuple, int | None] = {}

        def size_of(c, o):
            key = (c, o)
            if key not in sizes:
                sizes[key] = self._size_of(c, o)
            return sizes[key]

        def set_size(c, o, n):
            sizes[(c, o)] = n
            batch.set("O", _okey(c, o), struct.pack("<Q", n))

        def write_span(c, o, off, data):
            base = _okey(c, o) + SEP
            pos = 0
            while pos < len(data):
                s = (off + pos) // STRIPE
                s_off = (off + pos) % STRIPE
                n = min(STRIPE - s_off, len(data) - pos)
                old = self.db.get("D", base + f"{s:08x}") or b""
                buf = bytearray(max(len(old), s_off + n))
                buf[: len(old)] = old
                buf[s_off : s_off + n] = data[pos : pos + n]
                batch.set("D", base + f"{s:08x}", bytes(buf))
                # later ops in this txn must see this write
                self._pending_stripes[base + f"{s:08x}"] = bytes(buf)
                pos += n

        # overlay for intra-txn stripe reads
        self._pending_stripes: dict[str, bytes] = {}
        real_get = self.db.get

        def get_overlay(prefix, key):
            if prefix == "D" and key in self._pending_stripes:
                return self._pending_stripes[key]
            return real_get(prefix, key)

        self.db.get = get_overlay  # type: ignore[assignment]
        try:
            for op in txn.ops:
                self._translate(op, batch, size_of, set_size, write_span)
        finally:
            self.db.get = real_get  # type: ignore[assignment]
            self._pending_stripes = {}
        self.db.submit(batch)
        for cb in txn.on_applied:
            cb()
        for cb in txn.on_commit:
            cb()

    def _translate(self, op, batch, size_of, set_size, write_span) -> None:
        kind = op[0]
        if kind == TxOp.MKCOLL:
            batch.set("C", _ckey(op[1]), b"1")
        elif kind == TxOp.RMCOLL:
            batch.rmkey("C", _ckey(op[1]))
        elif kind == TxOp.TOUCH:
            _, c, o = op
            if size_of(c, o) is None:
                set_size(c, o, 0)
        elif kind == TxOp.WRITE:
            _, c, o, off, data = op
            cur = size_of(c, o) or 0
            write_span(c, o, off, data)
            if off + len(data) > cur or size_of(c, o) is None:
                set_size(c, o, max(cur, off + len(data)))
        elif kind == TxOp.ZERO:
            _, c, o, off, length = op
            cur = size_of(c, o) or 0
            write_span(c, o, off, b"\0" * length)
            set_size(c, o, max(cur, off + length))
        elif kind == TxOp.TRUNCATE:
            _, c, o, size = op
            cur = size_of(c, o) or 0
            if size < cur:
                base = _okey(c, o) + SEP
                last_keep = (size - 1) // STRIPE if size else -1
                for s in range(max(last_keep, 0), cur // STRIPE + 1):
                    if s > last_keep:
                        batch.rmkey("D", base + f"{s:08x}")
                        self._pending_stripes[base + f"{s:08x}"] = b""
                if size % STRIPE and size:
                    s = size // STRIPE
                    old = self.db.get("D", base + f"{s:08x}") or b""
                    batch.set("D", base + f"{s:08x}", old[: size % STRIPE])
                    self._pending_stripes[base + f"{s:08x}"] = old[: size % STRIPE]
            set_size(c, o, size)
        elif kind == TxOp.REMOVE:
            _, c, o = op
            self._rm_object(batch, c, o)
        elif kind == TxOp.SETATTRS:
            _, c, o, attrs = op
            if size_of(c, o) is None:
                set_size(c, o, 0)
            for k, v in attrs.items():
                batch.set("X", _okey(c, o) + SEP + k, v)
        elif kind == TxOp.RMATTR:
            _, c, o, name = op
            batch.rmkey("X", _okey(c, o) + SEP + name)
        elif kind == TxOp.OMAP_SETKEYS:
            _, c, o, kv = op
            if size_of(c, o) is None:
                set_size(c, o, 0)
            for k, v in kv.items():
                batch.set("M", _okey(c, o) + SEP + k, v)
        elif kind == TxOp.OMAP_RMKEYS:
            _, c, o, keys = op
            if size_of(c, o) is None:
                set_size(c, o, 0)
            for k in keys:
                batch.rmkey("M", _okey(c, o) + SEP + k)
        elif kind == TxOp.OMAP_CLEAR:
            _, c, o = op
            base = _okey(c, o) + SEP
            batch.rm_range("M", base, base + "\x7f")
            if size_of(c, o) is None:
                set_size(c, o, 0)
        elif kind == TxOp.CLONE:
            _, c, src, dst = op
            size = size_of(c, src)
            sbase = _okey(c, src) + SEP
            dbase = _okey(c, dst) + SEP
            set_size(c, dst, size or 0)
            for prefix in ("D", "X", "M"):
                it = self.db.get_iterator(prefix).lower_bound(sbase)
                while it.valid() and it.key().startswith(sbase):
                    batch.set(prefix, dbase + it.key()[len(sbase):], it.value())
                    it.next()
        elif kind == TxOp.COLL_MOVE_RENAME:
            _, src_c, src_o, dst_c, dst_o = op
            size = size_of(src_c, src_o)
            sbase = _okey(src_c, src_o) + SEP
            dbase = _okey(dst_c, dst_o) + SEP
            for prefix in ("D", "X", "M"):
                it = self.db.get_iterator(prefix).lower_bound(sbase)
                while it.valid() and it.key().startswith(sbase):
                    batch.set(prefix, dbase + it.key()[len(sbase):], it.value())
                    it.next()
            set_size(dst_c, dst_o, size or 0)
            self._rm_object(batch, src_c, src_o)
        else:  # pragma: no cover
            raise ValueError(f"unknown op {kind}")

    def _rm_object(self, batch: WriteBatch, c: coll_t, o: ghobject_t) -> None:
        batch.rmkey("O", _okey(c, o))
        base = _okey(c, o) + SEP
        for prefix in ("D", "X", "M"):
            batch.rm_range(prefix, base, base + "\x7f")

    # -- validation (MemStore-grade structural checks) -----------------

    def _validate(self, txn: Transaction) -> None:
        have_coll = {c for c in self.list_collections()}
        objs: dict[tuple, bool] = {}

        def obj_exists(c, o):
            key = (c, o)
            if key not in objs:
                objs[key] = self.exists(c, o)
            return objs[key]

        for op in txn.ops:
            kind = op[0]
            if kind == TxOp.MKCOLL:
                if op[1] in have_coll:
                    raise FileExistsError(f"collection {op[1]} exists")
                have_coll.add(op[1])
            elif kind == TxOp.RMCOLL:
                if op[1] not in have_coll:
                    raise FileNotFoundError(f"collection {op[1]}")
                have_coll.discard(op[1])
            elif kind == TxOp.COLL_MOVE_RENAME:
                _, src_c, src_o, dst_c, dst_o = op
                if src_c not in have_coll or not obj_exists(src_c, src_o):
                    raise FileNotFoundError(f"{src_c}/{src_o}")
                if dst_c not in have_coll:
                    raise FileNotFoundError(f"collection {dst_c}")
                if obj_exists(dst_c, dst_o):
                    raise FileExistsError(f"{dst_c}/{dst_o}")
                objs[(src_c, src_o)] = False
                objs[(dst_c, dst_o)] = True
            else:
                c = op[1]
                if c not in have_coll:
                    raise FileNotFoundError(f"collection {c}")
                if kind == TxOp.CLONE:
                    _, _, src, dst = op
                    if not obj_exists(c, src):
                        raise FileNotFoundError(f"{c}/{src}")
                    objs[(c, dst)] = True
                elif kind == TxOp.REMOVE:
                    _, _, o = op
                    if not obj_exists(c, o):
                        raise FileNotFoundError(f"{c}/{o}")
                    objs[(c, o)] = False
                elif kind == TxOp.RMATTR:
                    _, _, o, _name = op
                    if not obj_exists(c, o):
                        raise FileNotFoundError(f"{c}/{o}")
                else:
                    objs[(op[1], op[2])] = True
