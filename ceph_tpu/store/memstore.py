"""MemStore: in-RAM ObjectStore with all-or-nothing transactions.

Behavioral twin of the reference test/dev engine
(src/os/memstore/MemStore.{h,cc}): a dict of collections of objects,
each object = data buffer + xattrs + omap.  Like the reference MemStore
(and unlike BlueStore), apply == commit, so both callback sets fire
synchronously at queue_transaction.

Atomicity: the reference applies ops in order and asserts mid-txn
failures in debug; here a transaction validates against a shadow state
first and raises before mutating anything, so a failed transaction
leaves the store untouched (the stronger contract the OSD relies on).
"""

from __future__ import annotations

import threading

from ceph_tpu.common.fault_injector import (
    store_data_fault,
    store_fault_check,
)
from ceph_tpu.store.objectstore import (
    ObjectStore,
    Transaction,
    TxOp,
    coll_t,
    ghobject_t,
)


class _Obj:
    __slots__ = ("data", "xattrs", "omap")

    def __init__(self) -> None:
        self.data = bytearray()
        self.xattrs: dict[str, bytes] = {}
        self.omap: dict[str, bytes] = {}

    def clone(self) -> "_Obj":
        o = _Obj()
        o.data = bytearray(self.data)
        o.xattrs = dict(self.xattrs)
        o.omap = dict(self.omap)
        return o


class MemStore(ObjectStore):
    def __init__(self, quota_bytes: int = 1 << 40) -> None:
        self._colls: dict[coll_t, dict[ghobject_t, _Obj]] = {}
        self._lock = threading.RLock()
        # virtual device size for the statfs/fullness plane (tests set
        # it small to drive FULL states; reference MemStore reports
        # memstore_device_bytes the same way)
        self.quota_bytes = quota_bytes

    def statfs(self) -> dict:
        with self._lock:
            used = sum(
                len(o.data)
                for objs in self._colls.values() for o in objs.values()
            )
        return {
            "total": self.quota_bytes,
            "used": used,
            "available": max(0, self.quota_bytes - used),
        }

    def mount(self) -> None:
        store_fault_check("mount", self.fault_domain)

    # -- transactions --------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        store_fault_check("write", self.fault_domain)
        with self._lock:
            self._validate(txn)
            tear = store_data_fault("write", self.fault_domain)
            if tear is not None and tear.get("torn"):
                # torn write: a prefix of the transaction lands, then
                # the "disk" dies mid-commit — deliberately violating
                # the all-or-nothing contract the OSD relies on, which
                # is exactly what scrub/recovery must then absorb
                for op in txn.ops[: len(txn.ops) // 2]:
                    self._apply(op)
                from ceph_tpu.common.fault_injector import InjectedError

                raise InjectedError(5, "injected torn write (memstore)")
            for op in txn.ops:
                self._apply(op)
        # commit point: an error here means state applied but the
        # caller never learns (the lost-ack flavor of a dying disk)
        store_fault_check("commit", self.fault_domain)
        for cb in txn.on_applied:
            cb()
        for cb in txn.on_commit:
            cb()

    def validate(self, txn: Transaction) -> None:
        """Raise (mutating nothing) if the transaction cannot apply —
        journaling backends check this before persisting."""
        with self._lock:
            self._validate(txn)

    def _validate(self, txn: Transaction) -> None:
        """Dry-run structural checks so apply can't fail halfway."""
        # simulated collection/object existence (cheap: sets of keys)
        colls = {c: set(objs) for c, objs in self._colls.items()}
        for op in txn.ops:
            kind = op[0]
            if kind == TxOp.MKCOLL:
                if op[1] in colls:
                    raise FileExistsError(f"collection {op[1]} exists")
                colls[op[1]] = set()
                continue
            if kind == TxOp.RMCOLL:
                if op[1] not in colls:
                    raise FileNotFoundError(f"collection {op[1]}")
                if colls[op[1]]:
                    raise OSError(f"collection {op[1]} not empty")
                del colls[op[1]]
                continue
            if kind == TxOp.COLL_MOVE_RENAME:
                _, src_c, src_o, dst_c, dst_o = op
                if src_c not in colls or src_o not in colls[src_c]:
                    raise FileNotFoundError(f"{src_c}/{src_o}")
                if dst_c not in colls:
                    raise FileNotFoundError(f"collection {dst_c}")
                if dst_o in colls[dst_c]:
                    # reference MemStore::_collection_move_rename -EEXIST
                    raise FileExistsError(f"{dst_c}/{dst_o}")
                colls[src_c].discard(src_o)
                colls[dst_c].add(dst_o)
                continue
            c = op[1]
            if c not in colls:
                raise FileNotFoundError(f"collection {c}")
            if kind == TxOp.CLONE:
                _, _, src, dst = op
                if src not in colls[c]:
                    raise FileNotFoundError(f"{c}/{src}")
                colls[c].add(dst)
            elif kind == TxOp.REMOVE:
                _, _, o = op
                if o not in colls[c]:
                    raise FileNotFoundError(f"{c}/{o}")
                colls[c].discard(o)
            elif kind in (TxOp.TOUCH, TxOp.WRITE, TxOp.ZERO, TxOp.TRUNCATE,
                          TxOp.SETATTRS, TxOp.OMAP_SETKEYS, TxOp.OMAP_RMKEYS,
                          TxOp.OMAP_CLEAR):
                # create-on-write semantics
                colls[c].add(op[2])
            elif kind == TxOp.RMATTR:
                _, _, o, _name = op
                if o not in colls[c]:
                    raise FileNotFoundError(f"{c}/{o}")

    def _obj(self, c: coll_t, o: ghobject_t, create: bool = False) -> _Obj:
        coll = self._colls.get(c)
        if coll is None:
            raise FileNotFoundError(f"collection {c}")
        if o not in coll:
            if not create:
                raise FileNotFoundError(f"{c}/{o}")
            coll[o] = _Obj()
        return coll[o]

    def _apply(self, op: tuple) -> None:
        kind = op[0]
        if kind == TxOp.TOUCH:
            self._obj(op[1], op[2], create=True)
        elif kind == TxOp.WRITE:
            _, c, o, off, data = op
            obj = self._obj(c, o, create=True)
            if len(obj.data) < off + len(data):
                obj.data.extend(b"\0" * (off + len(data) - len(obj.data)))
            obj.data[off : off + len(data)] = data
        elif kind == TxOp.ZERO:
            _, c, o, off, length = op
            obj = self._obj(c, o, create=True)
            if len(obj.data) < off + length:
                obj.data.extend(b"\0" * (off + length - len(obj.data)))
            obj.data[off : off + length] = b"\0" * length
        elif kind == TxOp.TRUNCATE:
            _, c, o, size = op
            obj = self._obj(c, o, create=True)
            if len(obj.data) > size:
                del obj.data[size:]
            else:
                obj.data.extend(b"\0" * (size - len(obj.data)))
        elif kind == TxOp.REMOVE:
            _, c, o = op
            del self._colls[c][o]
        elif kind == TxOp.SETATTRS:
            _, c, o, attrs = op
            self._obj(c, o, create=True).xattrs.update(attrs)
        elif kind == TxOp.RMATTR:
            _, c, o, name = op
            self._obj(c, o).xattrs.pop(name, None)
        elif kind == TxOp.OMAP_SETKEYS:
            _, c, o, kv = op
            self._obj(c, o, create=True).omap.update(kv)
        elif kind == TxOp.OMAP_RMKEYS:
            _, c, o, keys = op
            omap = self._obj(c, o, create=True).omap
            for key in keys:
                omap.pop(key, None)
        elif kind == TxOp.OMAP_CLEAR:
            _, c, o = op
            self._obj(c, o, create=True).omap.clear()
        elif kind == TxOp.CLONE:
            _, c, src, dst = op
            self._colls[c][dst] = self._obj(c, src).clone()
        elif kind == TxOp.MKCOLL:
            self._colls[op[1]] = {}
        elif kind == TxOp.RMCOLL:
            del self._colls[op[1]]
        elif kind == TxOp.COLL_MOVE_RENAME:
            _, src_c, src_o, dst_c, dst_o = op
            self._colls[dst_c][dst_o] = self._colls[src_c].pop(src_o)
        else:  # pragma: no cover
            raise ValueError(f"unknown op {kind}")

    # -- reads ---------------------------------------------------------

    def read(self, c, o, off=0, length=None):
        store_fault_check("read", self.fault_domain)
        with self._lock:
            data = self._obj(c, o).data
            if data and store_data_fault(
                    "read", self.fault_domain, peek=True):
                spec = store_data_fault("read", self.fault_domain)
                if spec is not None and spec.get("bitflip"):
                    # silent bit rot AT REST: MemStore has no checksums
                    # (the no-csum store class), so the corruption rides
                    # out to the caller — only deep scrub's cross-member
                    # crc comparison can catch it (and repair heal it)
                    data[len(data) // 2] ^= 0x40
            end = len(data) if length is None else min(off + length, len(data))
            return bytes(data[off:end])

    def stat(self, c, o):
        with self._lock:
            return len(self._obj(c, o).data)

    def exists(self, c, o):
        with self._lock:
            return c in self._colls and o in self._colls[c]

    def getattr(self, c, o, name):
        with self._lock:
            return self._obj(c, o).xattrs[name]

    def getattrs(self, c, o):
        with self._lock:
            return dict(self._obj(c, o).xattrs)

    def omap_get(self, c, o):
        with self._lock:
            return dict(self._obj(c, o).omap)

    def omap_get_values(self, c, o, keys):
        with self._lock:
            omap = self._obj(c, o).omap
            return {key: omap[key] for key in keys if key in omap}

    def list_collections(self):
        with self._lock:
            return sorted(self._colls)

    def collection_exists(self, c):
        with self._lock:
            return c in self._colls

    def collection_list(self, c):
        with self._lock:
            if c not in self._colls:
                raise FileNotFoundError(f"collection {c}")
            return sorted(self._colls[c])
