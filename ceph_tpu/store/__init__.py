"""Local storage engines (reference src/os/): the ObjectStore
transaction seam and the in-RAM MemStore used by tests and the
mini-cluster OSD."""

from ceph_tpu.store.filestore import FileStore
from ceph_tpu.store.memstore import MemStore
from ceph_tpu.store.objectstore import (
    META_COLL,
    ObjectStore,
    Transaction,
    TxOp,
    coll_t,
    ghobject_t,
)

__all__ = [
    "FileStore",
    "META_COLL",
    "MemStore",
    "ObjectStore",
    "Transaction",
    "TxOp",
    "coll_t",
    "ghobject_t",
]
