"""FileStore: a durable ObjectStore (WAL + checkpoint).

The persistence rung between MemStore and a BlueStore-grade engine
(reference src/os/: BlueStore journals small writes through a RocksDB
WAL and checkpoints into its block allocation; the old FileStore
journaled whole transactions).  Same shape here, sized for the
mini-cluster:

- state lives in RAM (a MemStore) for reads and validation;
- every transaction is denc-encoded, crc32c-framed, appended to
  ``wal.log`` and flushed+fsynced BEFORE it is applied — a transaction
  is durable exactly when queue_transaction returns (the reference's
  writeahead contract);
- ``mount()`` replays the checkpoint then the WAL, ignoring a torn
  tail record (crash mid-append);
- when the WAL exceeds ``checkpoint_bytes`` the full state is written
  to ``checkpoint.new``, atomically renamed, and the WAL truncated.
"""

from __future__ import annotations

import os
import struct
import threading

from ceph_tpu.msg.denc import Decoder, Encoder, EncodingError
from ceph_tpu.native import crc32c
from ceph_tpu.store.memstore import MemStore
from ceph_tpu.store.objectstore import (
    ObjectStore,
    Transaction,
    TxOp,
    coll_t,
    ghobject_t,
)

_MAGIC = 0xC397


def _enc_coll(enc: Encoder, c: coll_t) -> None:
    enc.i64(c.pool)
    enc.u32(c.ps)
    enc.i32(c.shard)


def _dec_coll(dec: Decoder) -> coll_t:
    return coll_t(dec.i64(), dec.u32(), dec.i32())


def _enc_obj(enc: Encoder, o: ghobject_t) -> None:
    enc.str_(o.name)
    enc.i64(o.snap)
    enc.i64(o.gen)
    enc.i32(o.shard)


def _dec_obj(dec: Decoder) -> ghobject_t:
    return ghobject_t(dec.str_(), dec.i64(), dec.i64(), dec.i32())


def encode_txn(txn: Transaction) -> bytes:
    """ObjectStore::Transaction encode (reference Transaction.h
    ENCODE_START over the op list)."""
    enc = Encoder()
    with enc.versioned(1, 1):
        enc.u32(len(txn.ops))
        for op in txn.ops:
            kind = op[0]
            enc.str_(kind.value)
            if kind in (TxOp.MKCOLL, TxOp.RMCOLL):
                _enc_coll(enc, op[1])
            elif kind == TxOp.COLL_MOVE_RENAME:
                _enc_coll(enc, op[1])
                _enc_obj(enc, op[2])
                _enc_coll(enc, op[3])
                _enc_obj(enc, op[4])
            else:
                _enc_coll(enc, op[1])
                _enc_obj(enc, op[2])
                if kind == TxOp.WRITE:
                    enc.u64(op[3])
                    enc.bytes_(op[4])
                elif kind == TxOp.ZERO:
                    enc.u64(op[3])
                    enc.u64(op[4])
                elif kind == TxOp.TRUNCATE:
                    enc.u64(op[3])
                elif kind in (TxOp.SETATTRS, TxOp.OMAP_SETKEYS):
                    enc.u32(len(op[3]))
                    for k in sorted(op[3]):
                        enc.str_(k)
                        enc.bytes_(op[3][k])
                elif kind == TxOp.RMATTR:
                    enc.str_(op[3])
                elif kind == TxOp.OMAP_RMKEYS:
                    enc.u32(len(op[3]))
                    for k in op[3]:
                        enc.str_(k)
                elif kind == TxOp.CLONE:
                    _enc_obj(enc, op[3])
    return enc.bytes()


def decode_txn(raw: bytes) -> Transaction:
    dec = Decoder(raw)
    txn = Transaction()
    with dec.versioned():
        for _ in range(dec.u32()):
            kind = TxOp(dec.str_())
            if kind in (TxOp.MKCOLL, TxOp.RMCOLL):
                txn.ops.append((kind, _dec_coll(dec)))
                continue
            if kind == TxOp.COLL_MOVE_RENAME:
                txn.ops.append((
                    kind, _dec_coll(dec), _dec_obj(dec),
                    _dec_coll(dec), _dec_obj(dec),
                ))
                continue
            c = _dec_coll(dec)
            o = _dec_obj(dec)
            if kind == TxOp.WRITE:
                txn.ops.append((kind, c, o, dec.u64(), dec.bytes_()))
            elif kind == TxOp.ZERO:
                txn.ops.append((kind, c, o, dec.u64(), dec.u64()))
            elif kind == TxOp.TRUNCATE:
                txn.ops.append((kind, c, o, dec.u64()))
            elif kind in (TxOp.SETATTRS, TxOp.OMAP_SETKEYS):
                kv = {dec.str_(): dec.bytes_() for _ in range(dec.u32())}
                txn.ops.append((kind, c, o, kv))
            elif kind == TxOp.RMATTR:
                txn.ops.append((kind, c, o, dec.str_()))
            elif kind == TxOp.OMAP_RMKEYS:
                txn.ops.append((kind, c, o, [dec.str_() for _ in range(dec.u32())]))
            elif kind == TxOp.CLONE:
                txn.ops.append((kind, c, o, _dec_obj(dec)))
            else:
                txn.ops.append((kind, c, o))
    return txn


def _snapshot(mem: MemStore) -> bytes:
    """Full-state checkpoint: one big synthetic transaction."""
    txn = Transaction()
    for c in mem.list_collections():
        txn.create_collection(c)
        for o in mem.collection_list(c):
            data = mem.read(c, o)
            if data:
                txn.write(c, o, 0, data)
            else:
                txn.touch(c, o)
            attrs = mem.getattrs(c, o)
            if attrs:
                txn.setattrs(c, o, attrs)
            omap = mem.omap_get(c, o)
            if omap:
                txn.omap_setkeys(c, o, omap)
    return encode_txn(txn)


class FileStore(ObjectStore):
    def __init__(self, path: str, checkpoint_bytes: int = 64 * 1024 * 1024):
        self.path = path
        self.checkpoint_bytes = checkpoint_bytes
        self._mem = MemStore()
        self._wal = None
        self._wal_size = 0
        # commits may arrive from worker threads (asyncio.to_thread):
        # validate+journal+apply must be one atomic sequence
        self._commit_lock = threading.Lock()

    # -- mount/replay --------------------------------------------------

    def statfs(self) -> dict:
        """Host-filesystem truth (the FileStore reported its backing
        fs the same way)."""
        st = os.statvfs(self.path)
        total = st.f_frsize * st.f_blocks
        avail = st.f_frsize * st.f_bavail
        return {
            "total": total,
            "used": max(0, total - avail),
            "available": avail,
        }

    def mount(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        cp = os.path.join(self.path, "checkpoint")
        if os.path.exists(cp):
            with open(cp, "rb") as f:
                self._mem.queue_transaction(decode_txn(f.read()))
        walfn = os.path.join(self.path, "wal.log")
        if os.path.exists(walfn):
            with open(walfn, "rb") as f:
                raw = f.read()
            off = 0
            while off + 10 <= len(raw):
                magic, ln = struct.unpack_from("<HI", raw, off)
                if magic != _MAGIC or off + 10 + ln > len(raw):
                    break  # torn tail: crash mid-append
                (crc,) = struct.unpack_from("<I", raw, off + 6)
                body = raw[off + 10 : off + 10 + ln]
                if crc32c(body) != crc:
                    break
                try:
                    self._mem.queue_transaction(decode_txn(body))
                except (EncodingError, OSError, ValueError):
                    break
                off += 10 + ln
            self._wal_size = off
        self._wal = open(walfn, "ab")
        if self._wal.tell() != self._wal_size:
            # drop the torn tail so new records append cleanly
            self._wal.truncate(self._wal_size)

    def umount(self) -> None:
        if self._wal is not None:
            self._checkpoint()
            self._wal.close()
            self._wal = None

    # -- transactions --------------------------------------------------

    #: daemons sharing an event loop should offload queue_transaction
    #: (it fsyncs); OSDDaemon checks this and uses asyncio.to_thread
    blocking_commit = True

    def queue_transaction(self, txn: Transaction) -> None:
        """validate -> journal (flush+fsync) -> apply to RAM.

        Ordering is the durability contract: nothing mutates (and no
        on_applied/on_commit callback fires) until the record is on
        stable storage, and a failed journal write leaves RAM exactly
        as-is — a later checkpoint can never persist a transaction the
        caller saw fail."""
        assert self._wal is not None, "FileStore not mounted"
        with self._commit_lock:
            self._mem.validate(txn)
            body = encode_txn(txn)
            rec = struct.pack("<HI", _MAGIC, len(body)) + struct.pack(
                "<I", crc32c(body)
            ) + body
            self._wal.write(rec)
            self._wal.flush()
            os.fsync(self._wal.fileno())
            self._mem.queue_transaction(txn)
            self._wal_size += len(rec)
            if self._wal_size > self.checkpoint_bytes:
                self._checkpoint()

    def _checkpoint(self) -> None:
        cp = os.path.join(self.path, "checkpoint")
        tmp = cp + ".new"
        with open(tmp, "wb") as f:
            f.write(_snapshot(self._mem))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, cp)
        # the rename must be durable BEFORE the WAL shrinks, or a crash
        # could surface the OLD checkpoint beside an empty WAL — losing
        # acked transactions; fsync the directory to order them
        dirfd = os.open(self.path, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
        self._wal.truncate(0)
        self._wal.seek(0)
        os.fsync(self._wal.fileno())
        self._wal_size = 0

    # -- reads: delegate to the RAM state ------------------------------

    def read(self, c, o, off=0, length=None):
        return self._mem.read(c, o, off, length)

    def stat(self, c, o):
        return self._mem.stat(c, o)

    def exists(self, c, o):
        return self._mem.exists(c, o)

    def getattr(self, c, o, name):
        return self._mem.getattr(c, o, name)

    def getattrs(self, c, o):
        return self._mem.getattrs(c, o)

    def omap_get(self, c, o):
        return self._mem.omap_get(c, o)

    def omap_get_values(self, c, o, keys):
        return self._mem.omap_get_values(c, o, keys)

    def list_collections(self):
        return self._mem.list_collections()

    def collection_exists(self, c):
        return self._mem.collection_exists(c)

    def collection_list(self, c):
        return self._mem.collection_list(c)
