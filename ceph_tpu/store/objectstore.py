"""ObjectStore: collections, objects, atomic transactions.

Behavioral twin of the reference's local-storage seam
(src/os/ObjectStore.h; Transaction ops src/os/Transaction.h): the OSD
writes per-PG-shard collections of named objects through all-or-nothing
transactions that mix data writes, xattrs, omap and object lifecycle
ops, and gets completion callbacks when a transaction commits.

The op set is the subset the EC/replicated write paths and recovery
actually generate (reference ECTransaction.cc:37-95 writes per-shard
chunks + hinfo xattrs; PGLog persists via omap), plus clone for
snap/recovery temp objects.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable


@dataclass(frozen=True, order=True)
class coll_t:
    """Collection id: one per PG shard (reference coll_t(spg_t),
    src/osd/osd_types.h; EC writes address coll_t(spg_t(pgid, shard)),
    ECTransaction.cc:80-88).  ``shard=-1`` is NO_SHARD (replicated)."""

    pool: int
    ps: int
    shard: int = -1

    def __str__(self) -> str:
        s = "" if self.shard < 0 else f"s{self.shard}"
        return f"{self.pool}.{self.ps:x}{s}"


META_COLL = coll_t(-1, 0)


@dataclass(frozen=True, order=True)
class ghobject_t:
    """Object id within a collection (reference ghobject_t: hobject +
    generation + shard; src/common/hobject.h)."""

    name: str
    snap: int = -2          # CEPH_NOSNAP analogue
    gen: int = -1           # NO_GEN
    shard: int = -1         # shard_id_t::NO_SHARD

    def __str__(self) -> str:
        return f"{self.name}:{self.snap}:{self.gen}:{self.shard}"


class TxOp(enum.Enum):
    TOUCH = "touch"
    WRITE = "write"
    ZERO = "zero"
    TRUNCATE = "truncate"
    REMOVE = "remove"
    SETATTRS = "setattrs"
    RMATTR = "rmattr"
    OMAP_SETKEYS = "omap_setkeys"
    OMAP_RMKEYS = "omap_rmkeys"
    OMAP_CLEAR = "omap_clear"
    CLONE = "clone"
    MKCOLL = "mkcoll"
    RMCOLL = "rmcoll"
    COLL_MOVE_RENAME = "coll_move_rename"


@dataclass
class Transaction:
    """Ordered op list applied atomically (ObjectStore::Transaction).

    Callbacks mirror the reference's contexts: ``on_applied`` fires when
    the transaction is readable, ``on_commit`` when durable (in MemStore
    both fire at apply, as the reference MemStore does)."""

    ops: list[tuple] = field(default_factory=list)
    on_applied: list[Callable[[], None]] = field(default_factory=list)
    on_commit: list[Callable[[], None]] = field(default_factory=list)

    def touch(self, c: coll_t, o: ghobject_t) -> "Transaction":
        self.ops.append((TxOp.TOUCH, c, o))
        return self

    def write(self, c: coll_t, o: ghobject_t, off: int, data: bytes) -> "Transaction":
        self.ops.append((TxOp.WRITE, c, o, off, bytes(data)))
        return self

    def zero(self, c: coll_t, o: ghobject_t, off: int, length: int) -> "Transaction":
        self.ops.append((TxOp.ZERO, c, o, off, length))
        return self

    def truncate(self, c: coll_t, o: ghobject_t, size: int) -> "Transaction":
        self.ops.append((TxOp.TRUNCATE, c, o, size))
        return self

    def remove(self, c: coll_t, o: ghobject_t) -> "Transaction":
        self.ops.append((TxOp.REMOVE, c, o))
        return self

    def setattrs(self, c: coll_t, o: ghobject_t, attrs: dict[str, bytes]) -> "Transaction":
        self.ops.append((TxOp.SETATTRS, c, o, dict(attrs)))
        return self

    def rmattr(self, c: coll_t, o: ghobject_t, name: str) -> "Transaction":
        self.ops.append((TxOp.RMATTR, c, o, name))
        return self

    def omap_setkeys(self, c: coll_t, o: ghobject_t, kv: dict[str, bytes]) -> "Transaction":
        self.ops.append((TxOp.OMAP_SETKEYS, c, o, dict(kv)))
        return self

    def omap_rmkeys(self, c: coll_t, o: ghobject_t, keys: Iterable[str]) -> "Transaction":
        self.ops.append((TxOp.OMAP_RMKEYS, c, o, list(keys)))
        return self

    def omap_clear(self, c: coll_t, o: ghobject_t) -> "Transaction":
        self.ops.append((TxOp.OMAP_CLEAR, c, o))
        return self

    def clone(self, c: coll_t, src: ghobject_t, dst: ghobject_t) -> "Transaction":
        self.ops.append((TxOp.CLONE, c, src, dst))
        return self

    def create_collection(self, c: coll_t) -> "Transaction":
        self.ops.append((TxOp.MKCOLL, c))
        return self

    def remove_collection(self, c: coll_t) -> "Transaction":
        self.ops.append((TxOp.RMCOLL, c))
        return self

    def collection_move_rename(
        self, src_c: coll_t, src_o: ghobject_t, dst_c: coll_t, dst_o: ghobject_t
    ) -> "Transaction":
        self.ops.append((TxOp.COLL_MOVE_RENAME, src_c, src_o, dst_c, dst_o))
        return self

    def register_on_applied(self, cb: Callable[[], None]) -> None:
        self.on_applied.append(cb)

    def register_on_commit(self, cb: Callable[[], None]) -> None:
        self.on_commit.append(cb)

    def append(self, other: "Transaction") -> None:
        self.ops.extend(other.ops)
        self.on_applied.extend(other.on_applied)
        self.on_commit.extend(other.on_commit)

    def empty(self) -> bool:
        return not self.ops


class ObjectStore:
    """Abstract store (reference src/os/ObjectStore.h:793 surface, the
    slice the OSD uses)."""

    #: fault-injection scope for this store's FAULTS points
    #: (``store.<op>.<fault_domain>``); the owning OSD daemon sets it
    #: to ``osd.<id>`` so tests and the chaos engine can fail ONE disk
    fault_domain: str = ""

    def mount(self) -> None: ...
    def umount(self) -> None: ...

    def statfs(self) -> dict:
        """{"total": bytes, "used": bytes, "available": bytes} — the
        ObjectStore::statfs surface the fullness plane consumes
        (reference src/os/ObjectStore.h; consumed by
        OSD.cc:773 recalc_full_state and `ceph osd df`).  Stores
        report; admission control enforces."""
        raise NotImplementedError

    def queue_transaction(self, txn: Transaction) -> None:
        raise NotImplementedError

    # reads (never go through transactions)
    def read(self, c: coll_t, o: ghobject_t, off: int = 0, length: int | None = None) -> bytes:
        raise NotImplementedError

    def stat(self, c: coll_t, o: ghobject_t) -> int:
        """Returns object size; raises FileNotFoundError if the
        collection or object is missing (all read methods do)."""
        raise NotImplementedError

    def exists(self, c: coll_t, o: ghobject_t) -> bool:
        raise NotImplementedError

    def getattr(self, c: coll_t, o: ghobject_t, name: str) -> bytes:
        raise NotImplementedError

    def getattrs(self, c: coll_t, o: ghobject_t) -> dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, c: coll_t, o: ghobject_t) -> dict[str, bytes]:
        raise NotImplementedError

    def omap_get_values(self, c: coll_t, o: ghobject_t, keys: Iterable[str]) -> dict[str, bytes]:
        raise NotImplementedError

    def list_collections(self) -> list[coll_t]:
        raise NotImplementedError

    def collection_exists(self, c: coll_t) -> bool:
        raise NotImplementedError

    def collection_list(self, c: coll_t) -> list[ghobject_t]:
        raise NotImplementedError
