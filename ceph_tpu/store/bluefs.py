"""BlueFS-lite: the KV database living INSIDE the block device.

The reference BlueStore's defining trait is owning one raw device with
BlueFS hosting RocksDB's WAL + SSTs on allocator-managed extents of
that same device (src/os/bluestore/BlueFS.cc, ~4,800 LoC; the
bluestore_bdev superblock machinery).  This module is that contract at
our FileDB's fidelity:

- **superblock**: the device's first two MIN_ALLOC units hold
  alternating-generation JSON slots (crc-framed).  The live slot names
  the checkpoint extent chain and the WAL extent chain — everything
  needed to find the KV before any KV exists.
- **WAL**: crc+sequence-framed batch records appended into an
  allocator-owned extent chain; the chain grows by allocating another
  extent from the SHARED allocator and committing a new superblock
  generation first, so replay always knows the full chain.  Replay
  stops at the first bad frame OR sequence mismatch — stale frames
  from a reused extent can never replay (sequences are globally
  monotonic, never reused).
- **checkpoint**: the whole keyspace serialized to freshly-allocated
  extents; commit order is write-new -> flip superblock -> free-old,
  so a crash at any point leaves one complete, reachable state.

Space accounting is inherently shared: KV extents come from the same
allocator as data blobs, so BlockStore.statfs covers both (the
fullness plane sees metadata growth).  Durability uses pwrite+fsync
barriers on the shared fd (an O_DIRECT raw device would slot in at
the same seam).

Threading: all mutation entry points (mount/umount single-threaded;
submit via BlockStore.queue_transaction) run under BlockStore's
_txn_lock, which also serializes every allocator access — BlueFS
therefore touches the allocator without further locking.
"""

from __future__ import annotations

import json
import os
import struct

from ceph_tpu.common.fault_injector import store_fault_check
from ceph_tpu.kv import MemDB, WriteBatch
from ceph_tpu.native import crc32c

MIN_ALLOC = 65536
_MAGIC = 0xB1FE
_REC_HDR = struct.Struct("<HIIQ")  # magic, len, crc, seq
SUPER_UNITS = (0, 1)  # device units reserved for the two superblocks


class BlueFSLite(MemDB):
    """KeyValueDB co-located on the BlockStore's device."""

    blocking_commit = True

    def __init__(self, checkpoint_bytes: int = 16 * 2**20):
        super().__init__()
        self.checkpoint_bytes = checkpoint_bytes
        self._fd: int | None = None
        self._alloc = None          # set by activate()
        self.gen = 0
        self.cp_extents: list[list[int]] = []   # [[unit, units], ...]
        self.cp_len = 0
        self.wal_extents: list[list[int]] = []
        self.wal_seq = 1            # seq of the wal chain's FIRST record
        self._next_seq = 1
        self._wal_pos = 0           # append offset within the chain

    # -- wiring (called by BlockStore) ---------------------------------

    def attach(self, fd: int) -> None:
        self._fd = fd

    def activate(self, alloc) -> None:
        """Allocator is rebuilt and our extents are marked used: from
        here on the WAL may grow and checkpoints may run."""
        self._alloc = alloc
        if not self.wal_extents:
            self._grow_wal(1)

    def used_units(self) -> set[int]:
        """Every device unit this KV owns (superblocks + chains) — the
        BlockStore folds these into the allocator's used set."""
        out = set(SUPER_UNITS)
        for unit, units in self.cp_extents + self.wal_extents:
            out.update(range(unit, unit + units))
        return out

    # -- superblock ----------------------------------------------------

    def _write_super(self) -> None:
        self.gen += 1
        blob = json.dumps({
            "gen": self.gen, "cp_extents": self.cp_extents,
            "cp_len": self.cp_len, "wal_extents": self.wal_extents,
            "wal_seq": self.wal_seq,
        }).encode()
        rec = struct.pack("<II", crc32c(blob), len(blob)) + blob
        assert len(rec) <= MIN_ALLOC, "superblock overflow"
        slot = SUPER_UNITS[self.gen % 2]
        os.pwrite(self._fd, rec.ljust(MIN_ALLOC, b"\0"), slot * MIN_ALLOC)
        os.fsync(self._fd)

    def _read_super(self) -> dict | None:
        best = None
        for slot in SUPER_UNITS:
            raw = os.pread(self._fd, MIN_ALLOC, slot * MIN_ALLOC)
            if len(raw) < 8:
                continue
            crc, ln = struct.unpack_from("<II", raw)
            body = raw[8:8 + ln]
            if len(body) != ln or crc32c(body) != crc:
                continue
            try:
                sb = json.loads(body)
            except ValueError:
                continue
            if best is None or sb["gen"] > best["gen"]:
                best = sb
        return best

    # -- extent-chain IO -----------------------------------------------

    @staticmethod
    def _chain_len(extents: list[list[int]]) -> int:
        return sum(n for _u, n in extents) * MIN_ALLOC

    def _chain_write(self, extents, pos: int, data: bytes) -> None:
        off = 0
        for unit, units in extents:
            span = units * MIN_ALLOC
            lo = max(pos, off)
            hi = min(pos + len(data), off + span)
            if lo < hi:
                os.pwrite(self._fd, data[lo - pos:hi - pos],
                          unit * MIN_ALLOC + (lo - off))
            off += span
        if pos + len(data) > off:
            raise IOError("write past extent chain")

    def _chain_read(self, extents, pos: int, length: int) -> bytes:
        parts = []
        off = 0
        want_end = pos + length
        for unit, units in extents:
            span = units * MIN_ALLOC
            lo = max(pos, off)
            hi = min(want_end, off + span)
            if lo < hi:
                got = os.pread(
                    self._fd, hi - lo, unit * MIN_ALLOC + (lo - off))
                # the backing file grows on demand: space past its
                # physical end is unwritten device, i.e. zeros
                parts.append(got.ljust(hi - lo, b"\0"))
            off += span
        return b"".join(parts)

    # -- lifecycle -----------------------------------------------------

    def mount(self) -> None:
        """Load the live superblock generation, the checkpoint, and
        replay the WAL chain (the BlueFS mount + rocksdb recovery)."""
        store_fault_check("mount", "bluefs")
        assert self._fd is not None, "attach() first"
        sb = self._read_super()
        if sb is None:
            return  # fresh device: empty kv; activate() seeds the WAL
        self.gen = sb["gen"]
        self.cp_extents = [list(e) for e in sb["cp_extents"]]
        self.cp_len = sb["cp_len"]
        self.wal_extents = [list(e) for e in sb["wal_extents"]]
        self.wal_seq = sb["wal_seq"]
        if self.cp_len:
            self._load_checkpoint(
                self._chain_read(self.cp_extents, 0, self.cp_len))
        # WAL replay
        pos = 0
        seq = self.wal_seq
        total = self._chain_len(self.wal_extents)
        while pos + _REC_HDR.size <= total:
            hdr = self._chain_read(self.wal_extents, pos, _REC_HDR.size)
            magic, ln, crc, rseq = _REC_HDR.unpack(hdr)
            if magic != _MAGIC or rseq != seq or \
                    pos + _REC_HDR.size + ln > total:
                break
            body = self._chain_read(
                self.wal_extents, pos + _REC_HDR.size, ln)
            if crc32c(body) != crc:
                break
            self._apply(WriteBatch.decode(body))
            pos += _REC_HDR.size + ln
            seq += 1
        self._wal_pos = pos
        self._next_seq = seq

    def umount(self) -> None:
        if self._fd is None:
            return
        if self._alloc is not None:
            self._checkpoint()
        self._fd = None
        self._alloc = None

    # -- fsck ----------------------------------------------------------

    def fsck(self) -> list[dict]:
        """Verify BlueFS metadata at rest: BOTH superblock generation
        slots and every applied WAL frame's crc.

        Mount TOLERATES a corrupt stale superblock (it falls back to
        the other generation) and a torn WAL tail (replay stops) —
        correct for availability, but silent rot in the fallback slot
        means the NEXT crash has no good generation to land on.  fsck
        therefore REPORTS what mount tolerates (the BlueStore
        fsck-vs-mount split)."""
        out: list[dict] = []
        if self._fd is None:
            return out
        for slot in SUPER_UNITS:
            raw = os.pread(self._fd, MIN_ALLOC, slot * MIN_ALLOC)
            if not raw.rstrip(b"\0"):
                continue  # never-written slot (young device), not rot
            ok = len(raw) >= 8
            if ok:
                crc, ln = struct.unpack_from("<II", raw)
                body = raw[8:8 + ln]
                ok = len(body) == ln and crc32c(body) == crc
                if ok:
                    try:
                        json.loads(body)
                    except ValueError:
                        ok = False
            if not ok:
                out.append({"kind": "bluefs-superblock", "slot": slot})
        # WAL frames: every record up to the applied position must
        # still frame and crc — rot under an already-applied record
        # would silently truncate replay after the next crash
        pos = 0
        seq = self.wal_seq
        total = self._chain_len(self.wal_extents)
        while pos < self._wal_pos and pos + _REC_HDR.size <= total:
            hdr = self._chain_read(self.wal_extents, pos, _REC_HDR.size)
            magic, ln, crc, rseq = _REC_HDR.unpack(hdr)
            body_ok = (
                magic == _MAGIC and rseq == seq
                and pos + _REC_HDR.size + ln <= total
            )
            if body_ok:
                body = self._chain_read(
                    self.wal_extents, pos + _REC_HDR.size, ln)
                body_ok = crc32c(body) == crc
            if not body_ok:
                out.append({
                    "kind": "bluefs-wal-frame", "pos": pos, "seq": seq,
                })
                break  # framing is lost from here on
            pos += _REC_HDR.size + ln
            seq += 1
        return out

    # -- writes --------------------------------------------------------

    def submit(self, batch: WriteBatch, sync: bool = True) -> None:
        store_fault_check("commit", "bluefs")
        body = batch.encode()
        rec = _REC_HDR.pack(_MAGIC, len(body), crc32c(body),
                            self._next_seq) + body
        if self._wal_pos + len(rec) > self._chain_len(self.wal_extents):
            self._grow_wal(-(-len(rec) // MIN_ALLOC))
        self._chain_write(self.wal_extents, self._wal_pos, rec)
        if sync:
            os.fsync(self._fd)
        self._wal_pos += len(rec)
        self._next_seq += 1
        with self._lock:
            self._apply(batch)
        if self._wal_pos >= self.checkpoint_bytes:
            self._checkpoint()

    def _grow_wal(self, units: int) -> None:
        """Extend the WAL chain: allocate, then commit the new chain
        via a superblock flip BEFORE any record lands in it."""
        unit = self._alloc.alloc(max(units, 1))
        self.wal_extents.append([unit, max(units, 1)])
        self._write_super()

    def _checkpoint(self) -> None:
        """Compact: serialize the keyspace to fresh extents, flip the
        superblock, then free the old chains (write-new -> commit ->
        drop-old; a crash anywhere leaves one complete state)."""
        out = [struct.pack("<I", len(self._cf))]
        for p in sorted(self._cf):
            cf = self._cf[p]
            penc = p.encode()
            out.append(struct.pack("<I", len(penc)) + penc)
            out.append(struct.pack("<I", len(cf)))
            for k in sorted(cf):
                kenc = k.encode()
                out.append(struct.pack("<I", len(kenc)) + kenc)
                out.append(struct.pack("<I", len(cf[k])) + cf[k])
        blob = b"".join(out)
        blob = struct.pack("<I", crc32c(blob)) + blob
        old_cp = self.cp_extents
        old_wal = self.wal_extents
        cp_units = max(1, -(-len(blob) // MIN_ALLOC))
        new_cp = [[self._alloc.alloc(cp_units), cp_units]]
        self._chain_write(new_cp, 0, blob)
        new_wal = [[self._alloc.alloc(1), 1]]
        os.fsync(self._fd)
        self.cp_extents = new_cp
        self.cp_len = len(blob)
        self.wal_extents = new_wal
        self.wal_seq = self._next_seq
        self._wal_pos = 0
        self._write_super()
        for unit, units in old_cp + old_wal:
            self._alloc.free(unit, units)

    def _load_checkpoint(self, raw: bytes) -> None:
        (crc,) = struct.unpack_from("<I", raw)
        blob = raw[4:]
        if crc32c(blob) != crc:
            return  # torn checkpoint: WAL replay has everything
        off = 0

        def take():
            nonlocal off
            (ln,) = struct.unpack_from("<I", blob, off)
            off += 4
            v = blob[off:off + ln]
            off += ln
            return v

        (ncf,) = struct.unpack_from("<I", blob, off)
        off += 4
        for _ in range(ncf):
            p = take().decode()
            (nk,) = struct.unpack_from("<I", blob, off)
            off += 4
            cf = self._cf.setdefault(p, {})
            for _ in range(nk):
                k = take().decode()
                cf[k] = bytes(take())
