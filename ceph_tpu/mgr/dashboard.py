"""Dashboard: a read-only web UI + REST API over the monitor's state
(the src/pybind/mgr/dashboard role, radically simplified: no mutation
endpoints — observe-only, the part operators actually keep open).

Access control (the reference dashboard's auth/session layer, lite):
when the monitor runs with auth enabled, every request must carry
``Authorization: Bearer <hex-key>`` where the key belongs to an entity
in the cluster keyring whose caps grant mon read (``capable(caps,
"mon", "r")``) — a token minted by ``ceph auth get-or-create`` works
directly.  Unauthenticated or unauthorized requests get 401.  With
auth off (cephx=none analogue) everything is open, matching the rest
of the command plane.

Endpoints:

  GET /                 HTML overview (auto-refreshing): health, mons,
                        osd up/in counts, pool table, PG state totals
  GET /api/health       the mon's health checks (HEALTH_OK/WARN/ERR)
  GET /api/status       the `ceph status` blob
  GET /api/pools        pool table incl. pg_num/size/type/autoscale
  GET /api/osds         per-osd up/in/weight + crush host
  GET /api/pg           aggregated PG states (by_state)
  GET /api/traces       cross-daemon trace summaries + assembled
                        trees from the active mgr's TraceCollector
                        (rides the MMonMgrReport digest)
  GET /api/logs         the replicated cluster log's newest entries
                        (+ the follow cursor `ceph -w` uses)
  GET /api/progress     mgr progress-module events (recovery/
                        rebalance fractions + ETAs, via the digest)
  GET /metrics          prometheus text (same as the exporter)

Runs inside the monitor process and reads its in-memory state via the
same `_command` plane the CLI uses — no extra wire hops.
"""

from __future__ import annotations

import asyncio
import hmac
import html
import json

from ceph_tpu.common.caps import capable
from ceph_tpu.common.metrics import prometheus_text

_PAGE = """<!doctype html>
<html><head><title>ceph_tpu dashboard</title>
<meta http-equiv="refresh" content="5">
<style>
 body {{ font-family: monospace; margin: 2em; background: #101418;
        color: #d8dee9; }}
 h1 {{ font-size: 1.2em; }} h2 {{ font-size: 1em; margin-top: 1.5em; }}
 table {{ border-collapse: collapse; }}
 td, th {{ border: 1px solid #3b4252; padding: 2px 10px;
           text-align: left; }}
 .ok {{ color: #a3be8c; }} .warn {{ color: #ebcb8b; }}
 .err {{ color: #bf616a; }}
</style></head><body>
<h1>ceph_tpu &mdash; cluster dashboard</h1>
<p>health: <span class="{hcls}">{hstatus}</span> {hdetail}</p>
<h2>cluster</h2>
<table>
<tr><th>mons</th><td>{mons}</td></tr>
<tr><th>mgr</th><td>{mgr}</td></tr>
<tr><th>osds</th><td>{osds_up} up / {osds_in} in / {osds_total} total</td></tr>
<tr><th>map epoch</th><td>{epoch}</td></tr>
<tr><th>pg states</th><td>{pgs}</td></tr>
<tr><th>objects</th><td>{objects}</td></tr>
<tr><th>slowest osds</th><td>{top_slow}</td></tr>
</table>
<h2>pools</h2>
<table><tr><th>id</th><th>name</th><th>type</th><th>pg_num</th>
<th>size</th><th>autoscale</th></tr>{pool_rows}</table>
<p><a href="/api/status">status</a> &middot;
<a href="/api/health">health</a> &middot;
<a href="/api/pools">pools</a> &middot;
<a href="/api/osds">osds</a> &middot;
<a href="/api/pg">pg</a> &middot;
<a href="/api/traces">traces</a> &middot;
<a href="/api/logs">logs</a> &middot;
<a href="/api/progress">progress</a> &middot;
<a href="/metrics">metrics</a></p>
</body></html>
"""


class Dashboard:
    def __init__(self, mon):
        self.mon = mon
        self._server: asyncio.base_events.Server | None = None
        self.addr: tuple[str, int] | None = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._handle, host, port)
        self.addr = self._server.sockets[0].getsockname()[:2]
        return self.addr

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- state collection --------------------------------------------------

    async def _api(self, path: str) -> tuple[bytes, bytes]:
        """(body, content_type) for one endpoint."""
        if path == "/metrics":
            # a live mgr's digest carries the CLUSTER-aggregated
            # exposition (every daemon's series, rendered by the
            # prometheus module); fall back to this process's local
            # collections when no mgr is active
            digest = getattr(self.mon, "_mgr_digest", None) or {}
            mgr_map = getattr(self.mon, "_mgr_map", None) or {}
            if mgr_map.get("active") and digest.get("prometheus"):
                return (digest["prometheus"].encode(),
                        b"text/plain; version=0.0.4")
            return prometheus_text().encode(), b"text/plain; version=0.0.4"
        if path == "/api/health":
            return json.dumps(self.mon._health_checks()).encode(), \
                b"application/json"
        if path == "/api/status":
            _c, _rs, data = await self.mon._command({"prefix": "status"})
            return data, b"application/json"
        if path == "/api/pg":
            _c, _rs, data = await self.mon._command({"prefix": "pg stat"})
            return data, b"application/json"
        if path == "/api/traces":
            digest = getattr(self.mon, "_mgr_digest", None) or {}
            return (json.dumps(digest.get("traces", {})).encode(),
                    b"application/json")
        if path == "/api/logs":
            return (json.dumps(self.mon._log_last(50)).encode(),
                    b"application/json")
        if path == "/api/progress":
            digest = getattr(self.mon, "_mgr_digest", None) or {}
            return (json.dumps(digest.get("progress", {})).encode(),
                    b"application/json")
        if path == "/api/pools":
            om = self.mon.osdmap
            rows = []
            for pid, pool in sorted(om.pools.items()):
                rows.append({
                    "id": pid,
                    "name": om.pool_names.get(pid, str(pid)),
                    "type": "erasure" if pool.is_erasure() else
                            "replicated",
                    "pg_num": pool.pg_num,
                    "size": pool.size,
                    "pg_autoscale_mode": pool.extra.get(
                        "pg_autoscale_mode", "off"),
                })
            return json.dumps(rows).encode(), b"application/json"
        if path == "/api/osds":
            om = self.mon.osdmap
            host_of = {}
            for name, bid in om.crush.bucket_names.items():
                b = om.crush.buckets.get(bid)
                if b is None:
                    continue
                for it in b.items:
                    if it >= 0:
                        host_of[it] = name
            rows = [{
                "osd": o,
                "up": om.is_up(o),
                "in": not om.is_out(o),
                "weight": (om.osd_weight[o] if o < len(om.osd_weight)
                           else 0) / 0x10000,
                "host": host_of.get(o, ""),
            } for o in range(om.max_osd) if om.exists(o)]
            return json.dumps(rows).encode(), b"application/json"
        if path == "/":
            return (await self._page()).encode(), b"text/html"
        raise KeyError(path)

    async def _page(self) -> str:
        h = self.mon._health_checks()
        _c, _rs, data = await self.mon._command({"prefix": "status"})
        st = json.loads(data) if data else {}
        om = self.mon.osdmap
        pools_body, _ = await self._api("/api/pools")
        pool_rows = "".join(
            "<tr><td>{id}</td><td>{name}</td><td>{type}</td>"
            "<td>{pg_num}</td><td>{size}</td>"
            "<td>{pg_autoscale_mode}</td></tr>".format(
                **{k: html.escape(str(v)) for k, v in p.items()})
            for p in json.loads(pools_body)
        )
        pgs = st.get("pgs", {})
        status = h.get("status", "HEALTH_OK")
        cls = {"HEALTH_OK": "ok", "HEALTH_WARN": "warn"}.get(status, "err")
        detail = html.escape("; ".join(
            f"{k}: {v.get('summary', '')}"
            for k, v in h.get("checks", {}).items()))
        mgr_map = getattr(self.mon, "_mgr_map", None) or {}
        act = mgr_map.get("active")
        standbys = [sb["name"] for sb in mgr_map.get("standbys", [])]
        mgr_line = "no daemons" if not act else (
            f"{act['name']}(active)"
            + (f", standbys: {', '.join(standbys)}" if standbys else ""))
        digest = getattr(self.mon, "_mgr_digest", None) or {}
        top = digest.get("top_slow_osds") or []
        top_slow = ", ".join(
            f"{name} ({lat_us:g} &micro;s)" for name, lat_us in top
        ) or "&mdash;"
        return _PAGE.format(
            hcls=cls, hstatus=status, hdetail=detail,
            mgr=html.escape(mgr_line),
            top_slow=top_slow,
            mons=st.get("monmap", {}).get("num_mons",
                                          getattr(self.mon, "n_mons", 1)),
            osds_up=sum(1 for o in range(om.max_osd)
                        if om.exists(o) and om.is_up(o)),
            osds_in=sum(1 for o in range(om.max_osd)
                        if om.exists(o) and not om.is_out(o)),
            osds_total=sum(1 for o in range(om.max_osd) if om.exists(o)),
            epoch=om.epoch,
            pgs=json.dumps(pgs.get("by_state", {})),
            objects=pgs.get("num_objects", 0),
            pool_rows=pool_rows or "<tr><td colspan=6>none</td></tr>",
        )

    # -- http --------------------------------------------------------------

    def _authorized(self, token: str | None) -> bool:
        auth = self.mon.messenger.auth
        if auth is None:
            return True  # auth off: open, like the command plane
        if not token:
            return False
        try:
            key = bytes.fromhex(token)
        except ValueError:
            return False
        for entity, ekey in auth.keyring.items():
            if hmac.compare_digest(key, ekey):
                return capable(auth.caps_of(entity), "mon", "r")
        return False

    async def _handle(self, reader, writer) -> None:
        try:
            req = await asyncio.wait_for(reader.readline(), 5)
            token = None
            while True:  # drain headers, capturing Authorization
                line = await asyncio.wait_for(reader.readline(), 5)
                if line in (b"\r\n", b"\n", b""):
                    break
                if line.lower().startswith(b"authorization:"):
                    val = line.split(b":", 1)[1].strip()
                    if val.lower().startswith(b"bearer "):
                        token = val[7:].strip().decode("ascii", "replace")
            path = req.split(b" ")[1].decode() if b" " in req else "/"
            path = path.split("?", 1)[0]  # tolerate query strings
            if not self._authorized(token):
                body = b"unauthorized\n"
                ctype = b"text/plain"
                writer.write(
                    b"HTTP/1.1 401 Unauthorized\r\n"
                    b'WWW-Authenticate: Bearer realm="ceph_tpu"\r\n'
                    b"Content-Type: " + ctype + b"\r\n"
                    b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                    b"Connection: close\r\n\r\n" + body
                )
                await writer.drain()
                return
            try:
                body, ctype = await self._api(path)
                status = b"200 OK"
            except KeyError:
                body, ctype = b"not found\n", b"text/plain"
                status = b"404 Not Found"
            except Exception as e:  # state mid-transition: report, not die
                body = f"error: {e}\n".encode()
                ctype, status = b"text/plain", b"500 Internal Server Error"
            writer.write(
                b"HTTP/1.1 " + status + b"\r\n"
                b"Content-Type: " + ctype + b"\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"Connection: close\r\n\r\n" + body
            )
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, IndexError):
            pass
        finally:
            writer.close()
