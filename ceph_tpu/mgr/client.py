"""MgrClient: the report stream every daemon embeds.

Behavioral twin of the reference MgrClient (src/mgr/MgrClient.cc):
each daemon (OSD, mon, MDS, RGW frontend) owns one; it watches the
MgrMap the mon publishes, keeps a session open to the ACTIVE mgr
(MMgrOpen once per active-gid, re-opened automatically after a
failover), and ships an MMgrReport every ``mgr_report_interval``
seconds carrying:

- perf-counter **deltas** since the previous report (computed here by
  diffing cumulative ``perf dump`` snapshots, the reference's packed
  PerfCounterInstance deltas);
- instantaneous gauges, including per-interval latency means derived
  from the op tracker's cumulative log2 histograms (diffed exactly —
  integer sums/counts);
- the cumulative fixed-bucket latency histograms themselves;
- a json status side-channel (pg summary, read-error ledger, health
  bits) supplied by the owner's ``collect`` callback.

The mgr is NEVER in the data path: every send is fire-and-forget, any
connection error just waits for the next tick (or the next MgrMap).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time

from ceph_tpu.msg.messages import MMgrOpen, MMgrReport

log = logging.getLogger("ceph_tpu.mgr")


class MgrClient:
    """``entity`` is this daemon's report name ("osd.0", "mon.1", ...);
    ``messenger`` the daemon's own messenger (the mgr session rides it);
    ``collect()`` returns the report raw material::

        {
          "counters":   {key: cumulative float},   # deltas derived here
          "gauges":     {key: float},              # shipped as-is
          "histograms": {cls: LatencyHistogram},   # cumulative, diffed
          "status":     {...},                     # json side channel
        }

    Every key is optional.  Latency gauges ``<cls>_lat_us`` (interval
    mean per histogram class) are derived automatically.
    """

    def __init__(self, entity: str, messenger, conf, collect,
                 tracers=()):
        self.entity = entity
        self.messenger = messenger
        self.conf = conf
        self.collect = collect
        # tracers whose export buffers this client drains into each
        # report (the daemon's own + shared rings like the device-
        # launch profiler); drained spans ride MMgrReport.spans to the
        # mgr's TraceCollector
        self.tracers = tuple(tracers)
        # set by MMgrConfigure from the active mgr: outlier detection
        # flagged this daemon slow — its scrub scheduler defers
        # background scrubs while the flag holds
        self.scrub_deprioritized = False
        self.mgrmap: dict | None = None
        self._conn = None
        self._opened_gid: int | None = None
        self._task: asyncio.Task | None = None
        self._last_counters: dict[str, float] = {}
        self._last_hist: dict[str, tuple[int, int]] = {}  # cls -> (sum, n)
        self.reports_sent = 0
        self.opens_sent = 0
        self.last_report_at: float = 0.0
        self._stopping = False

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._report_loop())

    async def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            self._task = None

    # -- MgrMap intake -------------------------------------------------

    def handle_mgr_map(self, msg) -> None:
        """MMgrMap from the mon: note the active mgr; if it changed
        (failover or restart), drop the session so the report loop
        re-opens against the new active — the stream RESUMES without
        operator action."""
        try:
            m = json.loads(msg.blob or b"{}")
        except ValueError:
            return
        old = self.mgrmap
        self.mgrmap = m
        new_gid = (m.get("active") or {}).get("gid")
        old_gid = ((old or {}).get("active") or {}).get("gid")
        if new_gid != old_gid:
            self._conn = None  # lazily re-dialed by the next tick

    def handle_configure(self, msg) -> None:
        """MMgrConfigure from the active mgr: report-period tuning +
        the slow-OSD scrub-deprioritization flag (the analytics
        feedback loop)."""
        self.scrub_deprioritized = bool(
            getattr(msg, "scrub_deprioritize", False))

    def _active_addr(self) -> tuple[int, tuple[str, int]] | None:
        act = (self.mgrmap or {}).get("active")
        if not act or not act.get("addr"):
            return None
        return act["gid"], (act["addr"][0], int(act["addr"][1]))

    # -- the report loop -----------------------------------------------

    async def _report_loop(self) -> None:
        interval = self.conf["mgr_report_interval"]
        while not self._stopping:
            await asyncio.sleep(interval)
            try:
                await self._report_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # the mgr is observability, not the data path: never
                # let a report failure ripple into the daemon
                log.debug("%s: mgr report failed", self.entity,
                          exc_info=True)
                self._conn = None

    async def _report_once(self) -> None:
        target = self._active_addr()
        if target is None:
            return
        gid, addr = target
        if self._conn is None or self._conn._closed \
                or self._opened_gid != gid:
            self._conn = await self.messenger.connect_to(
                ("mgr", gid), *addr)
            await self._conn.send_message(MMgrOpen(
                daemon=self.entity,
                metadata=json.dumps({"entity": self.entity}).encode(),
            ))
            self._opened_gid = gid
            self.opens_sent += 1
        await self._conn.send_message(self._build_report())
        self.reports_sent += 1
        self.last_report_at = time.monotonic()

    def _build_report(self) -> MMgrReport:
        raw = self.collect() or {}
        cum = dict(raw.get("counters") or {})
        deltas = {
            k: v - self._last_counters.get(k, 0.0)
            for k, v in cum.items()
            if v != self._last_counters.get(k, 0.0)
        }
        self._last_counters = cum
        gauges = dict(raw.get("gauges") or {})
        hists = raw.get("histograms") or {}
        wire_h: dict[str, list[int]] = {}
        for cls, h in hists.items():
            wire_h[cls] = list(h.counts)
            psum, pn = self._last_hist.get(cls, (0, 0))
            dsum, dn = h.sum_us - psum, h.total - pn
            self._last_hist[cls] = (h.sum_us, h.total)
            if dn > 0:
                # per-interval mean latency: the scalar sample the
                # mgr's ring buffers ingest for this class
                gauges[f"{cls}_lat_us"] = dsum / dn
        status = raw.get("status")
        spans: list[dict] = []
        for t in self.tracers:
            if len(spans) >= 512:
                break
            spans.extend(t.drain_export(limit=512 - len(spans)))
        return MMgrReport(
            daemon=self.entity,
            counters=deltas,
            gauges=gauges,
            histograms=wire_h,
            status=json.dumps(status).encode() if status else b"",
            spans=json.dumps(spans).encode() if spans else b"",
        )
