"""Mgr module framework + the three initial modules.

The src/pybind/mgr role: the active mgr hosts pluggable modules whose
enable/disable set lives in the MgrMap (mon-replicated, so it survives
failover — ``ceph mgr module ls/enable/disable``).  Modules run ONLY
on the active mgr; a promoted standby reconciles its running set
against the map within one module tick.

- :class:`PrometheusModule` — cluster-aggregated exposition over HTTP:
  every reporting daemon's counters/gauges/histograms plus the
  analytics engine's cluster percentiles, replacing per-process-only
  scraping (reference src/pybind/mgr/prometheus);
- :class:`DeviceHealthModule` — consumes the OSDs' read-error-ledger
  and self-markdown telemetry into per-device health states + life
  expectancy buckets and health warnings (reference
  src/pybind/mgr/devicehealth);
- :class:`BalancerModule` — periodic automated upmap rounds through
  the mon's ``osd balance`` verb (wrapping osd/balancer.py's
  UpmapBalancer); **off by default** like any rebalancer that moves
  data without being asked;
- :class:`ProgressModule` — turns the OSDs' PG-state deltas (report
  side channel + the analytics engine's device-computed EWMA columns)
  into recovery/rebalance progress events with completion fraction and
  ETA (``ceph progress``; reference src/pybind/mgr/progress);
- :class:`CrashModule` — collects the crash dumps daemons persist on
  unhandled exit / induced death (``ceph crash ls/info/archive``) and
  raises the RECENT_CRASH health warning (reference
  src/pybind/mgr/crash).
"""

from __future__ import annotations

import asyncio
import logging
import time

log = logging.getLogger("ceph_tpu.mgr")

#: name -> module class (the available-modules registry)
MODULE_REGISTRY: dict[str, type] = {}

#: modules enabled in a fresh MgrMap (balancer is opt-in)
DEFAULT_MODULES = ("crash", "devicehealth", "progress", "prometheus")


def register(cls):
    MODULE_REGISTRY[cls.NAME] = cls
    return cls


class MgrModule:
    """Base module: subclass, set NAME, override start/stop/tick/
    health as needed.  ``tick`` runs every mgr_module_tick_interval
    while the module is enabled on the active mgr."""

    NAME = ""

    def __init__(self, mgr):
        self.mgr = mgr
        self.running = False

    async def start(self) -> None:
        self.running = True

    async def stop(self) -> None:
        self.running = False

    async def tick(self) -> None:
        pass

    def health(self) -> dict:
        """Health checks this module contributes to the mgr digest
        ({CHECK_NAME: {"severity", "summary", "detail"}})."""
        return {}


@register
class PrometheusModule(MgrModule):
    """Cluster-aggregated /metrics endpoint on the active mgr."""

    NAME = "prometheus"

    def __init__(self, mgr):
        super().__init__(mgr)
        self._server = None
        self.addr: tuple[str, int] | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, "127.0.0.1", 0)
        self.addr = self._server.sockets[0].getsockname()[:2]
        await super().start()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self.addr = None
        await super().stop()

    def text(self) -> str:
        """The cluster exposition: per-daemon series under
        ``ceph_tpu_<daemon>_*`` (typed), per-daemon histograms with
        proper ``le`` buckets, and the analytics summary under
        ``ceph_tpu_cluster_*``."""
        from ceph_tpu.common.metrics import _sanitize, histogram_text

        out: list[str] = []
        for daemon, sess in sorted(self.mgr.sessions.items()):
            base = f"ceph_tpu_{_sanitize(daemon)}"
            for key, val in sorted(sess.get("counters", {}).items()):
                metric = f"{base}_{_sanitize(key)}"
                out.append(f"# TYPE {metric} counter")
                out.append(f"{metric} {val}")
            for key, val in sorted(sess.get("gauges", {}).items()):
                metric = f"{base}_{_sanitize(key)}"
                out.append(f"# TYPE {metric} gauge")
                out.append(f"{metric} {val}")
            for cls, h in sorted(sess.get("histograms", {}).items()):
                counts = list(h)
                # cumulative sum/count are not on the wire per bucket;
                # derive count, approximate sum from bucket mids is
                # dishonest — use the daemon's reported mean gauge
                total = int(sum(counts))
                mean = sess.get("gauges", {}).get(f"{cls}_lat_us", 0.0)
                out.extend(histogram_text(
                    f"{base}_{_sanitize(cls)}_latency", counts,
                    int(mean * total), total))
        for line in self.mgr.cluster_metric_lines():
            out.append(line)
        out.extend(self._event_plane_lines())
        return "\n".join(out) + "\n"

    def _event_plane_lines(self) -> list[str]:
        """Health-check states, progress completion fractions and
        crash counts as typed series — the event plane's scrape
        surface (each state a 0/1 gauge; the mgr only exports the
        checks IT derives: module health + SLOW_OPS; map-level checks
        like OSD_DOWN are the mon's)."""
        from ceph_tpu.common.metrics import _sanitize

        out: list[str] = []
        checks: dict[str, dict] = {}
        for mod in self.mgr.modules.values():
            if mod.running:
                checks.update(mod.health())
        checks.update(self.mgr._slow_ops_health())
        sev_val = {"HEALTH_WARN": 1, "HEALTH_ERR": 2}
        for code, chk in sorted(checks.items()):
            name = f"ceph_tpu_health_{_sanitize(code.lower())}"
            out.append(f"# TYPE {name} gauge")
            out.append(
                f"{name} {sev_val.get(chk.get('severity'), 1)}")
        out.append("# TYPE ceph_tpu_health_checks_active gauge")
        out.append(f"ceph_tpu_health_checks_active {len(checks)}")
        prog = self.mgr.modules.get("progress")
        if prog is not None and prog.running:
            out.append("# TYPE ceph_tpu_progress_events_active gauge")
            out.append(
                f"ceph_tpu_progress_events_active {len(prog.events)}")
            for ev in prog.public_events():
                name = ("ceph_tpu_progress_"
                        f"{_sanitize(ev['kind'])}_fraction")
                out.append(f"# TYPE {name} gauge")
                out.append(f"{name} {ev['fraction']}")
        crash = self.mgr.modules.get("crash")
        if crash is not None and crash.running:
            out.append("# TYPE ceph_tpu_crash_reports_total counter")
            out.append(
                f"ceph_tpu_crash_reports_total {len(crash.crashes)}")
            out.append("# TYPE ceph_tpu_crash_recent gauge")
            out.append(
                f"ceph_tpu_crash_recent {len(crash.recent())}")
        return out

    async def _handle(self, reader, writer) -> None:
        try:
            req = await asyncio.wait_for(reader.readline(), 5)
            while True:
                line = await asyncio.wait_for(reader.readline(), 5)
                if line in (b"\r\n", b"\n", b""):
                    break
            path = req.split(b" ")[1].decode() if b" " in req else "/"
            if path == "/metrics":
                body, status = self.text().encode(), b"200 OK"
            else:
                body, status = b"see /metrics\n", b"404 Not Found"
            writer.write(
                b"HTTP/1.1 " + status + b"\r\n"
                b"Content-Type: text/plain; version=0.0.4\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"Connection: close\r\n\r\n" + body
            )
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, IndexError):
            pass
        finally:
            writer.close()


@register
class DeviceHealthModule(MgrModule):
    """Per-device health from real error telemetry: each OSD's report
    status carries its read-error ledger size and self-markdown flag;
    the module folds them into device states + a health warning."""

    NAME = "devicehealth"

    def __init__(self, mgr):
        super().__init__(mgr)
        #: daemon -> {"errors", "state", "life_expectancy"}
        self.devices: dict[str, dict] = {}

    async def tick(self) -> None:
        warn_at = self.mgr.conf["mgr_devicehealth_warn_errors"]
        max_err = max(warn_at, 1)
        for daemon, sess in self.mgr.sessions.items():
            if not daemon.startswith("osd."):
                continue
            st = sess.get("status") or {}
            errors = int(st.get("read_errors", 0))
            escalated = bool(st.get("disk_escalated", False))
            if escalated:
                state, life = "failed", "expired"
            elif errors >= max_err * 2:
                state, life = "failing", "imminent"
            elif errors >= warn_at:
                state, life = "warning", "reduced"
            else:
                state, life = "good", "normal"
            self.devices[daemon] = {
                "errors": errors,
                "escalated": escalated,
                "state": state,
                "life_expectancy": life,
            }

    def health(self) -> dict:
        bad = {d: v for d, v in self.devices.items()
               if v["state"] != "good"}
        if not bad:
            return {}
        return {
            "DEVICE_HEALTH": {
                "severity": "HEALTH_WARN",
                "summary": f"{len(bad)} device(s) with degraded health",
                "detail": [
                    f"{d}: {v['state']} ({v['errors']} verified read "
                    f"errors, life expectancy {v['life_expectancy']})"
                    for d, v in sorted(bad.items())
                ],
            }
        }


@register
class BalancerModule(MgrModule):
    """Automated upmap rounds (off by default): every
    mgr_balancer_interval the module asks the mon to run one
    ``osd balance`` pass (UpmapBalancer under the hood)."""

    NAME = "balancer"
    DEFAULT_OFF = True

    def __init__(self, mgr):
        super().__init__(mgr)
        self._last_run = 0.0
        self.rounds = 0
        self.last_swaps = -1

    async def tick(self) -> None:
        interval = self.mgr.conf["mgr_balancer_interval"]
        now = time.monotonic()
        if now - self._last_run < interval:
            return
        self._last_run = now
        try:
            code, _rs, data = await self.mgr.mon_command({
                "prefix": "osd balance", "max_swaps": "16"})
        except (ConnectionError, OSError, asyncio.TimeoutError):
            return
        if code == 0:
            import json

            self.rounds += 1
            try:
                self.last_swaps = json.loads(data).get("swaps", -1)
            except ValueError:
                self.last_swaps = -1


@register
class ProgressModule(MgrModule):
    """Recovery/rebalance progress events with completion fraction and
    device-computed ETA (the src/pybind/mgr/progress role).

    Source material: every OSD's report carries ``pgs_degraded`` /
    ``pgs_misplaced`` gauges for the PGs it leads (the PG-state side
    channel).  When a cluster-wide count leaves zero the module opens
    an event; the completion fraction is monotone non-decreasing
    (``1 - current/peak``, pinned at its maximum so transient
    re-degradation never makes a progress bar walk backwards), reaches
    1.0 when the count returns to zero, and the event is reaped after
    ``mgr_progress_complete_grace`` into a bounded completed history.

    The ETA divides the current count by the decline rate of the
    analytics engine's EWMA column for the metric — the integer-exact
    EWMA computed in the mgr's ONE batched device launch per digest
    (mgr/analytics.py), which is what smooths report jitter out of the
    estimate."""

    NAME = "progress"

    #: event kind -> the per-OSD gauge (analytics column) it follows
    KINDS = (("recovery", "pgs_degraded", "degraded"),
             ("rebalance", "pgs_misplaced", "misplaced"))

    def __init__(self, mgr):
        super().__init__(mgr)
        self.events: dict[str, dict] = {}     # kind -> active event
        self.completed: list[dict] = []       # bounded history
        self._n = 0

    def _cluster_count(self, metric: str) -> int:
        # a session that stopped reporting (daemon killed, link cut)
        # keeps its LAST gauges forever; summing those would pin the
        # cluster count at its peak and the event could never complete
        # — count only sessions fresh within a few report periods
        stale_after = 4.0 * self.mgr.conf["mgr_report_interval"]
        now = time.monotonic()
        total = 0
        for daemon, sess in self.mgr.sessions.items():
            if not daemon.startswith("osd."):
                continue
            last = sess.get("last_report")
            if last is None or now - last > stale_after:
                continue
            total += int(sess.get("gauges", {}).get(metric, 0))
        return total

    def _ewma_count(self, metric: str) -> float | None:
        """Cluster-wide EWMA of the metric from the analytics digest
        (device-computed; None before the first analytics pass)."""
        row = self.mgr._analytics_summary().get(
            "series", {}).get(metric)
        if not row:
            return None
        return float(sum(v["ewma"] for v in row.values()))

    @staticmethod
    def _public(ev: dict) -> dict:
        return {k: v for k, v in ev.items() if not k.startswith("_")}

    def public_events(self) -> list[dict]:
        return [self._public(ev) for _k, ev in sorted(self.events.items())]

    def public_completed(self) -> list[dict]:
        return [dict(ev) for ev in self.completed]

    async def tick(self) -> None:
        now = time.monotonic()
        grace = self.mgr.conf["mgr_progress_complete_grace"]
        for kind, metric, noun in self.KINDS:
            cur = self._cluster_count(metric)
            ev = self.events.get(kind)
            if ev is None:
                if cur <= 0:
                    continue
                self._n += 1
                ev = self.events[kind] = {
                    "id": f"{kind}-{self._n}", "kind": kind,
                    "message": f"{kind}: {cur} pgs {noun}",
                    "started_at": time.time(), "fraction": 0.0,
                    "eta_s": None, "peak": cur,
                    "_t0": now, "_prev": None,
                }
                self.mgr.clog.cluster.info(
                    f"{kind} started: {cur} pgs {noun}")
            if ev.get("_done_at") is not None and cur > 0:
                # re-degraded after completion but before the reap:
                # close this event now so a FRESH one (with a fresh
                # monotone fraction) opens next tick
                self._reap(kind, ev, now)
                continue
            ev["peak"] = max(ev["peak"], cur)
            frac = 1.0 - (cur / ev["peak"]) if ev["peak"] else 1.0
            ev["fraction"] = max(ev["fraction"], round(frac, 4))
            ev["message"] = f"{kind}: {cur}/{ev['peak']} pgs {noun}"
            # ETA from the EWMA column's decline rate (falls back to
            # the raw count before the first analytics pass)
            val = self._ewma_count(metric)
            if val is None:
                val = float(cur)
            prev = ev.get("_prev")
            if prev is not None and now > prev[0]:
                rate = (prev[1] - val) / (now - prev[0])
                if rate > 1e-6 and cur > 0:
                    ev["eta_s"] = round(cur / rate, 1)
            ev["_prev"] = (now, val)
            if cur == 0:
                ev["fraction"] = 1.0
                ev["eta_s"] = 0.0
                ev["message"] = f"{kind}: complete"
                if ev.get("_done_at") is None:
                    ev["_done_at"] = now
                if now - ev["_done_at"] >= grace:
                    self._reap(kind, ev, now)
            else:
                ev.pop("_done_at", None)

    def _reap(self, kind: str, ev: dict, now: float) -> None:
        self.events.pop(kind, None)
        done = self._public(ev)
        done["duration_s"] = round(now - ev["_t0"], 2)
        self.completed.append(done)
        del self.completed[:-16]
        self.mgr.clog.cluster.info(
            f"{kind} complete ({done['duration_s']}s, "
            f"peak {ev['peak']} pgs)")


@register
class CrashModule(MgrModule):
    """Crash-dump collector (the src/pybind/mgr/crash role): scans the
    shared ``crash_dir`` each tick for the dumps daemons persist on
    unhandled exit / fault-injector-induced death (common/crash.py),
    serves ``ceph crash ls/info`` through the mgr digest, and raises
    RECENT_CRASH while any unarchived dump is younger than
    ``mgr_crash_recent_age`` (``ceph crash archive`` acknowledges)."""

    NAME = "crash"

    def __init__(self, mgr):
        super().__init__(mgr)
        self.crashes: dict[str, dict] = {}
        self.scans = 0

    async def tick(self) -> None:
        d = self.mgr.conf["crash_dir"]
        if not d:
            return
        from ceph_tpu.common.crash import scan_crashes

        metas = await asyncio.to_thread(scan_crashes, d)
        self.crashes = {m["crash_id"]: m for m in metas}
        self.scans += 1

    def recent(self) -> list[dict]:
        age = self.mgr.conf["mgr_crash_recent_age"]
        now = time.time()
        return [
            m for m in self.crashes.values()
            if not m.get("archived")
            and now - float(m.get("timestamp", 0.0)) < age
        ]

    def health(self) -> dict:
        rec = self.recent()
        if not rec:
            return {}
        ents = sorted({m.get("entity", "?") for m in rec})
        return {
            "RECENT_CRASH": {
                "severity": "HEALTH_WARN",
                "summary": f"{len(rec)} recent crash(es): "
                           + ", ".join(ents),
                "detail": [
                    f"{m['crash_id']}: {m.get('reason', '')}"
                    for m in sorted(
                        rec, key=lambda m: m.get("timestamp", 0.0))
                ],
            }
        }

    def summary(self) -> dict:
        """The digest block `ceph crash ls/info` serves from."""
        metas = sorted(self.crashes.values(),
                       key=lambda m: m.get("timestamp", 0.0))
        return {"crashes": metas[-32:], "recent": len(self.recent()),
                "total": len(metas)}
