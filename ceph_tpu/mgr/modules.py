"""Mgr module framework + the three initial modules.

The src/pybind/mgr role: the active mgr hosts pluggable modules whose
enable/disable set lives in the MgrMap (mon-replicated, so it survives
failover — ``ceph mgr module ls/enable/disable``).  Modules run ONLY
on the active mgr; a promoted standby reconciles its running set
against the map within one module tick.

- :class:`PrometheusModule` — cluster-aggregated exposition over HTTP:
  every reporting daemon's counters/gauges/histograms plus the
  analytics engine's cluster percentiles, replacing per-process-only
  scraping (reference src/pybind/mgr/prometheus);
- :class:`DeviceHealthModule` — consumes the OSDs' read-error-ledger
  and self-markdown telemetry into per-device health states + life
  expectancy buckets and health warnings (reference
  src/pybind/mgr/devicehealth);
- :class:`BalancerModule` — periodic automated upmap rounds through
  the mon's ``osd balance`` verb (wrapping osd/balancer.py's
  UpmapBalancer); **off by default** like any rebalancer that moves
  data without being asked.
"""

from __future__ import annotations

import asyncio
import logging
import time

log = logging.getLogger("ceph_tpu.mgr")

#: name -> module class (the available-modules registry)
MODULE_REGISTRY: dict[str, type] = {}

#: modules enabled in a fresh MgrMap (balancer is opt-in)
DEFAULT_MODULES = ("devicehealth", "prometheus")


def register(cls):
    MODULE_REGISTRY[cls.NAME] = cls
    return cls


class MgrModule:
    """Base module: subclass, set NAME, override start/stop/tick/
    health as needed.  ``tick`` runs every mgr_module_tick_interval
    while the module is enabled on the active mgr."""

    NAME = ""

    def __init__(self, mgr):
        self.mgr = mgr
        self.running = False

    async def start(self) -> None:
        self.running = True

    async def stop(self) -> None:
        self.running = False

    async def tick(self) -> None:
        pass

    def health(self) -> dict:
        """Health checks this module contributes to the mgr digest
        ({CHECK_NAME: {"severity", "summary", "detail"}})."""
        return {}


@register
class PrometheusModule(MgrModule):
    """Cluster-aggregated /metrics endpoint on the active mgr."""

    NAME = "prometheus"

    def __init__(self, mgr):
        super().__init__(mgr)
        self._server = None
        self.addr: tuple[str, int] | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, "127.0.0.1", 0)
        self.addr = self._server.sockets[0].getsockname()[:2]
        await super().start()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self.addr = None
        await super().stop()

    def text(self) -> str:
        """The cluster exposition: per-daemon series under
        ``ceph_tpu_<daemon>_*`` (typed), per-daemon histograms with
        proper ``le`` buckets, and the analytics summary under
        ``ceph_tpu_cluster_*``."""
        from ceph_tpu.common.metrics import _sanitize, histogram_text

        out: list[str] = []
        for daemon, sess in sorted(self.mgr.sessions.items()):
            base = f"ceph_tpu_{_sanitize(daemon)}"
            for key, val in sorted(sess.get("counters", {}).items()):
                metric = f"{base}_{_sanitize(key)}"
                out.append(f"# TYPE {metric} counter")
                out.append(f"{metric} {val}")
            for key, val in sorted(sess.get("gauges", {}).items()):
                metric = f"{base}_{_sanitize(key)}"
                out.append(f"# TYPE {metric} gauge")
                out.append(f"{metric} {val}")
            for cls, h in sorted(sess.get("histograms", {}).items()):
                counts = list(h)
                # cumulative sum/count are not on the wire per bucket;
                # derive count, approximate sum from bucket mids is
                # dishonest — use the daemon's reported mean gauge
                total = int(sum(counts))
                mean = sess.get("gauges", {}).get(f"{cls}_lat_us", 0.0)
                out.extend(histogram_text(
                    f"{base}_{_sanitize(cls)}_latency", counts,
                    int(mean * total), total))
        for line in self.mgr.cluster_metric_lines():
            out.append(line)
        return "\n".join(out) + "\n"

    async def _handle(self, reader, writer) -> None:
        try:
            req = await asyncio.wait_for(reader.readline(), 5)
            while True:
                line = await asyncio.wait_for(reader.readline(), 5)
                if line in (b"\r\n", b"\n", b""):
                    break
            path = req.split(b" ")[1].decode() if b" " in req else "/"
            if path == "/metrics":
                body, status = self.text().encode(), b"200 OK"
            else:
                body, status = b"see /metrics\n", b"404 Not Found"
            writer.write(
                b"HTTP/1.1 " + status + b"\r\n"
                b"Content-Type: text/plain; version=0.0.4\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"Connection: close\r\n\r\n" + body
            )
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, IndexError):
            pass
        finally:
            writer.close()


@register
class DeviceHealthModule(MgrModule):
    """Per-device health from real error telemetry: each OSD's report
    status carries its read-error ledger size and self-markdown flag;
    the module folds them into device states + a health warning."""

    NAME = "devicehealth"

    def __init__(self, mgr):
        super().__init__(mgr)
        #: daemon -> {"errors", "state", "life_expectancy"}
        self.devices: dict[str, dict] = {}

    async def tick(self) -> None:
        warn_at = self.mgr.conf["mgr_devicehealth_warn_errors"]
        max_err = max(warn_at, 1)
        for daemon, sess in self.mgr.sessions.items():
            if not daemon.startswith("osd."):
                continue
            st = sess.get("status") or {}
            errors = int(st.get("read_errors", 0))
            escalated = bool(st.get("disk_escalated", False))
            if escalated:
                state, life = "failed", "expired"
            elif errors >= max_err * 2:
                state, life = "failing", "imminent"
            elif errors >= warn_at:
                state, life = "warning", "reduced"
            else:
                state, life = "good", "normal"
            self.devices[daemon] = {
                "errors": errors,
                "escalated": escalated,
                "state": state,
                "life_expectancy": life,
            }

    def health(self) -> dict:
        bad = {d: v for d, v in self.devices.items()
               if v["state"] != "good"}
        if not bad:
            return {}
        return {
            "DEVICE_HEALTH": {
                "severity": "HEALTH_WARN",
                "summary": f"{len(bad)} device(s) with degraded health",
                "detail": [
                    f"{d}: {v['state']} ({v['errors']} verified read "
                    f"errors, life expectancy {v['life_expectancy']})"
                    for d, v in sorted(bad.items())
                ],
            }
        }


@register
class BalancerModule(MgrModule):
    """Automated upmap rounds (off by default): every
    mgr_balancer_interval the module asks the mon to run one
    ``osd balance`` pass (UpmapBalancer under the hood)."""

    NAME = "balancer"
    DEFAULT_OFF = True

    def __init__(self, mgr):
        super().__init__(mgr)
        self._last_run = 0.0
        self.rounds = 0
        self.last_swaps = -1

    async def tick(self) -> None:
        interval = self.mgr.conf["mgr_balancer_interval"]
        now = time.monotonic()
        if now - self._last_run < interval:
            return
        self._last_run = now
        try:
            code, _rs, data = await self.mgr.mon_command({
                "prefix": "osd balance", "max_swaps": "16"})
        except (ConnectionError, OSError, asyncio.TimeoutError):
            return
        if code == 0:
            import json

            self.rounds += 1
            try:
                self.last_swaps = json.loads(data).get("swaps", -1)
            except ValueError:
                self.last_swaps = -1
