"""MgrDaemon: the manager process (ceph-mgr twin).

A real daemon with its own messenger: it beacons into the mon
(MMgrBeacon), the mon's MgrMonitor decides active vs standby and
publishes the MgrMap (MMgrMap) to every subscriber; the ACTIVE mgr
runs the DaemonServer plane — every daemon's MgrClient opens a session
(MMgrOpen -> MMgrConfigure) and streams MMgrReport telemetry, which
lands in a fixed-shape ``(daemons x metrics x window)`` ring-buffer
time-series store.  Each digest tick the analytics engine
(mgr/analytics.py) reduces the WHOLE store in one batched launch —
cluster percentiles, EWMA trends, outlier OSDs — and the result goes
back to the mon as an MMonMgrReport digest (`ceph osd perf`, the
dashboard's mgr views, health checks).

Standby failover: standbys beacon too; when the active's beacons stop
the mon promotes the first standby, the new MgrMap reaches every
daemon, and each MgrClient re-opens its session against the new
active — report streams resume without operator action.  The mgr is
never in the data path, so its death costs observability only.

Modules (mgr/modules.py) run on the active mgr; the enabled set lives
in the MgrMap so it survives failover.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import time

import numpy as np

from ceph_tpu.msg.messages import (
    MLogAck,
    MMgrBeacon,
    MMgrConfigure,
    MMgrMap,
    MMgrOpen,
    MMgrReport,
    MMonCommand,
    MMonCommandAck,
    MMonMgrReport,
    MMonSubscribe,
)
from ceph_tpu.msg.messenger import Connection, Message, Messenger

log = logging.getLogger("ceph_tpu.mgr")

#: ring samples are clamped here so batched int64 reductions can never
#: overflow (sum over D*W clamped samples stays far below 2**63)
SAMPLE_CLAMP = 1 << 40


class TimeSeriesStore:
    """Fixed-shape per-(daemon, metric) ring buffers.

    The WHOLE store is three dense arrays — ``values`` (D, M, W)
    int64, ``valid`` (D, M, W) bool, ``cursor`` (D,) — so the
    analytics engine reduces it in one batched launch with a shape
    known at mgr start (the prewarm contract).  Daemon slots are
    LRU-evicted when full; metric slots are first-come with overflow
    counted and dropped (never a silent resize — a resize would mint
    an in-path XLA compile)."""

    def __init__(self, max_daemons: int, max_metrics: int, window: int):
        self.shape = (max_daemons, max_metrics, window)
        self.values = np.zeros(self.shape, np.int64)
        self.valid = np.zeros(self.shape, bool)
        self.cursor = np.zeros(max_daemons, np.int64)
        self.daemons: dict[str, int] = {}
        self.metric_names: dict[str, int] = {}
        self.last_seen: dict[str, float] = {}
        self.dropped_metrics: dict[str, int] = {}
        self.evictions = 0

    def _daemon_slot(self, daemon: str) -> int:
        slot = self.daemons.get(daemon)
        if slot is not None:
            return slot
        D = self.shape[0]
        if len(self.daemons) < D:
            used = set(self.daemons.values())
            slot = next(i for i in range(D) if i not in used)
        else:
            victim = min(self.last_seen, key=self.last_seen.get)
            slot = self.daemons.pop(victim)
            self.last_seen.pop(victim, None)
            self.evictions += 1
        self.daemons[daemon] = slot
        self.values[slot] = 0
        self.valid[slot] = False
        self.cursor[slot] = 0
        return slot

    def _metric_slot(self, name: str) -> int | None:
        slot = self.metric_names.get(name)
        if slot is not None:
            return slot
        if len(self.metric_names) >= self.shape[1]:
            self.dropped_metrics[name] = self.dropped_metrics.get(
                name, 0) + 1
            return None
        slot = len(self.metric_names)
        self.metric_names[name] = slot
        return slot

    def ingest(self, daemon: str, samples: dict[str, float],
               now: float) -> None:
        """One report: every sample lands in the SAME window column
        (one column per report), then the cursor advances — samples
        absent from this report leave an invalid cell, so means and
        percentiles never see stale values."""
        d = self._daemon_slot(daemon)
        c = int(self.cursor[d])
        self.values[d, :, c] = 0
        self.valid[d, :, c] = False
        for name, v in samples.items():
            m = self._metric_slot(name)
            if m is None:
                continue
            q = int(np.rint(v))
            self.values[d, m, c] = min(max(q, 0), SAMPLE_CLAMP)
            self.valid[d, m, c] = True
        self.cursor[d] = (c + 1) % self.shape[2]
        self.last_seen[daemon] = now

    def series(self, daemon: str, metric: str) -> list[int]:
        """Time-ordered valid samples of one (daemon, metric) — the
        dashboard/test view; analytics never walks this path."""
        d = self.daemons.get(daemon)
        m = self.metric_names.get(metric)
        if d is None or m is None:
            return []
        W = self.shape[2]
        c = int(self.cursor[d])
        out = []
        for t in range(W):
            i = (c + t) % W
            if self.valid[d, m, i]:
                out.append(int(self.values[d, m, i]))
        return out

    def reserve(self, names) -> None:
        """Pre-assign metric slots (in order) so the declared
        analytics columns (analysis/prewarm_registry.py
        ANALYTICS_COLUMNS) get deterministic positions and can never
        be overflow-dropped by transient metrics racing for slots."""
        for name in names:
            self._metric_slot(name)

    def snapshot(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        return (self.values.copy(), self.valid.copy(),
                self.cursor.copy())


class MgrDaemon:
    """One manager daemon (active or standby is the mon's call)."""

    def __init__(self, name: str, mon_addr, conf=None):
        from ceph_tpu.common import ConfigProxy, get_perf_counters
        from ceph_tpu.common.tracing import Tracer
        from ceph_tpu.mgr.analytics import AnalyticsEngine
        from ceph_tpu.mgr.modules import MODULE_REGISTRY
        from ceph_tpu.mgr.tracer import TraceCollector

        self.name = name
        self.mon_addrs: list[tuple[str, int]] = (
            list(mon_addr) if isinstance(mon_addr, list) else [mon_addr]
        )
        self.conf = conf if conf is not None else ConfigProxy()
        # fresh per start: the mon tells a restart from a replay
        self.gid = time.time_ns()
        self.messenger = Messenger(("mgr", self.gid), self._dispatch)
        self.perf = get_perf_counters(f"mgr.{name}")
        self.tracer = Tracer(
            f"mgr.{name}",
            ring_max=self.conf["trace_ring_max"],
            sample_rate=self.conf["trace_sample_rate"],
            tail_slow_s=(self.conf["trace_tail_slow_s"] or None),
        )
        self.messenger.tracer = self.tracer
        # the jaeger-collector role: spans shipped on MMgrReport land
        # here; `ceph trace ls/show` serves from its assemblies
        self.trace_collector = TraceCollector(
            max_traces=self.conf["mgr_trace_max_traces"],
            slow_history=self.conf["mgr_trace_slow_history"],
            slow_s=self.conf["trace_tail_slow_s"] or 1.0,
        )
        # SLOW_OPS bookkeeping: daemon -> {"count", "grew_at",
        # "inflight"} from each report's status side channel
        self._slow_ops: dict[str, dict] = {}
        # last scrub-deprioritize verdict pushed per daemon (the
        # outlier -> MMgrConfigure feedback loop)
        self._deprioritized: dict[str, bool] = {}
        self.store = TimeSeriesStore(
            self.conf["mgr_stats_max_daemons"],
            self.conf["mgr_stats_max_metrics"],
            self.conf["mgr_stats_window"],
        )
        # declared analytics columns claim their slots up front (the
        # event plane's degraded/misplaced EWMA columns included)
        from ceph_tpu.analysis.prewarm_registry import ANALYTICS_COLUMNS

        self.store.reserve(ANALYTICS_COLUMNS)
        # cluster-log channel: SLOW_OPS raise/clear, scrub-
        # deprioritize verdicts and progress milestones all land in
        # the mon's replicated log through it
        from ceph_tpu.common.logclient import LogClient

        self.clog = LogClient(
            f"mgr.{name}", self.conf, send=self._send_mon)
        self.engine = AnalyticsEngine(
            *self.store.shape,
            backend=self.conf["mgr_analytics_backend"],
        )
        #: daemon name -> {"conn", "counters", "gauges", "histograms",
        #: "status", "reports", "last_report", "opened_at"}
        self.sessions: dict[str, dict] = {}
        self.mgrmap: dict = {}
        self.active = False
        self.modules = {
            name_: cls(self) for name_, cls in MODULE_REGISTRY.items()
        }
        self.last_analytics: dict | None = None
        self.digests_sent = 0
        self.addr: tuple[str, int] | None = None
        self._mon_conn: Connection | None = None
        self._tids = itertools.count(1)
        self._cmd_waiters: dict[int, asyncio.Future] = {}
        self._beacon_task: asyncio.Task | None = None
        self._digest_task: asyncio.Task | None = None
        self._module_task: asyncio.Task | None = None
        self._warm_task = None
        self._admin = None
        self.stopping = False

    # -- lifecycle -----------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0
                    ) -> tuple[str, int]:
        self.addr = await self.messenger.bind(host, port)
        sock_path = self.conf["admin_socket"]
        if sock_path:
            from ceph_tpu.common import AdminSocket

            self._admin = AdminSocket(
                sock_path.replace("$id", f"mgr.{self.name}"))
            self._register_admin_commands(self._admin)
            await self._admin.start()
        # prewarm the analytics shape NOW (off the loop): the digest
        # path must never compile — cold_launches stays 0 for the
        # daemon's whole life (the decode/scrub batcher discipline)
        def _warm_then_guard() -> None:
            self.engine.prewarm()
            # steady state starts here: arm the transfer guard so any
            # implicit host<->device transfer on a later digest pass
            # is counted (host_transfers) + answered from the numpy
            # fallback — the runtime twin of ctlint's transfer rules
            mode = self.conf["osd_transfer_guard"]
            if mode != "off":
                from ceph_tpu.common.transfer_guard import configure

                configure(mode, self.conf["osd_transfer_guard_window"])

        self._warm_task = asyncio.ensure_future(
            asyncio.to_thread(_warm_then_guard))
        await self._mon_hunt()
        self.clog.start()
        self._beacon_task = asyncio.ensure_future(self._beacon_loop())
        self._digest_task = asyncio.ensure_future(self._digest_loop())
        self._module_task = asyncio.ensure_future(self._module_loop())
        return self.addr

    async def stop(self) -> None:
        self.stopping = True
        await self.clog.stop()
        for t in (self._beacon_task, self._digest_task,
                  self._module_task, self._warm_task):
            if t:
                t.cancel()
        for mod in self.modules.values():
            if mod.running:
                await mod.stop()
        if self._admin is not None:
            await self._admin.stop()
        await self.messenger.shutdown()

    async def _send_mon(self, msg: Message) -> None:
        if self._mon_conn is None:
            raise ConnectionError("no monitor session")
        await self._mon_conn.send_message(msg)

    def record_crash(self, reason: str = "",
                     exc: BaseException | None = None) -> str | None:
        """Persist a crash dump for this mgr (unhandled death / chaos
        kill); the crash module on the surviving active collects it."""
        from ceph_tpu.common.crash import record_crash

        return record_crash(self.conf, f"mgr.{self.name}", exc=exc,
                            reason=reason, log_tail=self.clog.tail())

    def _register_admin_commands(self, sock) -> None:
        sock.register(
            "status", "mgr daemon status",
            lambda cmd: {
                "name": self.name, "gid": self.gid,
                "active": self.active,
                "sessions": sorted(self.sessions),
                "modules_running": sorted(
                    n for n, m in self.modules.items() if m.running),
            },
        )
        sock.register(
            "perf dump", "dump perf counters",
            lambda cmd: self.perf.dump(),
        )
        sock.register(
            "dump_traces", "recent spans of this mgr's tracer "
            "(blkin/otel role)",
            lambda cmd: self.tracer.dump(),
        )
        sock.register(
            "dump_trace_collector", "cross-daemon trace collector: "
            "summaries, slow-trace ids, ingest stats, recent "
            "device-launch profiling spans",
            lambda cmd: {
                "ls": self.trace_collector.ls(32),
                "device_recent":
                    self.trace_collector.device_launches(32),
                **self.trace_collector.dump(),
            },
        )
        sock.register(
            "trace show", "assemble one collected trace "
            "({'trace_id': N})",
            lambda cmd: (
                self.trace_collector.assemble(int(cmd["trace_id"]))
                or {"error": "unknown trace_id"}
            ),
        )
        sock.register(
            "dump_analytics", "analytics engine stats (launches, "
            "cold_launches, prewarmed shapes, fallbacks) + the last "
            "cluster summary",
            lambda cmd: {
                "stats": dict(self.engine.stats),
                "shape": list(self.engine.shape),
                "summary": self._analytics_summary(),
            },
        )

    async def _mon_hunt(self) -> None:
        last: Exception | None = None
        for mhost, mport in self.mon_addrs:
            try:
                conn = await self.messenger.connect(mhost, mport)
                # subscribe so MgrMap changes reach us like any daemon
                await conn.send_message(MMonSubscribe(start_epoch=0))
                self._mon_conn = conn
                return
            except (ConnectionError, OSError) as e:
                last = e
        raise ConnectionError(
            f"mgr.{self.name}: no monitor reachable: {last}")

    async def _beacon_loop(self) -> None:
        interval = self.conf["mgr_beacon_interval"]
        while not self.stopping:
            try:
                await self._mon_conn.send_message(MMgrBeacon(
                    name=self.name, gid=self.gid,
                    host=self.addr[0], port=self.addr[1],
                ))
            except (ConnectionError, OSError, AttributeError):
                try:
                    await self._mon_hunt()
                    continue
                except (ConnectionError, OSError):
                    pass
            await asyncio.sleep(interval)

    # -- dispatch ------------------------------------------------------

    async def _dispatch(self, msg: Message) -> None:
        try:
            if isinstance(msg, MMgrMap):
                await self._handle_mgr_map(msg)
            elif isinstance(msg, MMgrOpen):
                await self._handle_open(msg)
            elif isinstance(msg, MMgrReport):
                self._handle_report(msg)
            elif isinstance(msg, MLogAck):
                self.clog.handle_ack(msg)
            elif isinstance(msg, MMonCommandAck):
                fut = self._cmd_waiters.get(msg.tid)
                if fut and not fut.done():
                    fut.set_result(msg)
        except Exception:
            log.exception("mgr.%s: dispatch failed for %r",
                          self.name, msg)

    async def _handle_mgr_map(self, msg: MMgrMap) -> None:
        try:
            self.mgrmap = json.loads(msg.blob or b"{}")
        except ValueError:
            return
        act = self.mgrmap.get("active") or {}
        was = self.active
        self.active = act.get("gid") == self.gid
        if self.active and not was:
            log.info("mgr.%s: promoted to ACTIVE (map epoch %d)",
                     self.name, self.mgrmap.get("epoch", 0))
            self.perf.inc("promotions")
        elif was and not self.active:
            log.info("mgr.%s: demoted to standby", self.name)
            self.sessions.clear()
            for mod in self.modules.values():
                if mod.running:
                    await mod.stop()

    async def _handle_open(self, msg: MMgrOpen) -> None:
        sess = self.sessions.setdefault(msg.daemon, {
            "counters": {}, "gauges": {}, "histograms": {},
            "status": {}, "reports": 0,
        })
        sess["conn"] = msg.conn
        sess["opened_at"] = time.monotonic()
        self.perf.inc("session_opens")
        await msg.conn.send_message(MMgrConfigure(
            period=self.conf["mgr_report_interval"]))

    def _handle_report(self, msg: MMgrReport) -> None:
        sess = self.sessions.setdefault(msg.daemon, {
            "counters": {}, "gauges": {}, "histograms": {},
            "status": {}, "reports": 0,
        })
        for k, d in msg.counters.items():
            sess["counters"][k] = sess["counters"].get(k, 0.0) + d
        sess["gauges"].update(msg.gauges)
        sess["histograms"].update(msg.histograms)
        if msg.status:
            try:
                sess["status"] = json.loads(msg.status)
            except ValueError:
                pass
        sess["reports"] += 1
        sess["last_report"] = time.monotonic()
        self.perf.inc("reports_rx")
        if msg.spans:
            try:
                spans = json.loads(msg.spans)
            except ValueError:
                spans = []
            if spans:
                self.trace_collector.ingest(msg.daemon, spans)
                self.perf.inc("trace_spans_rx", len(spans))
        # SLOW_OPS bookkeeping: remember when each daemon's complaint
        # counter last GREW — the health check clears once no daemon
        # grew within mgr_slow_ops_warn_window and nothing slow is
        # still in flight
        st = sess.get("status") or {}
        if "slow_ops" in st:
            rec = self._slow_ops.setdefault(
                msg.daemon, {"count": 0, "grew_at": 0.0, "inflight": 0})
            count = int(st.get("slow_ops", 0))
            if count > rec["count"]:
                rec["grew_at"] = time.monotonic()
            rec["count"] = count
            rec["inflight"] = int(st.get("slow_ops_inflight", 0))
        # numeric gauges are the ring-buffer samples (latency means,
        # queue depths, ...) — one column per report
        self.store.ingest(msg.daemon, msg.gauges, time.monotonic())

    # -- the analytics/digest plane ------------------------------------

    async def _digest_loop(self) -> None:
        interval = self.conf["mgr_digest_interval"]
        while not self.stopping:
            await asyncio.sleep(interval)
            if not self.active:
                continue
            try:
                await self._digest_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("mgr.%s: digest pass failed", self.name)

    async def _digest_once(self) -> None:
        if self._warm_task is not None and not self._warm_task.done():
            # NEVER analyze before prewarm lands: the first pass would
            # win the compile race and count as a cold launch — the
            # exact in-path compile the prewarm discipline forbids
            return
        values, valid, cursor = self.store.snapshot()
        # the batched pass runs off the event loop: even a warm XLA
        # launch must not stall report ingestion
        self.last_analytics = await asyncio.to_thread(
            self.engine.analyze, values, valid, cursor)
        await self._push_scrub_flags()
        digest = self._build_digest()
        # SLOW_OPS raise/clear lands in the cluster log at its signal
        # site (the mon's health tick only logs its own map-derived
        # checks, so these lines never double up)
        slow = digest["health"].get("SLOW_OPS")
        if (slow is not None) != getattr(self, "_slow_ops_flag", False):
            self._slow_ops_flag = slow is not None
            if slow is not None:
                self.clog.cluster.warn(
                    f"Health check failed: {slow['summary']} (SLOW_OPS)")
            else:
                self.clog.cluster.info("Health check cleared: SLOW_OPS")
        try:
            await self._mon_conn.send_message(MMonMgrReport(
                blob=json.dumps(digest).encode()))
            self.digests_sent += 1
            self.perf.inc("digests_tx")
        except (ConnectionError, OSError, AttributeError):
            pass  # beacon loop re-homes the mon session

    def _analytics_summary(self) -> dict:
        """The analytics result keyed back to daemon/metric NAMES."""
        a = self.last_analytics
        if a is None:
            return {}
        names = {i: n for n, i in self.store.metric_names.items()}
        daemons = {i: n for n, i in self.store.daemons.items()}
        from ceph_tpu.mgr.analytics import PCTS, SCALE_SHIFT

        pct = {}
        for m, name in names.items():
            if int(a["n_samples"][m]) == 0:
                continue
            pct[name] = {
                f"p{p}": int(a["percentiles"][m, i])
                for i, p in enumerate(PCTS)
            }
            pct[name]["n"] = int(a["n_samples"][m])
        outliers = {}
        means = {}
        for m, mname in names.items():
            row = {}
            for d, dname in daemons.items():
                if int(a["count"][d, m]) > 0:
                    row[dname] = {
                        "mean": int(a["mean_scaled"][d, m]) / (
                            1 << SCALE_SHIFT),
                        "ewma": int(a["ewma_scaled"][d, m]) / (
                            1 << SCALE_SHIFT),
                        "outlier": bool(a["outlier"][d, m]),
                    }
            if row:
                means[mname] = row
                out = sorted(d for d, v in row.items() if v["outlier"])
                if out:
                    outliers[mname] = out
        return {"percentiles": pct, "series": means,
                "outliers": outliers}

    def cluster_metric_lines(self) -> list[str]:
        """Cluster-level exposition lines for the prometheus module."""
        from ceph_tpu.common.metrics import _sanitize

        out = []
        summary = self._analytics_summary()
        for metric, row in sorted(summary.get("percentiles", {}).items()):
            for p, v in sorted(row.items()):
                if p == "n":
                    continue
                name = f"ceph_tpu_cluster_{_sanitize(metric)}_{p}"
                out.append(f"# TYPE {name} gauge")
                out.append(f"{name} {v}")
        return out

    def _outlier_daemons(self) -> set[str]:
        """OSD daemons the analytics pass flags as latency outliers on
        ANY metric (the slow-OSD detection feeding scrub scheduling)."""
        out: set[str] = set()
        for names in self._analytics_summary().get(
                "outliers", {}).values():
            out.update(n for n in names if n.startswith("osd."))
        return out

    async def _push_scrub_flags(self) -> None:
        """Close the analytics loop: tell outlier OSDs to deprioritize
        background scrubs (MMgrConfigure scrub_deprioritize), and
        un-flag recovered ones.  Sent only on verdict CHANGES."""
        outliers = self._outlier_daemons()
        for daemon, sess in list(self.sessions.items()):
            if not daemon.startswith("osd."):
                continue
            want = daemon in outliers
            if self._deprioritized.get(daemon) == want:
                continue
            conn = sess.get("conn")
            if conn is None:
                continue
            try:
                await conn.send_message(MMgrConfigure(
                    period=self.conf["mgr_report_interval"],
                    scrub_deprioritize=want))
                self._deprioritized[daemon] = want
                self.perf.inc("scrub_deprioritize_pushes")
                self.clog.cluster.info(
                    f"{daemon} scrub deprioritized (latency outlier)"
                    if want else
                    f"{daemon} scrub deprioritization lifted")
            except (ConnectionError, OSError):
                pass  # daemon gone; next session re-opens clean

    def _slow_ops_health(self) -> dict:
        """The SLOW_OPS health check (reference `ceph health` SLOW_OPS
        raised by the mgr's DaemonServer): raised while any daemon has
        slow ops IN FLIGHT or its complaint counter grew within
        mgr_slow_ops_warn_window; clears a full quiet window after the
        last slow op."""
        window = self.conf["mgr_slow_ops_warn_window"]
        now = time.monotonic()
        noisy: dict[str, dict] = {}
        for daemon, rec in self._slow_ops.items():
            if rec["inflight"] > 0 or (
                rec["grew_at"] and now - rec["grew_at"] < window
            ):
                noisy[daemon] = rec
        if not noisy:
            return {}
        total = sum(r["count"] for r in noisy.values())
        return {
            "SLOW_OPS": {
                "severity": "HEALTH_WARN",
                "summary": (
                    f"{total} slow ops, oldest daemons: "
                    + ", ".join(sorted(noisy))
                ),
                "detail": [
                    f"{d}: {r['count']} slow ops total, "
                    f"{r['inflight']} in flight over the complaint "
                    "threshold"
                    for d, r in sorted(noisy.items())
                ],
            }
        }

    def _digest_traces(self) -> dict:
        """The trace block of the digest: summaries for `ceph trace
        ls` + assembled trees (recent + slow) for `ceph trace show` —
        bounded so the digest stays small."""
        col = self.trace_collector
        ls = col.ls(16)
        trees: dict[str, dict] = {}
        want = [t["trace_id"] for t in ls[:6]]
        want += [int(t) for t in list(col.slow)[-6:]]
        for tid in want:
            if str(tid) in trees:
                continue
            a = col.assemble(tid)
            if a is not None:
                trees[str(tid)] = a
        return {"ls": ls, "trees": trees, "stats": col.dump()}

    def _top_slow_osds(self, metric: str = "write_lat_us",
                       n: int = 3) -> list[list]:
        summary = self._analytics_summary()
        row = summary.get("series", {}).get(metric, {})
        ranked = sorted(
            ((d, v["mean"]) for d, v in row.items()
             if d.startswith("osd.")),
            key=lambda kv: -kv[1])
        return [[d, round(v, 1)] for d, v in ranked[:n]]

    def _build_digest(self) -> dict:
        summary = self._analytics_summary()
        osd_perf = {}
        for daemon, sess in self.sessions.items():
            if not daemon.startswith("osd."):
                continue
            row = {}
            for key, out in (("write_lat_us", "commit_latency_ms"),
                             ("subop_w_lat_us", "apply_latency_ms")):
                series = summary.get("series", {}).get(key, {})
                v = series.get(daemon)
                row[out] = round(v["mean"] / 1000.0, 3) if v else 0.0
            osd_perf[daemon.split(".", 1)[1]] = row
        health = {}
        for mod in self.modules.values():
            if mod.running:
                health.update(mod.health())
        health.update(self._slow_ops_health())
        digest = {
            "ts": time.time(),
            "active": self.name,
            "gid": self.gid,
            "daemons": sorted(self.sessions),
            "reports_rx": int(self.perf.dump().get("reports_rx", 0)),
            "osd_perf": osd_perf,
            "top_slow_osds": self._top_slow_osds(),
            "slow_osds": sorted(self._outlier_daemons()),
            "analytics": {
                "percentiles": summary.get("percentiles", {}),
                "outliers": summary.get("outliers", {}),
            },
            "traces": self._digest_traces(),
            "health": health,
            "engine": {
                "cold_launches": int(
                    self.engine.stats.get("cold_launches", 0)),
                "launches": int(self.engine.stats.get("launches", 0)),
                "prewarmed_shapes": int(
                    self.engine.stats.get("prewarmed_shapes", 0)),
                "fallbacks": int(self.engine.stats.get("fallbacks", 0)),
            },
        }
        load_clients = {}
        for daemon, sess in self.sessions.items():
            # load-harness telemetry sessions (loadgen/driver.py):
            # surfaced in the digest so `mgr digest` serves the
            # ingested client-side view back for cross-checking
            if not daemon.startswith("loadgen."):
                continue
            load_clients[daemon] = {
                "reports": sess.get("reports", 0),
                "gauges": {k: round(float(v), 1)
                           for k, v in sess.get("gauges", {}).items()},
                "counters": {k: float(v) for k, v in
                             sess.get("counters", {}).items()},
            }
        if load_clients:
            digest["load_clients"] = load_clients
        prom = self.modules.get("prometheus")
        if prom is not None and prom.running:
            digest["prometheus"] = prom.text()
            if prom.addr:
                digest["prometheus_addr"] = list(prom.addr)
        prog = self.modules.get("progress")
        if prog is not None and prog.running:
            digest["progress"] = {
                "events": prog.public_events(),
                "completed": prog.public_completed(),
            }
        crash = self.modules.get("crash")
        if crash is not None and crash.running:
            digest["crash"] = crash.summary()
        return digest

    # -- modules -------------------------------------------------------

    def enabled_modules(self) -> set[str]:
        return set(self.mgrmap.get("modules") or [])

    async def _module_loop(self) -> None:
        interval = self.conf["mgr_module_tick_interval"]
        while not self.stopping:
            await asyncio.sleep(interval)
            try:
                await self._reconcile_modules()
                if self.active:
                    for name in sorted(self.enabled_modules()):
                        mod = self.modules.get(name)
                        if mod is not None and mod.running:
                            await mod.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("mgr.%s: module tick failed", self.name)

    async def _reconcile_modules(self) -> None:
        want = self.enabled_modules() if self.active else set()
        for name, mod in self.modules.items():
            if name in want and not mod.running:
                await mod.start()
                self.perf.inc("module_starts")
            elif name not in want and mod.running:
                await mod.stop()

    # -- mon command client (for the balancer module) ------------------

    async def mon_command(self, cmd: dict) -> tuple[int, str, bytes]:
        tid = next(self._tids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._cmd_waiters[tid] = fut
        try:
            await self._mon_conn.send_message(MMonCommand(
                tid=tid, cmd=cmd))
            ack = await asyncio.wait_for(fut, 10.0)
            return ack.code, ack.rs, ack.data
        finally:
            self._cmd_waiters.pop(tid, None)
