"""Cluster analytics engine: ONE batched reduction over the whole
time-series store.

The mgr's DaemonServer lands every report in a fixed-shape
``(daemons x metrics x window)`` ring buffer (mgr/daemon.py
``TimeSeriesStore``).  This module computes the cluster-wide view —
p50/p95/p99 per metric, EWMA trend per (daemon, metric) series, and
outlier-OSD detection — as a single jitted XLA program over that whole
array: the same shape every tick, prewarmed at mgr start, so after
warmup **zero** XLA compiles happen on the digest path (the
``cold_launches`` discipline the decode/scrub batchers established;
counters land in ``BucketCounters("mgr_analytics")``).

Bit-identical numpy fallback
----------------------------
The contract is that the numpy host path returns *bit-identical*
arrays to the batched device path (tests/test_mgr.py pins it on random
data).  Floating-point reductions cannot promise that (XLA and numpy
order their sums differently), so the engine is **integer-exact** end
to end:

- samples are int64 (the store quantizes at ingest — latencies ride
  as integer microseconds);
- percentiles are nearest-rank selections on sorted int64 arrays
  (sorting identical integers is order-exact on every backend);
- EWMA runs in fixed point: values are scaled by ``2**SCALE_SHIFT``
  and the recurrence ``e += (x*S - e) >> ALPHA_SHIFT`` (alpha = 1/4)
  uses only int64 adds/shifts — ``lax.scan`` and the numpy loop walk
  the identical sequence;
- per-series means are ``(sum << SCALE_SHIFT) // count`` (int64
  floor division — exact and associative);
- an OSD is an outlier on a metric when its mean exceeds
  ``OUTLIER_FACTOR x`` the median of all daemon means (median =
  lower-median selection on sorted int64).

Everything a float could express is recovered on the way out
(``>> SCALE_SHIFT`` -> µs), but the reduction itself never leaves
int64 — that is what makes "numpy fallback bit-identical" a theorem
rather than a hope.
"""

from __future__ import annotations

import collections
import threading

import numpy as np

from ceph_tpu.common.metrics import BucketCounters

#: fixed-point scale for EWMA/means (values carry 2**8 sub-unit bits)
SCALE_SHIFT = 8
#: EWMA alpha = 1 / 2**ALPHA_SHIFT = 0.25
ALPHA_SHIFT = 2
#: percentiles the digest reports (nearest-rank)
PCTS = (50, 95, 99)
#: a daemon mean > OUTLIER_FACTOR * median(means) flags an outlier
OUTLIER_FACTOR = 2

_I64_MAX = np.int64(np.iinfo(np.int64).max)


def analytics_counters() -> BucketCounters:
    """Process-wide analytics perf collection (launch/cold-compile
    accounting, same shape as the decode/scrub batchers' so the chaos
    engine's cold_launches invariant can watch it)."""
    return BucketCounters("mgr_analytics")


def _ordered(values: np.ndarray, valid: np.ndarray, cursor: np.ndarray,
             xp):
    """Unroll each daemon's ring into time order (oldest first):
    ``cursor[d]`` is the next write position, i.e. the oldest sample.
    Pure gather — identical on both backends."""
    D, M, W = values.shape
    idx = (cursor[:, None].astype(np.int64)
           + xp.arange(W, dtype=np.int64)[None, :]) % W  # (D, W)
    gid = xp.broadcast_to(idx[:, None, :], (D, M, W))
    vals = xp.take_along_axis(values, gid, axis=2)
    mask = xp.take_along_axis(valid, gid, axis=2)
    return vals, mask


def _percentiles(vals, mask, xp):
    """(M, len(PCTS)) nearest-rank percentiles over every valid sample
    of each metric (daemons x window flattened)."""
    D, M, W = vals.shape
    flat = xp.swapaxes(vals, 0, 1).reshape(M, D * W)
    fmask = xp.swapaxes(mask, 0, 1).reshape(M, D * W)
    sent = xp.where(fmask, flat, _I64_MAX)
    srt = xp.sort(sent, axis=1)
    n = xp.sum(fmask.astype(np.int64), axis=1)  # (M,)
    cols = []
    for p in PCTS:
        pos = (np.int64(p) * n + np.int64(99)) // np.int64(100) - np.int64(1)
        pos = xp.clip(pos, 0, D * W - 1)
        v = xp.take_along_axis(srt, pos[:, None], axis=1)[:, 0]
        cols.append(xp.where(n > 0, v, np.int64(0)))
    return xp.stack(cols, axis=1), n


def _means(vals, mask, xp):
    """Scaled per-(daemon, metric) means + counts, exact int64."""
    sums = xp.sum(xp.where(mask, vals, np.int64(0)), axis=2)
    cnt = xp.sum(mask.astype(np.int64), axis=2)
    mean_scaled = (sums << np.int64(SCALE_SHIFT)) // xp.maximum(
        cnt, np.int64(1))
    return xp.where(cnt > 0, mean_scaled, np.int64(0)), cnt


def _outliers(mean_scaled, cnt, xp):
    """(D, M) bool: daemon's mean > OUTLIER_FACTOR x lower-median of
    reporting daemons' means on that metric."""
    col = xp.swapaxes(mean_scaled, 0, 1)  # (M, D)
    have = xp.swapaxes(cnt, 0, 1) > 0
    sent = xp.where(have, col, _I64_MAX)
    srt = xp.sort(sent, axis=1)
    nv = xp.sum(have.astype(np.int64), axis=1)
    med_idx = xp.clip((nv - 1) // 2, 0, col.shape[1] - 1)
    med = xp.take_along_axis(srt, med_idx[:, None], axis=1)[:, 0]
    med = xp.where(nv > 0, med, np.int64(0))
    out = have & (col > np.int64(OUTLIER_FACTOR) * med[:, None]) \
        & (med[:, None] > 0)
    return xp.swapaxes(out, 0, 1)


def _ewma_numpy(vals: np.ndarray, mask: np.ndarray) -> np.ndarray:
    D, M, W = vals.shape
    e = np.zeros((D, M), np.int64)
    seen = np.zeros((D, M), bool)
    for t in range(W):
        x = vals[:, :, t]
        v = mask[:, :, t]
        xs = x << np.int64(SCALE_SHIFT)
        upd = e + ((xs - e) >> np.int64(ALPHA_SHIFT))
        e = np.where(v, np.where(seen, upd, xs), e)
        seen = seen | v
    return e


def analyze_numpy(values: np.ndarray, valid: np.ndarray,
                  cursor: np.ndarray) -> dict[str, np.ndarray]:
    """Host reference path — the semantics the batched path must match
    bit for bit."""
    values = values.astype(np.int64, copy=False)
    valid = valid.astype(bool, copy=False)
    vals, mask = _ordered(values, valid, cursor, np)
    pct, nsamples = _percentiles(vals, mask, np)
    mean_scaled, cnt = _means(vals, mask, np)
    outlier = _outliers(mean_scaled, cnt, np)
    return {
        "percentiles": pct,            # (M, 3) int64, raw units
        "n_samples": nsamples,         # (M,) int64
        "ewma_scaled": _ewma_numpy(vals, mask),  # (D, M) int64 << 8
        "mean_scaled": mean_scaled,    # (D, M) int64 << 8
        "count": cnt,                  # (D, M) int64
        "outlier": outlier,            # (D, M) bool
    }


class AnalyticsEngine:
    """The batched engine: one jitted program per (D, M, W) shape.

    The shape is FIXED at construction (from mgr_stats_* config), so
    :meth:`prewarm` compiles the entire launch set — one program — at
    mgr start; every later :meth:`analyze` is a warm launch.  Any
    device failure answers from :func:`analyze_numpy` (bit-identical,
    so callers cannot tell).
    """

    def __init__(self, n_daemons: int, n_metrics: int, window: int,
                 backend: str = "jax"):
        self.shape = (n_daemons, n_metrics, window)
        self.backend = backend
        self.stats = collections.Counter()
        self.metrics = analytics_counters()
        self._warm: set[tuple] = set()
        self._warm_lock = threading.Lock()
        self._jit = None

    # -- device path ---------------------------------------------------

    def _build_jit(self):
        import jax
        import jax.numpy as jnp

        def _ewma_jax(vals, mask):
            xs_all = jnp.moveaxis(vals, 2, 0)   # (W, D, M)
            v_all = jnp.moveaxis(mask, 2, 0)

            def step(carry, xv):
                e, seen = carry
                x, v = xv
                xs = x << np.int64(SCALE_SHIFT)
                upd = e + ((xs - e) >> np.int64(ALPHA_SHIFT))
                e2 = jnp.where(v, jnp.where(seen, upd, xs), e)
                return (e2, seen | v), None

            D, M, _W = vals.shape
            init = (jnp.zeros((D, M), jnp.int64),
                    jnp.zeros((D, M), bool))
            (e, _seen), _ = jax.lax.scan(step, init, (xs_all, v_all))
            return e

        def run(values, valid, cursor):
            vals, mask = _ordered(values, valid, cursor, jnp)
            pct, nsamples = _percentiles(vals, mask, jnp)
            mean_scaled, cnt = _means(vals, mask, jnp)
            outlier = _outliers(mean_scaled, cnt, jnp)
            ewma = _ewma_jax(vals, mask)
            return pct, nsamples, ewma, mean_scaled, cnt, outlier

        return jax.jit(run)

    def _run_device(self, values, valid, cursor,
                    count_cold: bool = True) -> dict[str, np.ndarray]:
        import jax

        try:
            _x64 = jax.enable_x64
        except AttributeError:  # jax-0.4.x
            from jax.experimental import enable_x64 as _x64
        from ceph_tpu.ops.compile_cache import ensure_persistent_cache

        ensure_persistent_cache()
        with _x64(True):
            if self._jit is None:
                self._jit = self._build_jit()
            shape_key = ("analytics", self.shape)
            if shape_key not in self._warm:
                with self._warm_lock:
                    if shape_key not in self._warm:
                        self._warm.add(shape_key)
                        if count_cold:
                            # an analyze() winning the compile race IS
                            # a cold launch; prewarm passes False and
                            # never touches the counter (it must not
                            # even transiently read non-zero)
                            self.stats["cold_launches"] += 1
                            self.metrics.inc("cold_launches")
            import contextlib

            from ceph_tpu.common.tracing import device_tracer
            from ceph_tpu.common.transfer_guard import (
                no_implicit_transfers,
            )

            # device-launch profiling span on real digest passes only
            # (prewarm's compile is intentional, not a launch to study)
            span_cm = (
                device_tracer().span(
                    "xla_launch", stage="device", kind="mgr_analytics",
                    shape=str(self.shape))
                if count_cold else contextlib.nullcontext()
            )
            # transfers are explicit: the three store-snapshot arrays
            # ride ONE device_put each (they used to slide into the
            # jitted digest as raw numpy — an implicit h2d per array
            # per tick, flagged by ctlint's transfer rules and
            # disallowed under the runtime guard), and the six digest
            # outputs come back in ONE device_get (the by-design host
            # exit: the digest is consumed host-side by the mon/mgr
            # report plane)
            with span_cm, no_implicit_transfers("mgr_analytics"):
                out = self._jit(
                    jax.device_put(values.astype(np.int64)),
                    jax.device_put(valid.astype(bool)),
                    jax.device_put(cursor.astype(np.int64)))
                out = jax.device_get(jax.block_until_ready(list(out)))
        pct, nsamples, ewma, mean_scaled, cnt, outlier = out
        return {
            "percentiles": pct, "n_samples": nsamples,
            "ewma_scaled": ewma, "mean_scaled": mean_scaled,
            "count": cnt, "outlier": outlier,
        }

    # -- public API ----------------------------------------------------

    def prewarm(self) -> int:
        """Compile the engine's single launch shape with zeros.  Call
        at mgr start (via to_thread) — after this, analyze() never
        compiles (cold_launches stays 0).  Returns programs compiled
        (0 when the backend is numpy or the shape is already warm)."""
        if self.backend != "jax":
            return 0
        shape_key = ("analytics", self.shape)
        if shape_key in self._warm:
            return 0
        D, M, W = self.shape
        try:
            self._run_device(np.zeros((D, M, W), np.int64),
                             np.zeros((D, M, W), bool),
                             np.zeros(D, np.int64),
                             count_cold=False)
        except Exception:
            self.stats["prewarm_failures"] += 1
            return 0
        self.stats["prewarmed_shapes"] += 1
        self.metrics.inc("prewarmed_shapes")
        return 1

    def analyze(self, values: np.ndarray, valid: np.ndarray,
                cursor: np.ndarray) -> dict[str, np.ndarray]:
        """One batched pass over the whole store snapshot.  Shapes must
        match the engine's fixed (D, M, W)."""
        assert values.shape == self.shape, (values.shape, self.shape)
        self.stats["passes"] += 1
        self.metrics.inc("passes")
        if self.backend == "jax":
            try:
                out = self._run_device(values, valid, cursor)
                self.stats["launches"] += 1
                self.metrics.inc("launches")
                return out
            except Exception:
                self.stats["fallbacks"] += 1
                self.metrics.inc("fallbacks")
        return analyze_numpy(values, valid, cursor)
