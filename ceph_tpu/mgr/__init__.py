"""Manager-module services (the src/pybind/mgr/ role).

The always-on mgr functions — PG-stat aggregation, health, balancer,
pg_autoscaler, prometheus text — live in the monitor process
(ceph_tpu/mon/monitor.py, ceph_tpu/common/metrics.py); this package
holds the optional module services: the dashboard (dashboard.py)."""
