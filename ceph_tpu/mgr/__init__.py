"""Manager daemon + module services (the ceph-mgr / src/pybind/mgr
role).

- :mod:`daemon` — the MgrDaemon process: beacons into the mon
  (active/standby is the MgrMonitor's call), hosts the DaemonServer
  report plane (every daemon's MgrClient streams MMgrReport
  telemetry into fixed-shape ring buffers) and the batched analytics
  engine, and digests back to the mon (MMonMgrReport — `ceph osd
  perf`, dashboard views, health checks);
- :mod:`analytics` — cluster-wide p50/p95/p99, EWMA trends and
  outlier-OSD detection as ONE jitted reduction over the whole
  (daemons x metrics x window) array, prewarmed at mgr start
  (cold_launches == 0), with a bit-identical numpy fallback;
- :mod:`client` — MgrClient, embedded in OSD/mon/MDS/RGW daemons:
  watches the MgrMap, re-opens its session after failover, ships
  perf-counter deltas + log2 latency histograms + status;
- :mod:`modules` — the module framework (`ceph mgr module
  ls/enable/disable`) hosting prometheus (cluster-aggregated
  exposition), devicehealth (read-error-ledger -> device life
  expectancy + warnings) and balancer (periodic automated upmap
  rounds, off by default);
- :mod:`dashboard` — the read-only web UI (serves the mgr's
  aggregated series when a mgr is active).
"""

from ceph_tpu.mgr.analytics import AnalyticsEngine, analyze_numpy  # noqa: F401
from ceph_tpu.mgr.client import MgrClient  # noqa: F401
from ceph_tpu.mgr.daemon import MgrDaemon, TimeSeriesStore  # noqa: F401
from ceph_tpu.mgr.modules import (  # noqa: F401
    DEFAULT_MODULES,
    MODULE_REGISTRY,
    MgrModule,
)
