"""TraceCollector: cross-daemon trace assembly on the active mgr.

The jaeger-collector role for the cluster's tracing plane
(common/tracing.py): every daemon's MgrClient drains its tracers'
export buffers into ``MMgrReport.spans``; the active mgr lands them
here, keyed by trace_id.  On demand (``ceph trace ls/show``, the
dashboard, the digest) the collector assembles each trace's span tree,
computes the **critical path** and a **per-stage latency breakdown**
(net / queue / device / store / other), and keeps a bounded history of
slow traces — the cluster-wide analogue of the op tracker's
``dump_historic_slow_ops``.

Ordering: spans are sorted by their monotonic start stamps when they
come from the same process (shared clock) and by wall-clock start
otherwise, so cross-daemon assembly never produces negative-latency
children from clock skew.

Assembly tolerates missing parents: the client's root span never
reaches the mgr (clients carry no MgrClient), so a span whose
parent_id is unknown becomes a child of a SYNTHESIZED root labelled
from the wire context's reqid — the tree still reads client -> primary
-> shards -> store commit.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque

from ceph_tpu.common.tracing import STAGES


def _stage_of(span: dict) -> str:
    st = str(span.get("tags", {}).get("stage", "other"))
    return st if st in STAGES else "other"


class TraceCollector:
    def __init__(self, max_traces: int = 256, slow_history: int = 32,
                 slow_s: float = 1.0):
        self.max_traces = max_traces
        self.slow_s = slow_s
        #: trace_id -> {"spans": [span dicts], "first", "last", "reqid"}
        self.traces: "OrderedDict[int, dict]" = OrderedDict()
        #: assembled slow-trace records (bounded)
        self.slow: deque = deque(maxlen=slow_history)
        self._slow_seen: set[int] = set()
        #: device-launch profiling spans (xla_launch): standalone
        #: roots by design — kept in their own ring so thousands of
        #: launches cannot evict real request traces from the LRU
        self.device: deque = deque(maxlen=512)
        self.stats = {
            "spans_rx": 0, "traces_evicted": 0, "orphan_spans": 0,
            "device_spans": 0,
        }

    # -- ingest --------------------------------------------------------

    def ingest(self, daemon: str, spans: list[dict]) -> None:
        now = time.monotonic()
        for sp in spans:
            tid = sp.get("trace_id")
            if not tid:
                continue
            if sp.get("daemon") == "device" or sp.get("name") == "xla_launch":
                self.device.append(dict(sp))
                self.stats["device_spans"] += 1
                continue
            rec = self.traces.get(tid)
            if rec is None:
                rec = self.traces[tid] = {
                    "spans": [], "first": now, "reqid": "",
                }
                while len(self.traces) > self.max_traces:
                    self.traces.popitem(last=False)
                    self.stats["traces_evicted"] += 1
            else:
                self.traces.move_to_end(tid)
            sp = dict(sp)
            sp.setdefault("daemon", daemon)
            rec["spans"].append(sp)
            rec["last"] = now
            if not rec["reqid"] and sp.get("tags", {}).get("reqid"):
                rec["reqid"] = str(sp["tags"]["reqid"])
            self.stats["spans_rx"] += 1
            # tail capture: a slow trace is archived once its slow
            # span count stabilizes (re-assembled lazily on access)
            dur = sp.get("duration_ms") or 0.0
            if dur >= self.slow_s * 1e3 and tid not in self._slow_seen:
                self._slow_seen.add(tid)
                self.slow.append(tid)

    # -- assembly ------------------------------------------------------

    @staticmethod
    def _sort_key(sp: dict):
        return (sp.get("start") or 0.0, sp.get("start_mono") or 0.0)

    def assemble(self, trace_id: int) -> dict | None:
        """Build the span tree + critical path + stage breakdown for
        one trace.  Returns None for an unknown trace_id."""
        rec = self.traces.get(trace_id)
        if rec is None:
            return None
        spans = sorted(rec["spans"], key=self._sort_key)
        by_id = {sp["span_id"]: sp for sp in spans}
        children: dict[int, list[dict]] = {}
        roots: list[dict] = []
        synthetic: dict | None = None
        for sp in spans:
            pid = sp.get("parent_id")
            if pid is None:
                roots.append(sp)
            elif pid in by_id:
                children.setdefault(pid, []).append(sp)
            else:
                # parent never reached us (the client's root, or an
                # evicted/raced report): hang it under a synthesized
                # root so the tree stays connected
                self.stats["orphan_spans"] += 1
                if synthetic is None:
                    synthetic = {
                        "name": "client_op*", "span_id": pid,
                        "parent_id": None, "trace_id": trace_id,
                        "daemon": "client", "synthetic": True,
                        "start": sp.get("start"),
                        "start_mono": sp.get("start_mono"),
                        "end_mono": sp.get("end_mono"),
                        "duration_ms": None,
                        "tags": {"reqid": rec["reqid"]},
                    }
                    roots.append(synthetic)
                    by_id[pid] = synthetic
                children.setdefault(pid, []).append(sp)
        if synthetic is not None:
            # bound the synthetic root by its known descendants
            kids = children.get(synthetic["span_id"], [])
            if kids:
                starts = [k.get("start_mono") or 0.0 for k in kids]
                ends = [k.get("end_mono") or 0.0 for k in kids]
                synthetic["start_mono"] = min(starts)
                synthetic["end_mono"] = max(ends)
                synthetic["start"] = min(
                    k.get("start") or 0.0 for k in kids)
                synthetic["duration_ms"] = round(
                    (synthetic["end_mono"] - synthetic["start_mono"])
                    * 1e3, 3)
        if not roots:
            return None
        root = max(
            roots,
            key=lambda sp: (sp.get("duration_ms") or 0.0),
        )

        def _node(sp: dict) -> dict:
            return {
                "name": sp["name"],
                "daemon": sp.get("daemon", ""),
                "span_id": sp["span_id"],
                "stage": _stage_of(sp),
                "start": sp.get("start"),
                "start_mono": sp.get("start_mono"),
                "end_mono": sp.get("end_mono"),
                "duration_ms": sp.get("duration_ms"),
                "tags": dict(sp.get("tags", {})),
                "children": [
                    _node(c) for c in sorted(
                        children.get(sp["span_id"], ()),
                        key=self._sort_key)
                ],
            }

        tree = _node(root)
        path, stages = self._critical_path(tree)
        return {
            "trace_id": trace_id,
            "reqid": rec["reqid"],
            "root": tree["name"],
            "daemons": sorted({sp.get("daemon", "") for sp in spans}),
            "n_spans": len(spans),
            "duration_ms": tree["duration_ms"],
            "stages_ms": stages,
            "critical_path": path,
            "tree": tree,
        }

    @staticmethod
    def _critical_path(tree: dict) -> tuple[list[dict], dict]:
        """Walk the dominant child chain: at each node follow the child
        that ends LATEST (the op cannot have completed before it); the
        node's exclusive time — its duration minus the on-path child's
        — lands in the node's stage bucket.  Returns (path, stage_ms).
        """
        stages = {s: 0.0 for s in STAGES}
        path: list[dict] = []
        node = tree
        while node is not None:
            dur = node.get("duration_ms") or 0.0
            kids = [
                c for c in node.get("children", ())
                if c.get("end_mono") is not None
            ]
            nxt = max(
                kids, key=lambda c: c["end_mono"], default=None)
            child_dur = (nxt.get("duration_ms") or 0.0) if nxt else 0.0
            exclusive = max(dur - child_dur, 0.0)
            stages[_stage_of(node)] += exclusive
            path.append({
                "name": node["name"], "daemon": node.get("daemon", ""),
                "stage": _stage_of(node),
                "duration_ms": dur,
                "exclusive_ms": round(exclusive, 3),
            })
            node = nxt
        return path, {k: round(v, 3) for k, v in stages.items()}

    # -- query surface -------------------------------------------------

    def ls(self, limit: int = 32) -> list[dict]:
        """Newest-first trace summaries (`ceph trace ls`)."""
        out = []
        for tid in list(reversed(self.traces.keys()))[:limit]:
            a = self.assemble(tid)
            if a is None:
                continue
            out.append({
                "trace_id": tid,
                "reqid": a["reqid"],
                "root": a["root"],
                "daemons": a["daemons"],
                "n_spans": a["n_spans"],
                "duration_ms": a["duration_ms"],
                "slow": tid in self._slow_seen,
            })
        return out

    def slow_traces(self, limit: int = 8) -> list[dict]:
        out = []
        for tid in list(self.slow)[-limit:]:
            a = self.assemble(tid)
            if a is not None:
                out.append(a)
        return out

    def device_launches(self, limit: int = 64) -> list[dict]:
        """Most recent device-launch profiling spans (bucket shape,
        occupancy, cold verdict, block-until-ready duration)."""
        return list(self.device)[-limit:]

    def dump(self) -> dict:
        return {
            "stats": dict(self.stats),
            "n_traces": len(self.traces),
            "slow": [int(t) for t in self.slow],
            "device_launches": len(self.device),
        }


def render_tree(tree: dict, indent: int = 0) -> list[str]:
    """Human-readable span-tree lines (the `ceph trace show` view)."""
    dur = tree.get("duration_ms")
    line = "{}{} [{}] {}{}".format(
        "  " * indent, tree["name"], tree.get("daemon", "?"),
        f"{dur:.3f}ms" if dur is not None else "?",
        f" stage={tree.get('stage')}" if tree.get("stage") else "",
    )
    out = [line]
    for c in tree.get("children", ()):
        out.extend(render_tree(c, indent + 1))
    return out
