"""librados-style client: cluster handle, IoCtx, op targeting.

Twin of the reference client stack (librados IoCtx ->
IoCtxImpl::operate -> Objecter::op_submit, SURVEY.md §3.1): the cluster
handle subscribes to maps from the mon; each op hashes the object name
to a PG (object_locator_to_pg via ceph_str_hash_rjenkins), computes the
acting primary with the same OSDMap pipeline the OSDs use
(Objecter::_calc_target, src/osdc/Objecter.cc:2783), sends an MOSDOp
to it, and resends after a map change when the primary moved or
replied -EAGAIN — the Objecter's resend-on-new-epoch behavior.
"""

from __future__ import annotations

import asyncio
import errno
import itertools
import logging
import os

from ceph_tpu.msg.messages import (
    MConfig,
    MMgrMap,
    MMonCommand,
    MMonCommandAck,
    MMonSubscribe,
    MOSDMap,
    MOSDOp,
    MOSDOpReply,
    MWatchNotify,
    MWatchNotifyAck,
    OP_APPEND,
    OP_CALL,
    OP_CREATE,
    OP_DELETE,
    OP_GETXATTR,
    OP_GETXATTRS,
    OP_OMAP_CLEAR,
    OP_OMAP_GETKEYS,
    OP_OMAP_GETVALS,
    OP_OMAP_GETVALSBYKEYS,
    OP_OMAP_RMKEYS,
    OP_OMAP_SETKEYS,
    OP_READ,
    OP_RMXATTR,
    OP_SETXATTR,
    OP_STAT,
    OP_NOTIFY,
    OP_TRUNCATE,
    OP_UNWATCH,
    OP_WATCH,
    OP_WRITE,
    OP_WRITE_FULL,
    OP_ZERO,
    OSDOp,
)
from ceph_tpu.msg.messenger import Connection, Message, Messenger
from ceph_tpu.osd.daemon import object_to_pg
from ceph_tpu.osd.osdmap import OSDMap

log = logging.getLogger("ceph_tpu.client")

OP_TIMEOUT = 30.0
# the reference Objecter resends indefinitely as maps advance; bounded
# here but generous — under heavy co-tenant CPU contention a recovering
# cluster can legitimately answer EAGAIN for a while
MAX_RETRIES = 25
# resend backoff: exponential with full jitter, bounded (the
# objecter_retry/backoff discipline — fixed sleeps synchronize every
# blocked client into retry storms against a recovering primary)
BACKOFF_BASE = 0.05
BACKOFF_MAX = 1.0


class RadosError(OSError):
    pass


class RadosClient:
    """The cluster handle (librados::Rados)."""

    def __init__(self, client_id: int | None = None, auth=None,
                 handshake_timeout: float | None = None,
                 op_timeout: float = 120.0,
                 trace_sample_rate: float = 1.0, conf=None):
        from ceph_tpu.common import ConfigProxy

        self.id = client_id if client_id is not None else (os.getpid() << 8) | 1
        # per-op wall-clock budget across ALL resends (librados
        # rados_osd_op_timeout role): an op that can't complete within
        # it raises ETIMEDOUT instead of spinning through retries
        self.op_timeout = op_timeout
        # client-side option view (objecter window sizes, batch caps)
        self.conf = conf if conf is not None else ConfigProxy()
        _mkw = {}
        if handshake_timeout is not None:
            _mkw["handshake_timeout"] = handshake_timeout
        self.messenger = Messenger(
            ("client", self.id), self._dispatch, on_reset=self._on_reset,
            auth=auth, **_mkw,
        )
        # cluster-wide tracing root: every submitted op opens a
        # client_op span whose context rides the MOSDOp frame — the
        # Objecter-side jaeger root of the reference's trace chain
        from ceph_tpu.common.tracing import get_tracer

        self.tracer = get_tracer(f"client.{self.id}")
        self.tracer.sample_rate = trace_sample_rate
        self.messenger.tracer = self.tracer
        self.osdmap: OSDMap | None = None
        self._mon_conn: Connection | None = None
        self._tids = itertools.count(1)
        self._op_waiters: dict[int, asyncio.Future] = {}
        self._cmd_waiters: dict[int, asyncio.Future] = {}
        self._map_event = asyncio.Event()
        # watch registrations: cookie -> callback(notify_id, payload)
        # -> optional reply bytes (librados watch2/notify2)
        self._watches: dict[int, object] = {}
        # the async submission engine (client/objecter.py): EVERY op —
        # serial convenience calls included — rides it, so resends,
        # map waits and timeout accounting are per-op by construction
        from ceph_tpu.client.objecter import Objecter

        self.objecter = Objecter(self)

    async def connect(self, mon_host: str, mon_port: int) -> None:
        await self.connect_multi([(mon_host, mon_port)])

    async def connect_multi(self, monmap: list[tuple[str, int]]) -> None:
        """Connect against a monitor quorum: subscribe to the first
        reachable member; commands re-target the leader on ENOTLEADER
        redirects (the MonClient hunting/redirect behavior)."""
        self._mon_addrs = list(monmap)
        if not hasattr(self, "_monmap"):
            self._monmap: dict[int, tuple[str, int]] = {}  # rank -> addr
        new_conn = None
        last: Exception | None = None
        addr_rank = {a: r for r, a in self._monmap.items()}
        for host, port in self._mon_addrs:
            rank = addr_rank.get((host, port))
            if rank is not None:
                # reuse a live session instead of stacking new sockets
                existing = self.messenger.get_connection(("mon", rank))
                if existing is not None and not existing._closed:
                    if new_conn is None:
                        new_conn = existing
                    continue
            try:
                conn = await self.messenger.connect(host, port)
            except (ConnectionError, OSError) as e:
                last = e
                continue
            # the HELLO tells us which rank answers at this address
            self._monmap[conn.peer[1]] = (host, port)
            if new_conn is None:
                new_conn = conn
        if new_conn is None:
            raise RadosError(errno.EHOSTUNREACH, f"no monitor reachable: {last}")
        # swap atomically: concurrent commands never see a None session
        self._mon_conn = new_conn
        await self._mon_conn.send_message(MMonSubscribe(
            start_epoch=self.osdmap.epoch if self.osdmap else 0
        ))
        await self._wait_new_map(0, timeout=10.0)
        if self.osdmap is None:
            raise RadosError(errno.ETIMEDOUT, "no map from mon")

    async def shutdown(self) -> None:
        self._stopping = True
        t = getattr(self, "_hunt_task", None)
        if t:
            t.cancel()
        await self.objecter.shutdown()
        await self.messenger.shutdown()

    async def _on_reset(self, conn) -> None:
        """Our monitor session died: hunt for a live quorum member and
        re-subscribe so maps keep flowing (MonClient hunting)."""
        if conn is not self._mon_conn or getattr(self, "_stopping", False):
            return

        async def hunt():
            for _ in range(50):
                await asyncio.sleep(0.2)
                if getattr(self, "_stopping", False):
                    return
                try:
                    await self.connect_multi(self._mon_addrs)
                    return
                except (RadosError, ConnectionError, OSError):
                    continue

        self._hunt_task = asyncio.ensure_future(hunt())

    async def _dispatch(self, msg: Message) -> None:
        if isinstance(msg, MOSDMap):
            from ceph_tpu.osd.mapenc import apply_map_message

            # copy-on-write swap: in-flight ops' `om` snapshots stay
            # stable, so _wait_new_map(om.epoch) wakes immediately
            new_map, gap = apply_map_message(self.osdmap, msg.maps, msg.incs)
            if new_map is not None:
                self.osdmap = new_map
            if gap:
                # re-subscribe from our epoch (mon sends the missing
                # incrementals, or a full map)
                try:
                    await self._mon_conn.send_message(MMonSubscribe(
                        start_epoch=self.osdmap.epoch if self.osdmap else 0
                    ))
                except ConnectionError:
                    pass  # hunt will re-subscribe
            ev, self._map_event = self._map_event, asyncio.Event()
            ev.set()  # wake everyone waiting for "a newer map than X"
        elif isinstance(msg, MOSDOpReply):
            fut = self._op_waiters.get(msg.tid)
            if fut and not fut.done():
                fut.set_result(msg)
        elif isinstance(msg, MConfig):
            pass  # clients carry no daemon config to apply (yet)
        elif isinstance(msg, MMgrMap):
            # the mon broadcasts the MgrMap to every subscriber; hosts
            # that embed an MgrClient over this session (MDS, the RGW
            # frontend) register a listener for it
            self.mgrmap_msg = msg
            cb = getattr(self, "_mgr_map_cb", None)
            if cb is not None:
                cb(msg)
        elif isinstance(msg, MMonCommandAck):
            fut = self._cmd_waiters.get(msg.tid)
            if fut and not fut.done():
                fut.set_result(msg)
        elif isinstance(msg, MWatchNotify):
            cb = self._watches.get(msg.cookie)
            if cb is None:
                return  # stale/unknown watch handle: no ack (the
                # notifier times this watcher out)
            reply = b""
            try:
                out = cb(msg.notify_id, msg.payload)
                if out:
                    reply = bytes(out)
            except Exception:
                log.exception("watch callback failed")
            try:
                await msg.conn.send_message(MWatchNotifyAck(
                    notify_id=msg.notify_id, cookie=msg.cookie, reply=reply,
                ))
            except ConnectionError:
                pass

    def set_mgr_map_listener(self, cb) -> None:
        """Register a callback for MMgrMap broadcasts on this session
        (late registration replays the latest map immediately)."""
        self._mgr_map_cb = cb
        msg = getattr(self, "mgrmap_msg", None)
        if msg is not None:
            cb(msg)

    async def _wait_new_map(self, than_epoch: int, timeout: float = 10.0) -> None:
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while self.osdmap is None or self.osdmap.epoch <= than_epoch:
            # snapshot the event BEFORE re-checking: the dispatcher swaps
            # it under us when a map lands
            ev = self._map_event
            if self.osdmap is not None and self.osdmap.epoch > than_epoch:
                return
            remaining = deadline - loop.time()
            if remaining <= 0:
                return
            try:
                # wake at least once a second to RENEW the subscription
                # (MonClient's sub renewal): a subscribe that landed on
                # a mon mid-election can be forgotten, and without the
                # renewal no map would ever arrive
                await asyncio.wait_for(ev.wait(), min(remaining, 1.0))
            except asyncio.TimeoutError:
                if deadline - loop.time() <= 0:
                    return
                try:
                    if self._mon_conn is not None:
                        await self._mon_conn.send_message(MMonSubscribe(
                            start_epoch=(
                                self.osdmap.epoch if self.osdmap else 0)
                        ))
                except (ConnectionError, OSError):
                    pass  # the hunt task is re-homing us

    # -- admin commands ------------------------------------------------

    async def command(self, cmd: dict[str, str]) -> tuple[int, str, bytes]:
        ack = None
        for _redirect in range(6):
            tid = next(self._tids)
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._cmd_waiters[tid] = fut
            try:
                await self._mon_conn.send_message(MMonCommand(tid=tid, cmd=cmd))
                ack: MMonCommandAck = await asyncio.wait_for(fut, OP_TIMEOUT)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                # our monitor died: hunt for a live one (MonClient
                # hunting) and retry after the election settles
                await asyncio.sleep(0.2)
                try:
                    await self.connect_multi(getattr(self, "_mon_addrs", []))
                except (RadosError, ConnectionError, OSError):
                    pass  # whole quorum briefly unreachable; keep trying
                continue
            finally:
                self._cmd_waiters.pop(tid, None)
            if ack.code == -errno.EAGAIN and ack.rs.startswith("ENOTLEADER"):
                leader = int(ack.rs.split()[1])
                addr = getattr(self, "_monmap", {}).get(leader)
                try:
                    if addr is not None:
                        self._mon_conn = await self.messenger.connect_to(
                            ("mon", leader), *addr
                        )
                        await self._mon_conn.send_message(MMonSubscribe())
                        continue
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    pass  # the named leader just died; wait + retry
                await asyncio.sleep(0.2)  # quorum electing; retry
                continue
            return ack.code, ack.rs, ack.data
        if ack is None:
            return -errno.ETIMEDOUT, "command retries exhausted", b""
        return ack.code, ack.rs, ack.data

    async def wait_clean(
        self, timeout: float = 30.0, min_epoch: int = 0,
    ) -> dict:
        """Poll the mon until every PG reports active+clean (the
        qa-helper wait_for_clean contract, reference
        qa/standalone/ceph-helpers.sh) — via the mon's aggregated pg
        stats, not by probing OSDs.  Returns the final status blob.

        ``min_epoch``: additionally require every counted PG report to
        have been computed at that osdmap epoch or later.  A caller
        that just forced a map change (kill + osd out) passes the
        post-change epoch so leftover pre-change active+clean reports
        cannot satisfy the wait (they made recovery look instant)."""
        import json as _json
        import time as _time

        deadline = _time.monotonic() + timeout
        last = {}
        while _time.monotonic() < deadline:
            code, _rs, data = await self.command({"prefix": "status"})
            if code == 0:
                last = _json.loads(data)
                pgs = last.get("pgs", {})
                by_state = pgs.get("by_state", {})
                if (
                    pgs.get("num_pgs", 0) > 0
                    and pgs.get("num_reported", 0) >= pgs["num_pgs"]
                    and set(by_state) == {"active+clean"}
                    and pgs.get("min_reported_epoch", 0) >= min_epoch
                ):
                    return last
            await asyncio.sleep(0.2)
        raise TimeoutError(f"cluster not clean after {timeout}s: {last.get('pgs')}")

    async def pool_create(
        self, name: str, pg_num: int = 8, pool_type: str = "replicated", **kw
    ) -> int:
        import json

        cmd = {
            "prefix": "osd pool create", "name": name,
            "pg_num": str(pg_num), "pool_type": pool_type,
        }
        cmd.update({k: str(v) for k, v in kw.items()})
        code, rs, data = await self.command(cmd)
        if code != 0:
            raise RadosError(-code, rs)
        return json.loads(data)["pool_id"]

    async def ec_profile_set(self, name: str, profile: dict[str, str]) -> None:
        code, rs, _ = await self.command({
            "prefix": "osd erasure-code-profile set", "name": name,
            "profile": " ".join(f"{k}={v}" for k, v in profile.items()),
        })
        if code != 0:
            raise RadosError(-code, rs)

    def ioctx(self, pool_name: str) -> "IoCtx":
        pid = self.osdmap.lookup_pg_pool_name(pool_name)
        if pid < 0:
            raise RadosError(errno.ENOENT, f"no pool {pool_name!r}")
        return IoCtx(self, pid)

    # -- op engine (Objecter) ------------------------------------------

    async def _backoff(self, attempt: int) -> None:
        """Bounded exponential backoff with full jitter before a
        resend.  Jitter decorrelates the resend times of many clients
        whose ops all failed against the same dead/busy primary —
        without it every retry round lands as one synchronized burst."""
        import random

        cap = min(BACKOFF_BASE * (2 ** attempt), BACKOFF_MAX)
        await asyncio.sleep(cap * (0.5 + random.random() / 2))

    async def _submit(self, pool_id: int, op: MOSDOp) -> MOSDOpReply:
        """Serial convenience path: submit through the objecter and
        wait.  The engine owns op_submit/_calc_target/the resend loop
        and the client_op root span (one client op, one cluster-wide
        trace); timeout/backoff accounting is per-op there, so a slow
        op can never charge a neighbor's deadline."""
        comp = await self.objecter.submit(pool_id, op)
        return await comp.wait()

    async def aio_submit(self, pool_id: int, op: MOSDOp):
        """Async path (librados aio_operate): returns a
        :class:`~ceph_tpu.client.objecter.Completion` once the op is
        admitted through the in-flight window — admission is the
        backpressure seam (objecter_inflight_ops/_op_bytes)."""
        return await self.objecter.submit(pool_id, op)


class ObjectOperation:
    """Batched compound op (librados::ObjectWriteOperation /
    ObjectReadOperation): ops accumulate and ship as ONE atomic
    MOSDOp vector via :meth:`IoCtx.operate`."""

    def __init__(self):
        self.ops: list[OSDOp] = []

    # write class
    def write_full(self, data: bytes):
        self.ops.append(OSDOp(OP_WRITE_FULL, data=bytes(data)))
        return self

    def write(self, off: int, data: bytes):
        self.ops.append(OSDOp(OP_WRITE, off=off, data=bytes(data)))
        return self

    def append(self, data: bytes):
        self.ops.append(OSDOp(OP_APPEND, data=bytes(data)))
        return self

    def zero(self, off: int, length: int):
        self.ops.append(OSDOp(OP_ZERO, off=off, length=length))
        return self

    def truncate(self, size: int):
        self.ops.append(OSDOp(OP_TRUNCATE, off=size))
        return self

    def create(self, exclusive: bool = False):
        self.ops.append(OSDOp(OP_CREATE, off=1 if exclusive else 0))
        return self

    def remove(self):
        self.ops.append(OSDOp(OP_DELETE))
        return self

    def setxattr(self, name: str, value: bytes):
        self.ops.append(OSDOp(OP_SETXATTR, name=name, data=bytes(value)))
        return self

    def rmxattr(self, name: str):
        self.ops.append(OSDOp(OP_RMXATTR, name=name))
        return self

    def omap_set(self, kv: dict[str, bytes]):
        self.ops.append(OSDOp(OP_OMAP_SETKEYS, kv=dict(kv)))
        return self

    def omap_rm_keys(self, keys: list[str]):
        self.ops.append(OSDOp(OP_OMAP_RMKEYS, keys=list(keys)))
        return self

    def omap_clear(self):
        self.ops.append(OSDOp(OP_OMAP_CLEAR))
        return self

    def copy_from(self, src_pool: int, src_oid: str):
        """CEPH_OSD_OP_COPY_FROM: fill the target from another object
        (the tiering promote/flush primitive, PrimaryLogPG copy-from)."""
        from ceph_tpu.msg.messages import OP_COPY_FROM

        self.ops.append(OSDOp(OP_COPY_FROM, name=f"{src_pool}:{src_oid}"))
        return self

    def cache_flush(self):
        from ceph_tpu.msg.messages import OP_CACHE_FLUSH

        self.ops.append(OSDOp(OP_CACHE_FLUSH))
        return self

    def cache_evict(self):
        from ceph_tpu.msg.messages import OP_CACHE_EVICT

        self.ops.append(OSDOp(OP_CACHE_EVICT))
        return self

    # read class
    def read(self, off: int = 0, length: int = 0):
        self.ops.append(OSDOp(OP_READ, off=off, length=length))
        return self

    def stat(self):
        self.ops.append(OSDOp(OP_STAT))
        return self

    def getxattr(self, name: str):
        self.ops.append(OSDOp(OP_GETXATTR, name=name))
        return self

    def getxattrs(self):
        self.ops.append(OSDOp(OP_GETXATTRS))
        return self

    def omap_get_keys(self):
        self.ops.append(OSDOp(OP_OMAP_GETKEYS))
        return self

    def omap_get_vals(self):
        self.ops.append(OSDOp(OP_OMAP_GETVALS))
        return self

    def omap_get_vals_by_keys(self, keys: list[str]):
        self.ops.append(OSDOp(OP_OMAP_GETVALSBYKEYS, keys=list(keys)))
        return self


class IoCtx:
    """Per-pool I/O handle (librados::IoCtx).

    Snapshots (librados snap API): :meth:`set_snap_context` attaches a
    self-managed SnapContext to writes (selfmanaged_snap_set_write_ctx);
    :meth:`snap_set_read` points reads at a snap id (NOSNAP = head).
    """

    def __init__(self, client: RadosClient, pool_id: int):
        self.client = client
        self.pool_id = pool_id
        from ceph_tpu.osd.snaps import NOSNAP

        self.snap_seq: int = 0
        self.snaps: list[int] = []
        self.read_snap: int = NOSNAP
        # dmclock tenant tag stamped on every op from this handle (''
        # = the OSD's built-in client class); the load harness sets it
        # per simulated tenant to exercise mClock differentiation
        self.qos_class: str = ""

    def dup(self) -> "IoCtx":
        """An independent handle on the same pool (librados ioctx
        duplication): snap context and read snap are per-handle, so
        e.g. each RBD image carries its own."""
        io = IoCtx(self.client, self.pool_id)
        io.snap_seq, io.snaps = self.snap_seq, list(self.snaps)
        io.read_snap = self.read_snap
        io.qos_class = self.qos_class
        return io

    def set_snap_context(self, seq: int, snaps: list[int]) -> None:
        """selfmanaged_snap_set_write_ctx: snaps newest-first."""
        if snaps and (seq < snaps[0] or sorted(
                snaps, reverse=True) != list(snaps)):
            raise RadosError(22, "invalid snap context")
        self.snap_seq, self.snaps = seq, list(snaps)

    def snap_set_read(self, snapid) -> None:
        from ceph_tpu.osd.snaps import NOSNAP

        self.read_snap = NOSNAP if snapid is None else snapid

    async def selfmanaged_snap_create(self) -> int:
        """Allocate a new self-managed snap id (pool snap_seq bump)."""
        import json as _json

        name = self.client.osdmap.pool_names[self.pool_id]
        code, rs, data = await self.client.command({
            "prefix": "osd pool selfmanaged-snap create", "pool": name,
        })
        if code != 0:
            raise RadosError(-code, rs)
        return _json.loads(data)["snapid"]

    async def selfmanaged_snap_remove(self, snapid: int) -> None:
        name = self.client.osdmap.pool_names[self.pool_id]
        code, rs, _ = await self.client.command({
            "prefix": "osd pool selfmanaged-snap rm", "pool": name,
            "snapid": str(snapid),
        })
        if code != 0:
            raise RadosError(-code, rs)

    def _msg(self, oid: str, **kw) -> MOSDOp:
        m = MOSDOp(pool=self.pool_id, oid=oid, **kw)
        m.snap_seq, m.snaps = self.snap_seq, list(self.snaps)
        m.snapid = self.read_snap
        m.qos_class = self.qos_class
        return m

    async def _op1(self, oid: str, what: str, **kw) -> MOSDOpReply:
        reply = await self.client._submit(
            self.pool_id, self._msg(oid, **kw))
        if reply.result != 0:
            raise RadosError(-reply.result, f"{what} {oid!r}")
        return reply

    async def operate(self, oid: str, op: ObjectOperation) -> MOSDOpReply:
        """Submit a compound vector; per-op results in reply.outs."""
        reply = await self.client._submit(
            self.pool_id, self._msg(oid, ops=list(op.ops)))
        if reply.result != 0:
            raise RadosError(-reply.result, f"operate {oid!r}")
        return reply

    # -- async I/O (librados aio_*): completions, not round trips ------

    async def aio_operate(self, oid: str, op: ObjectOperation):
        """Submit a compound vector without waiting for the reply:
        returns a Completion (await ``.wait()`` or attach callbacks).
        The call itself only blocks when the objecter's in-flight
        window is full — the backpressure contract."""
        return await self.client.aio_submit(
            self.pool_id, self._msg(oid, ops=list(op.ops)))

    async def aio_write_full(self, oid: str, data: bytes):
        return await self.client.aio_submit(self.pool_id, self._msg(
            oid, op=OP_WRITE_FULL, data=bytes(data)))

    async def aio_write(self, oid: str, data: bytes, off: int):
        return await self.client.aio_submit(self.pool_id, self._msg(
            oid, op=OP_WRITE, off=off, data=bytes(data)))

    async def aio_append(self, oid: str, data: bytes):
        return await self.client.aio_submit(self.pool_id, self._msg(
            oid, op=OP_APPEND, data=bytes(data)))

    async def aio_read(self, oid: str, off: int = 0, length: int = 0):
        return await self.client.aio_submit(self.pool_id, self._msg(
            oid, op=OP_READ, off=off, length=length))

    async def aio_stat(self, oid: str):
        return await self.client.aio_submit(
            self.pool_id, self._msg(oid, op=OP_STAT))

    async def aio_remove(self, oid: str):
        return await self.client.aio_submit(
            self.pool_id, self._msg(oid, op=OP_DELETE))

    async def rollback(self, oid: str, snapid: int) -> None:
        """selfmanaged_snap_rollback: restore head from snap."""
        from ceph_tpu.msg.messages import OP_ROLLBACK

        await self._op1(oid, "rollback", op=OP_ROLLBACK, off=snapid)

    async def list_snaps(self, oid: str) -> dict:
        """Object SnapSet dump (CEPH_OSD_OP_LIST_SNAPS)."""
        import json as _json

        from ceph_tpu.msg.messages import OP_LIST_SNAPS

        reply = await self._op1(oid, "list_snaps", op=OP_LIST_SNAPS)
        return _json.loads(reply.data)

    async def write_full(self, oid: str, data: bytes) -> None:
        await self._op1(oid, "write_full", op=OP_WRITE_FULL, data=bytes(data))

    async def write(self, oid: str, data: bytes, off: int) -> None:
        await self._op1(oid, "write", op=OP_WRITE, off=off, data=bytes(data))

    async def append(self, oid: str, data: bytes) -> None:
        await self._op1(oid, "append", op=OP_APPEND, data=bytes(data))

    async def zero(self, oid: str, off: int, length: int) -> None:
        await self._op1(oid, "zero", op=OP_ZERO, off=off, length=length)

    async def truncate(self, oid: str, size: int) -> None:
        await self._op1(oid, "truncate", op=OP_TRUNCATE, off=size)

    async def create(self, oid: str, exclusive: bool = False) -> None:
        await self._op1(oid, "create", op=OP_CREATE, off=1 if exclusive else 0)

    async def read(self, oid: str, off: int = 0, length: int = 0) -> bytes:
        reply = await self._op1(oid, "read", op=OP_READ, off=off, length=length)
        return reply.data

    async def stat(self, oid: str) -> int:
        return (await self._op1(oid, "stat", op=OP_STAT)).size

    async def remove(self, oid: str) -> None:
        await self._op1(oid, "remove", op=OP_DELETE)

    async def setxattr(self, oid: str, name: str, value: bytes) -> None:
        await self.operate(oid, ObjectOperation().setxattr(name, value))

    async def getxattr(self, oid: str, name: str) -> bytes:
        reply = await self.operate(oid, ObjectOperation().getxattr(name))
        return reply.outs[0][1]

    async def getxattrs(self, oid: str) -> dict[str, bytes]:
        reply = await self.operate(oid, ObjectOperation().getxattrs())
        return reply.outs[0][2]

    async def rmxattr(self, oid: str, name: str) -> None:
        await self.operate(oid, ObjectOperation().rmxattr(name))

    async def omap_set(self, oid: str, kv: dict[str, bytes]) -> None:
        await self.operate(oid, ObjectOperation().omap_set(kv))

    async def omap_get(self, oid: str) -> dict[str, bytes]:
        reply = await self.operate(oid, ObjectOperation().omap_get_vals())
        return reply.outs[0][2]

    async def omap_get_keys(self, oid: str) -> list[str]:
        reply = await self.operate(oid, ObjectOperation().omap_get_keys())
        return sorted(reply.outs[0][2])

    async def omap_get_vals_by_keys(
        self, oid: str, keys: list[str]
    ) -> dict[str, bytes]:
        reply = await self.operate(
            oid, ObjectOperation().omap_get_vals_by_keys(keys)
        )
        return reply.outs[0][2]

    async def omap_rm_keys(self, oid: str, keys: list[str]) -> None:
        await self.operate(oid, ObjectOperation().omap_rm_keys(keys))

    # -- object classes (librados exec / cls dispatch) -----------------

    async def execute(
        self, oid: str, cls: str, method: str, indata: bytes = b""
    ) -> bytes:
        """librados exec(): run an object-class method on the primary."""
        reply = await self.client._submit(self.pool_id, MOSDOp(
            pool=self.pool_id, oid=oid,
            ops=[OSDOp(OP_CALL, name=f"{cls}.{method}", data=bytes(indata))],
        ))
        if reply.outs and reply.outs[0][0] < 0:
            raise RadosError(-reply.outs[0][0], f"exec {cls}.{method}")
        if reply.result != 0:
            raise RadosError(-reply.result, f"exec {cls}.{method}")
        return reply.outs[0][1] if reply.outs else reply.data

    # -- watch / notify (librados watch2/notify2) ----------------------

    async def watch(self, oid: str, callback) -> int:
        """Register a watch; returns the cookie.  ``callback(notify_id,
        payload) -> bytes | None`` runs on every notify."""
        cookie = next(self.client._tids)
        # register BEFORE the op lands: a notify can race the watch
        # reply and must find the callback
        self.client._watches[cookie] = callback
        try:
            await self._op1(oid, "watch", op=OP_WATCH, off=cookie)
        except BaseException:
            self.client._watches.pop(cookie, None)
            raise
        return cookie

    async def unwatch(self, oid: str, cookie: int) -> None:
        self.client._watches.pop(cookie, None)
        await self._op1(oid, "unwatch", op=OP_UNWATCH, off=cookie)

    async def notify(
        self, oid: str, payload: bytes = b"", timeout_ms: int = 5000
    ) -> dict:
        """Returns {"acks": [[entity, cookie, reply bytes]...],
        "timeouts": [[entity, cookie]...]}."""
        import base64
        import json

        reply = await self._op1(
            oid, "notify", op=OP_NOTIFY, data=bytes(payload),
            length=timeout_ms,
        )
        out = json.loads(reply.data.decode()) if reply.data else {
            "acks": [], "timeouts": [],
        }
        out["acks"] = [
            [tuple(e), c, base64.b64decode(r)] for e, c, r in out["acks"]
        ]
        out["timeouts"] = [[tuple(e), c] for e, c in out["timeouts"]]
        return out
