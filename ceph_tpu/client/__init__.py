"""Client access library (reference src/librados + src/osdc)."""

from ceph_tpu.client.rados import IoCtx, RadosClient, RadosError

__all__ = ["IoCtx", "RadosClient", "RadosError"]
