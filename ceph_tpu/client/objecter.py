"""Objecter: the client's async op-submission engine.

Behavioral twin of the reference Objecter (src/osdc/Objecter.cc): the
librados aio_* surface hands ops to a submission engine that keeps
MANY ops in flight at once instead of round-tripping one at a time.
Three coupled mechanisms:

- **Completions** (:class:`Completion`, the librados AioCompletion
  role): ``submit()`` returns immediately after admission; callers
  ``await comp.wait()`` or attach callbacks, so thousands of logical
  clients pipeline over one handle.

- **Per-OSD coalescing**: targeted ops land in a per-primary send
  queue drained by one writer task, which ships up to
  ``objecter_batch_max_ops`` of them as back-to-back wire frames under
  a single send-lock hold (``Connection.send_messages``) — multiple
  ops to the same primary cost one writer wakeup, with no per-op
  await between frames (the reference's out_q per-session batching).

- **Bounded in-flight window** (the reference's
  ``objecter_inflight_ops`` / ``objecter_inflight_op_bytes``
  Throttles): admission blocks the SUBMITTER once the window fills,
  so an open-loop generator cannot OOM the client or bufferbloat the
  wire; completions release the window and wake parked submitters
  FIFO.

Retries, OSDMap waits, tracing and timeouts all stay **per-op**: each
submitted op gets its own driver coroutine owning its deadline,
attempt counter and jittered backoff (``_drive``), so a slow op in a
batch can neither starve its batchmates' resends nor double-charge
their deadlines — the resend-on-new-epoch behavior of the serial
client, now N-wide.
"""

from __future__ import annotations

import asyncio
import errno
import logging
from collections import deque

from ceph_tpu.common.metrics import get_perf_counters
from ceph_tpu.msg.messages import MOSDOp, MOSDOpReply
from ceph_tpu.osd.daemon import object_to_pg

log = logging.getLogger("ceph_tpu.client")

#: per-attempt reply wait bound (the serial client's OP_TIMEOUT role)
ATTEMPT_TIMEOUT = 30.0
#: resend budget per op (the serial client's MAX_RETRIES)
MAX_RETRIES = 25


class Completion:
    """librados AioCompletion: resolved with the MOSDOpReply (or a
    RadosError), awaitable, with done-callbacks."""

    __slots__ = ("_fut", "oid", "submitted_at", "completed_at")

    def __init__(self, loop: asyncio.AbstractEventLoop, oid: str):
        self._fut: asyncio.Future = loop.create_future()
        self.oid = oid
        self.submitted_at = loop.time()
        self.completed_at: float | None = None

    def done(self) -> bool:
        return self._fut.done()

    @property
    def latency(self) -> float | None:
        """submit -> completion seconds (None while in flight)."""
        if self.completed_at is None:
            return None
        return self.completed_at - self.submitted_at

    def add_done_callback(self, cb) -> None:
        """``cb(completion)`` once resolved (immediately if already)."""
        self._fut.add_done_callback(lambda _fut: cb(self))

    async def wait(self) -> MOSDOpReply:
        """Await the reply; raises RadosError on failure."""
        return await asyncio.shield(self._fut)

    def result(self) -> MOSDOpReply:
        return self._fut.result()

    def exception(self) -> BaseException | None:
        return self._fut.exception()

    # -- engine side ---------------------------------------------------

    def _resolve(self, loop, reply=None, exc=None) -> None:
        if self._fut.done():
            return
        self.completed_at = loop.time()
        if exc is not None:
            self._fut.set_exception(exc)
        else:
            self._fut.set_result(reply)


class _OpRec:
    """One submitted op's in-flight state (the Objecter's Op struct):
    deadline/attempt/backoff accounting is HERE, per op, never shared
    with batchmates."""

    __slots__ = ("op", "pool_id", "comp", "deadline", "attempt",
                 "cost", "fut", "span")

    def __init__(self, op: MOSDOp, pool_id: int, comp: Completion,
                 deadline: float, cost: int, span):
        self.op = op
        self.pool_id = pool_id
        self.comp = comp
        self.deadline = deadline
        self.attempt = 0
        self.cost = cost
        self.fut: asyncio.Future | None = None  # current attempt's reply
        self.span = span


class Objecter:
    """The submission engine one RadosClient embeds."""

    def __init__(self, client):
        self.client = client
        conf = client.conf
        self.inflight_ops = conf["objecter_inflight_ops"]
        self.inflight_op_bytes = conf["objecter_inflight_op_bytes"]
        self.batch_max = conf["objecter_batch_max_ops"]
        self.perf = get_perf_counters(f"client.{client.id}.objecter")
        self._inflight = 0
        self._inflight_bytes = 0
        self._admit_waiters: deque[asyncio.Future] = deque()
        self._queues: dict[int, deque[_OpRec]] = {}
        self._writers: dict[int, asyncio.Task] = {}
        self._drivers: set[asyncio.Task] = set()
        self._stopping = False

    # -- window accounting ---------------------------------------------

    @staticmethod
    def _op_cost(op: MOSDOp) -> int:
        return sum(len(o.data) for o in op.ops)

    def _window_full(self, cost: int) -> bool:
        if self._inflight == 0:
            # an op larger than the whole byte budget still runs alone
            return False
        return (self._inflight >= self.inflight_ops
                or self._inflight_bytes + cost > self.inflight_op_bytes)

    async def _admit(self, cost: int, loop) -> None:
        first = True
        while self._window_full(cost):
            fut: asyncio.Future = loop.create_future()
            if first:
                self._admit_waiters.append(fut)
                self.perf.inc("backpressure_waits")
                first = False
            else:
                # re-park at the head: a big op that was woken but
                # still doesn't fit must not be starved by smaller
                # late arrivals overtaking it forever
                self._admit_waiters.appendleft(fut)
            await fut
        self._inflight += 1
        self._inflight_bytes += cost
        self.perf.set_gauge("inflight_ops", self._inflight)
        self.perf.set_gauge("inflight_bytes", self._inflight_bytes)

    def _release(self, rec: _OpRec) -> None:
        self._inflight -= 1
        self._inflight_bytes -= rec.cost
        self.perf.set_gauge("inflight_ops", self._inflight)
        self.perf.set_gauge("inflight_bytes", self._inflight_bytes)
        while self._admit_waiters:
            fut = self._admit_waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                break

    # -- submission ----------------------------------------------------

    async def submit(self, pool_id: int, op: MOSDOp) -> Completion:
        """Admit through the in-flight window (may block — that IS the
        backpressure), open the op's cluster-trace root, and hand it
        to its own driver.  Returns the Completion immediately."""
        from ceph_tpu.client.rados import RadosError

        if self._stopping:
            raise RadosError(errno.ESHUTDOWN, "client shutting down")
        client = self.client
        loop = asyncio.get_running_loop()
        if op.is_write() and not op.reqid:
            # stable across resends (osd_reqid_t): the OSD dedups a
            # retried non-idempotent op by this id
            op.reqid = f"client.{client.id}:{next(client._tids)}"
        cost = self._op_cost(op)
        await self._admit(cost, loop)
        comp = Completion(loop, op.oid)
        span = client.tracer.start_span(
            "client_op", oid=op.oid, pool=pool_id,
            write=op.is_write(), reqid=op.reqid or "aio",
        )
        op.trace = client.tracer.ctx_for(span)
        rec = _OpRec(op, pool_id, comp,
                     loop.time() + client.op_timeout, cost, span)
        self.perf.inc("ops_submitted")
        task = asyncio.ensure_future(self._drive(rec))
        self._drivers.add(task)
        task.add_done_callback(self._drivers.discard)
        return comp

    # -- the per-op driver (op_submit/_calc_target/resend loop) --------

    async def _drive(self, rec: _OpRec) -> None:
        from ceph_tpu.client.rados import RadosError

        client = self.client
        loop = asyncio.get_running_loop()
        op = rec.op
        last_err = errno.EIO
        try:
            while True:
                if loop.time() >= rec.deadline:
                    raise RadosError(
                        errno.ETIMEDOUT,
                        f"op {op.oid!r} timed out after "
                        f"{client.op_timeout}s ({rec.attempt} sends)")
                if rec.attempt >= MAX_RETRIES:
                    raise RadosError(
                        last_err,
                        f"op {op.oid!r} failed after {MAX_RETRIES} tries")
                om = client.osdmap
                pool = om.get_pg_pool(rec.pool_id)
                if pool is None:
                    raise RadosError(
                        errno.ENOENT, f"pool {rec.pool_id} vanished")
                # cache-tier overlay redirect (Objecter::_calc_target
                # read_tier/write_tier) — recomputed every attempt so a
                # retry after an overlay change re-homes
                tier = pool.extra.get(
                    "write_tier" if op.is_write() else "read_tier")
                if tier is not None:
                    tpool = om.get_pg_pool(int(tier))
                    if tpool is not None:
                        pool = tpool
                op.pool = pool.id
                pg = object_to_pg(pool, op.oid)
                _, _, _, primary = om.pg_to_up_acting_osds(pg)
                addr = om.osd_addrs.get(primary) if primary >= 0 else None
                if primary < 0 or addr is None:
                    rec.attempt += 1
                    await client._wait_new_map(om.epoch)
                    continue
                op.tid = next(client._tids)
                op.epoch = om.epoch
                fut: asyncio.Future = loop.create_future()
                client._op_waiters[op.tid] = fut
                rec.fut = fut
                self._enqueue(primary, rec)
                try:
                    reply: MOSDOpReply = await asyncio.wait_for(
                        fut, min(ATTEMPT_TIMEOUT,
                                 max(0.5, rec.deadline - loop.time())))
                except (ConnectionError, OSError,
                        asyncio.TimeoutError) as e:
                    log.debug("objecter: op to osd.%d failed (%r), "
                              "waiting for map", primary, e)
                    rec.attempt += 1
                    # never outwait the op deadline: a partitioned
                    # client must fire ETIMEDOUT on time, not after a
                    # full map-wait round on top of it
                    await client._wait_new_map(
                        om.epoch,
                        timeout=min(10.0, max(
                            0.1, rec.deadline - loop.time())))
                    if (client.osdmap is not None
                            and client.osdmap.epoch <= om.epoch):
                        # no newer map (e.g. primary dead, unreported):
                        # this op backs off on ITS OWN jittered timer
                        await client._backoff(rec.attempt)
                    last_err = errno.EIO
                    continue
                finally:
                    client._op_waiters.pop(op.tid, None)
                    rec.fut = None
                if reply.result == -errno.EAGAIN:
                    # peer had a different map, or the object is
                    # transiently busy: wait for a newer map, else
                    # back off with jitter
                    rec.attempt += 1
                    await client._wait_new_map(
                        min(om.epoch, reply.epoch - 1),
                        timeout=min(10.0, max(
                            0.1, rec.deadline - loop.time())))
                    if client.osdmap.epoch <= om.epoch:
                        await client._backoff(rec.attempt)
                    last_err = errno.EAGAIN
                    continue
                rec.span.tag(result=reply.result)
                client.tracer.finish_span(rec.span)
                self.perf.inc("ops_completed")
                rec.comp._resolve(loop, reply=reply)
                return
        except RadosError as e:
            rec.span.tag(error=e.errno)
            client.tracer.finish_span(rec.span)
            self.perf.inc("ops_failed")
            rec.comp._resolve(loop, exc=e)
        except asyncio.CancelledError:
            client.tracer.finish_span(rec.span)
            rec.comp._resolve(loop, exc=RadosError(
                errno.ESHUTDOWN, f"op {op.oid!r} cancelled"))
            raise
        except Exception as e:  # engine bug: surface it, never hang
            log.exception("objecter: driver crashed for %r", op.oid)
            client.tracer.finish_span(rec.span)
            rec.comp._resolve(loop, exc=RadosError(
                errno.EIO, f"op {op.oid!r} driver error: {e!r}"))
        finally:
            self._release(rec)

    # -- per-OSD coalescing writers ------------------------------------

    def _enqueue(self, osd: int, rec: _OpRec) -> None:
        self._queues.setdefault(osd, deque()).append(rec)
        t = self._writers.get(osd)
        if t is None or t.done():
            self._writers[osd] = asyncio.ensure_future(
                self._writer_loop(osd))

    async def _writer_loop(self, osd: int) -> None:
        """Drain osd's queue in bursts: ops queued while a burst is on
        the wire ride the next one (no barrier — the queue refills
        during the await and the loop re-checks).  Exit when empty;
        single-threaded asyncio makes the empty-check/exit atomic."""
        client = self.client
        q = self._queues[osd]
        try:
            while q:
                batch: list[_OpRec] = []
                while q and len(batch) < self.batch_max:
                    rec = q.popleft()
                    # an op whose attempt already failed/timed out is
                    # being re-driven; don't send a zombie frame
                    if rec.fut is not None and not rec.fut.done():
                        batch.append(rec)
                if not batch:
                    continue
                try:
                    om = client.osdmap
                    addr = om.osd_addrs.get(osd) if om else None
                    if addr is None:
                        raise ConnectionError(
                            f"osd.{osd} has no address in current map")
                    conn = await client.messenger.connect_to(
                        ("osd", osd), *addr)
                    await conn.send_messages([r.op for r in batch])
                except (ConnectionError, OSError) as e:
                    for r in batch:
                        if r.fut is not None and not r.fut.done():
                            r.fut.set_exception(ConnectionError(str(e)))
                    continue
                self.perf.inc("wire_bursts")
                self.perf.inc("ops_sent", len(batch))
                if len(batch) > 1:
                    self.perf.inc("coalesced_ops", len(batch))
        finally:
            self._writers.pop(osd, None)

    # -- reply intake / lifecycle --------------------------------------

    def dump(self) -> dict:
        """Engine introspection (perf counters + live window)."""
        return {
            "inflight_ops": self._inflight,
            "inflight_bytes": self._inflight_bytes,
            "admit_waiters": len(self._admit_waiters),
            "queued": {
                str(osd): len(q)
                for osd, q in self._queues.items() if q
            },
            "perf": self.perf.dump(),
        }

    async def shutdown(self) -> None:
        self._stopping = True
        for t in list(self._writers.values()):
            t.cancel()
        for t in list(self._drivers):
            t.cancel()
        if self._drivers:
            await asyncio.gather(*self._drivers, return_exceptions=True)
