"""Client-side striping — the Striper/libradosstriper twin.

The reference maps a logical byte stream onto RADOS objects with a
RAID0-style layout (src/osdc/Striper.cc file_to_extents: stripe_unit
bytes round-robin across stripe_count objects, object_size bytes per
object before moving to the next object set; libradosstriper stores
the logical size in an xattr of the first object).  Same math here,
issued as parallel IoCtx ops.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

SIZE_XATTR = "striper.size"


@dataclass(frozen=True)
class Layout:
    """file_layout_t (src/include/fs_types.h): all byte counts."""

    stripe_unit: int = 65536
    stripe_count: int = 4
    object_size: int = 4 * 2**20

    def __post_init__(self):
        assert self.object_size % self.stripe_unit == 0
        assert self.stripe_unit > 0 and self.stripe_count > 0


def file_to_extents(
    layout: Layout, off: int, length: int
) -> list[tuple[int, int, int]]:
    """Striper::file_to_extents (Striper.cc:47): logical [off, off+len)
    -> [(object_no, object_off, len)] runs, in logical order."""
    su, sc, osz = layout.stripe_unit, layout.stripe_count, layout.object_size
    stripes_per_object = osz // su
    out: list[tuple[int, int, int]] = []
    pos = off
    end = off + length
    while pos < end:
        blockno = pos // su           # which stripe_unit block
        stripeno = blockno // sc      # which stripe (row)
        stripepos = blockno % sc      # which object column
        objectsetno = stripeno // stripes_per_object
        objectno = objectsetno * sc + stripepos
        block_off = pos % su
        obj_off = (stripeno % stripes_per_object) * su + block_off
        n = min(su - block_off, end - pos)
        if out and out[-1][0] == objectno and (
            out[-1][1] + out[-1][2] == obj_off
        ):
            out[-1] = (objectno, out[-1][1], out[-1][2] + n)
        else:
            out.append((objectno, obj_off, n))
        pos += n
    return out


class StripedObject:
    """A logically-striped byte stream over one pool
    (libradosstriper::RadosStriper surface: write/read/trunc/stat)."""

    def __init__(self, ioctx, name: str, layout: Layout | None = None):
        self.io = ioctx
        self.name = name
        self.layout = layout or Layout()

    def _oid(self, objectno: int) -> str:
        return f"{self.name}.{objectno:016x}"

    async def size(self) -> int:
        import errno as _e

        try:
            raw = await self.io.getxattr(self._oid(0), SIZE_XATTR)
            return int(raw)
        except OSError as err:
            if err.errno in (_e.ENOENT, _e.ENODATA):
                return 0  # never written
            raise  # a transient error must NOT read as "empty file" —
            # the next write would shrink the logical size over live data

    async def _set_size(self, size: int) -> None:
        await self.io.setxattr(self._oid(0), SIZE_XATTR, str(size).encode())

    async def write(self, off: int, data: bytes) -> None:
        extents = file_to_extents(self.layout, off, len(data))
        pos = 0
        writes = []
        for objectno, obj_off, n in extents:
            writes.append(self.io.write(
                self._oid(objectno), data[pos : pos + n], off=obj_off
            ))
            pos += n
        await asyncio.gather(*writes)
        cur = await self.size()
        if off + len(data) > cur:
            await self._set_size(off + len(data))

    async def read(self, off: int = 0, length: int = 0) -> bytes:
        size = await self.size()
        end = size if length == 0 else min(off + length, size)
        if off >= end:
            return b""
        extents = file_to_extents(self.layout, off, end - off)

        async def _read_one(objectno: int, obj_off: int, n: int) -> bytes:
            try:
                chunk = await self.io.read(
                    self._oid(objectno), off=obj_off, length=n
                )
            except OSError as e:
                import errno as _e

                if e.errno == _e.ENOENT:
                    chunk = b""  # sparse hole
                else:
                    raise
            return chunk.ljust(n, b"\0")  # short object => zeros

        parts = await asyncio.gather(*(
            _read_one(*ext) for ext in extents
        ))
        return b"".join(parts)

    async def truncate(self, size: int) -> None:
        cur = await self.size()
        if size < cur:
            # drop whole objects past the end, trim the boundary object
            old_extents = file_to_extents(self.layout, 0, cur)
            live: dict[int, int] = {}
            if size > 0:
                for objectno, obj_off, n in file_to_extents(self.layout, 0, size):
                    live[objectno] = max(live.get(objectno, 0), obj_off + n)
            ops = []
            for objectno, _o, _n in old_extents:
                if objectno not in live:
                    ops.append(self._remove_quiet(self._oid(objectno)))
            for objectno, keep in live.items():
                ops.append(self.io.truncate(self._oid(objectno), keep))
            await asyncio.gather(*ops)
        await self._set_size(size)

    async def _remove_quiet(self, oid: str) -> None:
        import errno as _e

        try:
            await self.io.remove(oid)
        except OSError as err:
            if err.errno != _e.ENOENT:
                raise

    async def remove(self) -> None:
        size = await self.size()
        seen = {0}
        ops = [self._remove_quiet(self._oid(0))]
        for objectno, _o, _n in file_to_extents(self.layout, 0, max(size, 1)):
            if objectno not in seen:
                seen.add(objectno)
                ops.append(self._remove_quiet(self._oid(objectno)))
        await asyncio.gather(*ops)
