"""S3 REST frontend — asyncio HTTP server + op dispatch.

Twin of the reference's beast/asio frontend (rgw_asio_frontend.cc) and
the REST op dispatch in rgw_op.cc / rgw_rest_s3.cc, for path-style S3:

    GET    /                       ListBuckets
    PUT    /bucket                 CreateBucket
    DELETE /bucket                 DeleteBucket
    GET    /bucket?list-type=2     ListObjectsV2
    GET    /bucket?uploads         ListMultipartUploads (stub: empty)
    POST   /bucket?delete          DeleteObjects (batch)
    PUT    /bucket/key             PutObject | UploadPart (partNumber&uploadId)
                                   | CopyObject (x-amz-copy-source)
    GET    /bucket/key             GetObject (Range) | ListParts (uploadId)
    HEAD   /bucket/key             HeadObject
    DELETE /bucket/key             DeleteObject | AbortMultipart (uploadId)
    POST   /bucket/key?uploads     CreateMultipartUpload
    POST   /bucket/key?uploadId=X  CompleteMultipartUpload

Every request is SigV4-authenticated against the user records in the
store (rgw_auth_s3.cc) — header auth or presigned query auth — and
x-amz-meta-* user metadata round-trips through put/copy/get/head;
errors render as S3 XML error bodies.
"""

from __future__ import annotations

import asyncio
import logging
import urllib.parse
import xml.etree.ElementTree as ET

from . import sigv4
from .store import RGWError, RGWStore, entag_strip

log = logging.getLogger("ceph_tpu.rgw")

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
MAX_BODY = 5 * 2**30


class _HTTPRequest:
    def __init__(self, method, path, query, headers, body):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers  # lowercased keys
        self.body = body
        self.params = dict(urllib.parse.parse_qsl(
            query, keep_blank_values=True))
        self.uid = None  # set by auth


def _xml(tag: str, *children, text: str | None = None) -> ET.Element:
    el = ET.Element(tag)
    if text is not None:
        el.text = text
    for c in children:
        el.append(c)
    return el


def _render(root: ET.Element) -> bytes:
    root.set("xmlns", XMLNS)
    return (
        b'<?xml version="1.0" encoding="UTF-8"?>'
        + ET.tostring(root, encoding="utf-8")
    )


_STATUS = {
    200: "OK", 204: "No Content", 206: "Partial Content",
    400: "Bad Request", 403: "Forbidden", 404: "Not Found",
    405: "Method Not Allowed", 409: "Conflict",
    416: "Range Not Satisfiable", 500: "Internal Server Error",
    501: "Not Implemented",
}


class S3Frontend:
    def __init__(self, store: RGWStore, host: str = "127.0.0.1",
                 port: int = 0, conf=None):
        self.store = store
        self.host, self.port = host, port
        self._server: asyncio.AbstractServer | None = None
        # mgr report stream: the MgrMap rides the store's rados
        # session (mon subscription); reports dial out over the same
        # client messenger — rgw has no daemon messenger of its own
        from ceph_tpu.common import ConfigProxy, get_perf_counters
        from ceph_tpu.common.tracing import Tracer
        from ceph_tpu.mgr.client import MgrClient

        self.conf = conf if conf is not None else ConfigProxy()
        self.perf = get_perf_counters("rgw.main")
        self.tracer = Tracer(
            "rgw.main",
            ring_max=self.conf["trace_ring_max"],
            sample_rate=self.conf["trace_sample_rate"],
            tail_slow_s=(self.conf["trace_tail_slow_s"] or None),
        )
        self._admin = None
        rados = store.meta.client
        self.mgr_client = MgrClient(
            "rgw.main", rados.messenger, self.conf,
            self._mgr_collect, tracers=(self.tracer,))
        self._rados = rados

    def _mgr_collect(self) -> dict:
        return {
            "counters": self.perf.dump(),
            "status": {"frontend": f"{self.host}:{self.port}"},
        }

    @property
    def addr(self) -> tuple[str, int]:
        return (self.host, self.port)

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        sock_path = self.conf["admin_socket"]
        if sock_path:
            from ceph_tpu.common import AdminSocket

            self._admin = AdminSocket(sock_path.replace("$id", "rgw.main"))
            self._admin.register(
                "dump_traces", "recent spans (blkin/otel role)",
                lambda cmd: self.tracer.dump(),
            )
            self._admin.register(
                "perf dump", "dump perf counters",
                lambda cmd: self.perf.dump(),
            )
            self._admin.register(
                "status", "daemon status",
                lambda cmd: {"frontend": f"{self.host}:{self.port}"},
            )
            await self._admin.start()
        self._rados.set_mgr_map_listener(self.mgr_client.handle_mgr_map)
        self.mgr_client.start()
        log.info("rgw: listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        await self.mgr_client.stop()
        if self._admin is not None:
            await self._admin.stop()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- HTTP plumbing -------------------------------------------------

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                with self.tracer.span(
                    "rgw_req", method=req.method, path=req.path,
                ) as sp:
                    status, headers, body = await self._handle(req)
                    sp.tag(status=status)
                self.perf.inc("req")
                if status >= 400:
                    self.perf.inc("req_err")
                await self._respond(writer, status, headers, body,
                                    head_only=req.method == "HEAD")
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _read_request(self, reader) -> _HTTPRequest | None:
        try:
            line = await reader.readline()
        except (ConnectionError, OSError):
            return None
        if not line:
            return None
        try:
            method, target, _version = line.decode().split()
        except ValueError:
            return None
        headers: dict[str, str] = {}
        while True:
            hline = await reader.readline()
            if hline in (b"\r\n", b"\n", b""):
                break
            name, _, val = hline.decode().partition(":")
            headers[name.strip().lower()] = val.strip()
        try:
            length = int(headers.get("content-length", "0"))
        except ValueError:
            return None
        if length > MAX_BODY or length < 0:
            return None
        body = await reader.readexactly(length) if length else b""
        parsed = urllib.parse.urlsplit(target)
        return _HTTPRequest(method.upper(), parsed.path, parsed.query,
                            headers, body)

    async def _respond(self, writer, status: int, headers: dict, body: bytes,
                       head_only: bool = False) -> None:
        headers.setdefault("content-length", str(len(body)))
        lines = [f"HTTP/1.1 {status} {_STATUS.get(status, '?')}\r\n"]
        lines += [f"{k}: {v}\r\n" for k, v in headers.items()]
        lines.append("\r\n")
        writer.write("".join(lines).encode())
        if body and not head_only:
            writer.write(body)
        await writer.drain()

    # -- auth + dispatch -----------------------------------------------

    def _error(self, e: RGWError) -> tuple[int, dict, bytes]:
        body = _render(_xml(
            "Error",
            _xml("Code", text=e.code),
            _xml("Message", text=str(e)),
        ))
        return e.status, {"content-type": "application/xml"}, body

    async def _authenticate(self, req: _HTTPRequest) -> None:
        auth_hdr = req.headers.get("authorization", "")
        try:
            if not auth_hdr and "X-Amz-Signature" in req.params:
                # presigned URL: auth rides the query string
                parsed = sigv4.parse_presigned_query(req.query)
                user = await self.store.get_user_by_access_key(
                    parsed.access_key)
                if user is None:
                    raise RGWError(
                        "InvalidAccessKeyId", 403, parsed.access_key)
                sigv4.verify_presigned(
                    req.method, req.path, req.query, req.headers,
                    user["secret_key"])
            elif auth_hdr:
                parsed = sigv4.parse_authorization(auth_hdr)
                user = await self.store.get_user_by_access_key(
                    parsed.access_key)
                if user is None:
                    raise RGWError(
                        "InvalidAccessKeyId", 403, parsed.access_key)
                sigv4.verify(req.method, req.path, req.query, req.headers,
                             req.body, user["secret_key"])
            else:
                raise RGWError("AccessDenied", 403,
                               "anonymous access denied")
        except sigv4.SigV4Error as e:
            raise RGWError(e.code, 403, str(e))
        req.uid = user["uid"]

    async def _handle(self, req: _HTTPRequest) -> tuple[int, dict, bytes]:
        try:
            await self._authenticate(req)
            parts = req.path.lstrip("/").split("/", 1)
            bucket_name = urllib.parse.unquote(parts[0])
            key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
            if not bucket_name:
                return await self._service(req)
            if not key:
                return await self._bucket(req, bucket_name)
            return await self._object(req, bucket_name, key)
        except RGWError as e:
            return self._error(e)
        except Exception:
            log.exception("rgw: internal error on %s %s", req.method, req.path)
            return self._error(RGWError("InternalError", 500, "internal"))

    # -- service ops ----------------------------------------------------

    async def _service(self, req) -> tuple[int, dict, bytes]:
        if req.method != "GET":
            raise RGWError("MethodNotAllowed", 405, req.method)
        buckets = await self.store.list_buckets(req.uid)
        root = _xml(
            "ListAllMyBucketsResult",
            _xml("Owner", _xml("ID", text=req.uid)),
            _xml("Buckets", *[
                _xml("Bucket",
                     _xml("Name", text=b["name"]),
                     _xml("CreationDate", text=b["created"]))
                for b in buckets
            ]),
        )
        return 200, {"content-type": "application/xml"}, _render(root)

    # -- bucket ops ------------------------------------------------------

    async def _bucket(self, req, name: str) -> tuple[int, dict, bytes]:
        if req.method == "PUT":
            if "versioning" in req.params:
                status = _xml_find_text(req.body, "Status")
                if status is None:
                    raise RGWError("MalformedXML", 400,
                                   "Status required")
                await self.store.set_bucket_versioning(name, status)
                return 200, {}, b""
            if "lifecycle" in req.params:
                rules = _parse_lifecycle_xml(req.body)
                await self.store.set_lifecycle(name, rules)
                return 200, {}, b""
            placement = req.headers.get("x-rgw-placement")  # extension
            await self.store.create_bucket(name, req.uid, placement)
            return 200, {"location": f"/{name}"}, b""
        if req.method == "DELETE" and "lifecycle" in req.params:
            await self.store.delete_lifecycle(name)
            return 204, {}, b""
        if req.method == "DELETE":
            await self.store.delete_bucket(name, req.uid)
            return 204, {}, b""
        if req.method == "HEAD":
            await self.store.get_bucket(name)
            return 200, {}, b""
        if req.method == "GET":
            bucket = await self.store.get_bucket(name)
            if "uploads" in req.params:
                root = _xml("ListMultipartUploadsResult",
                            _xml("Bucket", text=name))
                return 200, {"content-type": "application/xml"}, _render(root)
            if "versioning" in req.params:
                status = self.store.versioning_of(bucket)
                kids = []
                if status != "Off":
                    kids.append(_xml("Status", text=status))
                root = _xml("VersioningConfiguration", *kids)
                return 200, {"content-type": "application/xml"}, _render(root)
            if "versions" in req.params:
                return await self._list_versions(req, bucket)
            if "lifecycle" in req.params:
                rules = await self.store.get_lifecycle(name)
                root = _xml("LifecycleConfiguration", *[
                    _rule_to_xml(r) for r in rules])
                return 200, {"content-type": "application/xml"}, _render(root)
            return await self._list_objects_v2(req, bucket)
        if req.method == "POST" and "delete" in req.params:
            return await self._batch_delete(req, name)
        raise RGWError("MethodNotAllowed", 405, req.method)

    async def _batch_delete(self, req, name: str) -> tuple[int, dict, bytes]:
        """POST /bucket?delete — DeleteObjects (RGWDeleteMultiObj,
        rgw_op.cc): up to 1000 keys per request, per-key outcome."""
        bucket = await self.store.get_bucket(name)
        try:
            root = ET.fromstring(req.body)
        except ET.ParseError:
            raise RGWError("MalformedXML", 400, "bad Delete body")
        quiet = any(
            c.tag.endswith("Quiet") and (c.text or "").lower() == "true"
            for c in root)
        keys = []
        for obj in root:
            if not obj.tag.endswith("Object"):
                continue
            for child in obj:
                if child.tag.endswith("Key") and child.text:
                    keys.append(child.text)
        if len(keys) > 1000:
            raise RGWError("MalformedXML", 400, "over 1000 keys")
        out = _xml("DeleteResult")
        for key in keys:
            try:
                await self.store.delete_object(bucket, key)
                if not quiet:
                    out.append(_xml("Deleted", _xml("Key", text=key)))
            except RGWError as e:
                out.append(_xml(
                    "Error", _xml("Key", text=key),
                    _xml("Code", text=e.code),
                ))
        return 200, {"content-type": "application/xml"}, _render(out)

    async def _list_versions(self, req, bucket) -> tuple[int, dict, bytes]:
        prefix = req.params.get("prefix", "")
        key_marker = req.params.get("key-marker", "")
        max_keys = _int_param(req.params.get("max-keys", "1000"), "max-keys")
        res = await self.store.list_object_versions(
            bucket, prefix=prefix, key_marker=key_marker,
            max_keys=max_keys)
        children = [
            _xml("Name", text=bucket["name"]),
            _xml("Prefix", text=prefix),
            _xml("MaxKeys", text=str(max_keys)),
            _xml("IsTruncated",
                 text="true" if res["truncated"] else "false"),
        ]
        for rec in res["entries"]:
            tag = ("DeleteMarker" if rec.get("delete_marker")
                   else "Version")
            kids = [
                _xml("Key", text=rec["key"]),
                _xml("VersionId", text=rec["vid"]),
                _xml("IsLatest",
                     text="true" if rec["is_latest"] else "false"),
                _xml("LastModified", text=rec.get("mtime", "")),
            ]
            if tag == "Version":
                kids += [
                    _xml("ETag", text=f"\"{rec.get('etag', '')}\""),
                    _xml("Size", text=str(rec.get("size", 0))),
                ]
            children.append(_xml(tag, *kids))
        root = _xml("ListVersionsResult", *children)
        return 200, {"content-type": "application/xml"}, _render(root)

    async def _list_objects_v2(self, req, bucket) -> tuple[int, dict, bytes]:
        prefix = req.params.get("prefix", "")
        delimiter = req.params.get("delimiter", "")
        max_keys = _int_param(req.params.get("max-keys", "1000"), "max-keys")
        token = req.params.get("continuation-token", "")
        start_after = req.params.get("start-after", "")
        marker = token or start_after
        res = await self.store.list_objects(
            bucket, prefix=prefix, delimiter=delimiter,
            marker=marker, max_keys=max_keys)
        children = [
            _xml("Name", text=bucket["name"]),
            _xml("Prefix", text=prefix),
            _xml("KeyCount", text=str(
                len(res["entries"]) + len(res["common_prefixes"]))),
            _xml("MaxKeys", text=str(max_keys)),
            _xml("IsTruncated", text="true" if res["truncated"] else "false"),
        ]
        if res["truncated"]:
            children.append(
                _xml("NextContinuationToken", text=res["next_marker"]))
        for key, meta in res["entries"]:
            children.append(_xml(
                "Contents",
                _xml("Key", text=key),
                _xml("LastModified", text=meta.get("mtime", "")),
                _xml("ETag", text=f"\"{meta.get('etag', '')}\""),
                _xml("Size", text=str(meta.get("size", 0))),
            ))
        for cp in res["common_prefixes"]:
            children.append(_xml("CommonPrefixes", _xml("Prefix", text=cp)))
        root = _xml("ListBucketResult", *children)
        return 200, {"content-type": "application/xml"}, _render(root)

    # -- object ops ------------------------------------------------------

    async def _object(self, req, bucket_name: str, key: str):
        bucket = await self.store.get_bucket(bucket_name)
        if req.method == "PUT":
            if "partnumber" in {k.lower() for k in req.params}:
                return await self._upload_part(req, bucket, key)
            if "x-amz-copy-source" in req.headers:
                return await self._copy_object(req, bucket, key)
            ct = req.headers.get("content-type", "binary/octet-stream")
            meta = await self.store.put_object(
                bucket, key, req.body, ct,
                user_meta=_user_meta_headers(req.headers))
            hdrs = {"etag": f"\"{meta['etag']}\""}
            if "version_id" in meta:
                hdrs["x-amz-version-id"] = meta["version_id"]
            return 200, hdrs, b""
        if req.method == "POST":
            if "uploads" in req.params:
                ct = req.headers.get("content-type", "binary/octet-stream")
                upload_id = await self.store.initiate_multipart(bucket, key, ct)
                root = _xml(
                    "InitiateMultipartUploadResult",
                    _xml("Bucket", text=bucket_name),
                    _xml("Key", text=key),
                    _xml("UploadId", text=upload_id),
                )
                return 200, {"content-type": "application/xml"}, _render(root)
            if "uploadId" in req.params:
                return await self._complete_multipart(req, bucket, key)
            raise RGWError("MethodNotAllowed", 405, "POST")
        if req.method in ("GET", "HEAD"):
            if "uploadId" in req.params and req.method == "GET":
                parts = await self.store.list_parts(
                    bucket, key, req.params["uploadId"])
                root = _xml(
                    "ListPartsResult",
                    _xml("Bucket", text=bucket_name),
                    _xml("Key", text=key),
                    _xml("UploadId", text=req.params["uploadId"]),
                    *[_xml("Part",
                           _xml("PartNumber", text=str(p["part_number"])),
                           _xml("ETag", text=f"\"{p['etag']}\""),
                           _xml("Size", text=str(p["size"])))
                      for p in parts],
                )
                return 200, {"content-type": "application/xml"}, _render(root)
            return await self._get_object(req, bucket, key)
        if req.method == "DELETE":
            if "uploadId" in req.params:
                await self.store.abort_multipart(
                    bucket, key, req.params["uploadId"])
                return 204, {}, b""
            out = await self.store.delete_object(
                bucket, key, version_id=req.params.get("versionId"))
            hdrs = {}
            if out.get("version_id"):
                hdrs["x-amz-version-id"] = out["version_id"]
            if out.get("delete_marker"):
                hdrs["x-amz-delete-marker"] = "true"
            return 204, hdrs, b""
        raise RGWError("MethodNotAllowed", 405, req.method)

    async def _get_object(self, req, bucket, key):
        rng = req.headers.get("range", "")
        vid = req.params.get("versionId")
        meta = await self.store.head_object(bucket, key, version_id=vid)
        size = meta["size"]
        status = 200
        off, length = 0, None
        resp_headers = {}
        if "version_id" in meta:
            resp_headers["x-amz-version-id"] = meta["version_id"]
        if rng:
            off, end_incl = _parse_range(rng, size)
            length = end_incl - off + 1
            status = 206
            resp_headers["content-range"] = f"bytes {off}-{end_incl}/{size}"
        if req.method == "HEAD":
            body = b""
            resp_headers["content-length"] = str(
                length if length is not None else size)
        else:
            _meta, body = await self.store.get_object(
                bucket, key, off, length, version_id=vid)
        resp_headers.update({
            "etag": f"\"{meta['etag']}\"",
            "last-modified": meta.get("mtime", ""),
            "content-type": meta.get("content_type", "binary/octet-stream"),
            "accept-ranges": "bytes",
        })
        for k, v in meta.get("user_meta", {}).items():
            resp_headers[f"x-amz-meta-{k}"] = v
        return status, resp_headers, body

    async def _copy_object(self, req, bucket, key):
        """PUT with x-amz-copy-source (RGWCopyObj, rgw_op.cc): server-
        side copy, metadata COPY by default or REPLACE per the
        x-amz-metadata-directive header."""
        src = urllib.parse.unquote(req.headers["x-amz-copy-source"])
        src = src.lstrip("/")
        if "/" not in src:
            raise RGWError("InvalidArgument", 400, "bad copy source")
        src_bucket_name, src_key = src.split("/", 1)
        src_bucket = await self.store.get_bucket(src_bucket_name)
        try:
            src_meta, data = await self.store.get_object(
                src_bucket, src_key)
        except RGWError as e:
            if e.code == "NoSuchKey":
                raise RGWError("NoSuchKey", 404, src)
            raise
        directive = req.headers.get(
            "x-amz-metadata-directive", "COPY").upper()
        if directive == "REPLACE":
            ct = req.headers.get("content-type", "binary/octet-stream")
            um = _user_meta_headers(req.headers)
        else:
            ct = src_meta.get("content_type", "binary/octet-stream")
            um = src_meta.get("user_meta", {})
        meta = await self.store.put_object(
            bucket, key, data, ct, user_meta=um)
        out = _xml(
            "CopyObjectResult",
            _xml("ETag", text=f"\"{meta['etag']}\""),
            _xml("LastModified", text=meta["mtime"]),
        )
        return 200, {"content-type": "application/xml"}, _render(out)

    async def _upload_part(self, req, bucket, key):
        params = {k.lower(): v for k, v in req.params.items()}
        upload_id = params.get("uploadid")
        if not upload_id:
            raise RGWError("InvalidArgument", 400, "uploadId required")
        part_num = _int_param(params.get("partnumber", "0"), "partNumber")
        if "x-amz-copy-source" in req.headers:
            # UploadPartCopy (RGWCopyObj in multipart mode): the part
            # body comes from an existing object, optionally ranged
            src = urllib.parse.unquote(
                req.headers["x-amz-copy-source"]).lstrip("/")
            if "/" not in src:
                raise RGWError("InvalidArgument", 400, "bad copy source")
            src_bucket_name, src_key = src.split("/", 1)
            src_bucket = await self.store.get_bucket(src_bucket_name)
            src_meta = await self.store.head_object(src_bucket, src_key)
            off, length = 0, None
            crange = req.headers.get("x-amz-copy-source-range", "")
            if crange:
                off, end_incl = _parse_range(crange, src_meta["size"])
                length = end_incl - off + 1
            _m, data = await self.store.get_object(
                src_bucket, src_key, off, length)
            etag = await self.store.upload_part(
                bucket, key, upload_id, part_num, data)
            out = _xml(
                "CopyPartResult",
                _xml("ETag", text=f"\"{etag}\""),
                _xml("LastModified", text=src_meta["mtime"]),
            )
            return 200, {"content-type": "application/xml"}, _render(out)
        etag = await self.store.upload_part(
            bucket, key, upload_id, part_num, req.body)
        return 200, {"etag": f"\"{etag}\""}, b""

    async def _complete_multipart(self, req, bucket, key):
        upload_id = req.params["uploadId"]
        try:
            root = ET.fromstring(req.body)
        except ET.ParseError:
            raise RGWError("MalformedXML", 400, "bad CompleteMultipartUpload")
        parts: list[tuple[int, str]] = []
        for part in root:
            if not part.tag.endswith("Part"):
                continue
            pn = etag = None
            for child in part:
                if child.tag.endswith("PartNumber"):
                    try:
                        pn = int(child.text)
                    except (TypeError, ValueError):
                        raise RGWError("MalformedXML", 400, "bad PartNumber")
                elif child.tag.endswith("ETag"):
                    etag = entag_strip(child.text or "")
            if pn is None or etag is None:
                raise RGWError("MalformedXML", 400, "Part missing fields")
            parts.append((pn, etag))
        meta = await self.store.complete_multipart(bucket, key, upload_id, parts)
        out = _xml(
            "CompleteMultipartUploadResult",
            _xml("Bucket", text=bucket["name"]),
            _xml("Key", text=key),
            _xml("ETag", text=f"\"{meta['etag']}\""),
        )
        return 200, {"content-type": "application/xml"}, _render(out)


def _strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _xml_find_text(body: bytes, tag: str) -> str | None:
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise RGWError("MalformedXML", 400, "bad XML body")
    for el in root.iter():
        if _strip_ns(el.tag) == tag:
            return (el.text or "").strip()
    return None


def _parse_lifecycle_xml(body: bytes) -> list[dict]:
    """<LifecycleConfiguration><Rule>... -> [{id, prefix, status,
    days?, noncurrent_days?}] (the slice of rgw_lc.cc's rule model the
    lite worker executes)."""
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise RGWError("MalformedXML", 400, "bad lifecycle XML")
    rules = []
    for rel in root:
        if _strip_ns(rel.tag) != "Rule":
            continue
        rule: dict = {"status": "Enabled", "prefix": ""}
        for el in rel:
            t = _strip_ns(el.tag)
            if t == "ID":
                rule["id"] = (el.text or "").strip()
            elif t == "Status":
                rule["status"] = (el.text or "Enabled").strip()
            elif t == "Prefix":
                rule["prefix"] = (el.text or "").strip()
            elif t == "Filter":
                for f in el.iter():
                    if _strip_ns(f.tag) == "Prefix":
                        rule["prefix"] = (f.text or "").strip()
            elif t == "Expiration":
                for d in el:
                    if _strip_ns(d.tag) == "Days":
                        rule["days"] = int(d.text or "0")
            elif t == "NoncurrentVersionExpiration":
                for d in el:
                    if _strip_ns(d.tag) == "NoncurrentDays":
                        rule["noncurrent_days"] = int(d.text or "0")
        rules.append(rule)
    if not rules:
        raise RGWError("MalformedXML", 400, "no rules")
    return rules


def _rule_to_xml(rule: dict) -> ET.Element:
    kids = [
        _xml("ID", text=rule.get("id", "")),
        _xml("Prefix", text=rule.get("prefix", "")),
        _xml("Status", text=rule.get("status", "Enabled")),
    ]
    if "days" in rule:
        kids.append(_xml("Expiration",
                         _xml("Days", text=str(rule["days"]))))
    if "noncurrent_days" in rule:
        kids.append(_xml(
            "NoncurrentVersionExpiration",
            _xml("NoncurrentDays", text=str(rule["noncurrent_days"]))))
    return _xml("Rule", *kids)


def _user_meta_headers(headers: dict[str, str]) -> dict[str, str]:
    """x-amz-meta-* request headers -> the user-metadata dict stored
    alongside the object (RGW_ATTR_META_PREFIX role)."""
    return {
        k[len("x-amz-meta-"):]: v
        for k, v in headers.items() if k.startswith("x-amz-meta-")
    }


def _int_param(value: str, name: str) -> int:
    try:
        return int(value)
    except ValueError:
        raise RGWError("InvalidArgument", 400, f"bad {name}: {value!r}")


def _parse_range(value: str, size: int) -> tuple[int, int]:
    """'bytes=a-b' (also 'a-' and '-suffix') -> (first, last) inclusive."""
    if not value.startswith("bytes="):
        raise RGWError("InvalidRange", 416, value)
    spec = value[len("bytes="):].split(",")[0].strip()
    first_s, _, last_s = spec.partition("-")
    try:
        if first_s == "":           # suffix: last N bytes
            n = int(last_s)
            if n <= 0 or size == 0:
                raise ValueError
            return max(0, size - n), size - 1
        first = int(first_s)
        last = int(last_s) if last_s else size - 1
    except ValueError:
        raise RGWError("InvalidRange", 416, value)
    if first >= size or first > last:
        raise RGWError("InvalidRange", 416, value)
    return first, min(last, size - 1)
