"""S3 REST frontend — asyncio HTTP server + op dispatch.

Twin of the reference's beast/asio frontend (rgw_asio_frontend.cc) and
the REST op dispatch in rgw_op.cc / rgw_rest_s3.cc, for path-style S3:

    GET    /                       ListBuckets
    PUT    /bucket                 CreateBucket
    DELETE /bucket                 DeleteBucket
    GET    /bucket?list-type=2     ListObjectsV2
    GET    /bucket?uploads         ListMultipartUploads (stub: empty)
    PUT    /bucket/key             PutObject | UploadPart (partNumber&uploadId)
    GET    /bucket/key             GetObject (Range) | ListParts (uploadId)
    HEAD   /bucket/key             HeadObject
    DELETE /bucket/key             DeleteObject | AbortMultipart (uploadId)
    POST   /bucket/key?uploads     CreateMultipartUpload
    POST   /bucket/key?uploadId=X  CompleteMultipartUpload

Every request is SigV4-authenticated against the user records in the
store (rgw_auth_s3.cc); errors render as S3 XML error bodies.
"""

from __future__ import annotations

import asyncio
import logging
import urllib.parse
import xml.etree.ElementTree as ET

from . import sigv4
from .store import RGWError, RGWStore, entag_strip

log = logging.getLogger("ceph_tpu.rgw")

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
MAX_BODY = 5 * 2**30


class _HTTPRequest:
    def __init__(self, method, path, query, headers, body):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers  # lowercased keys
        self.body = body
        self.params = dict(urllib.parse.parse_qsl(
            query, keep_blank_values=True))
        self.uid = None  # set by auth


def _xml(tag: str, *children, text: str | None = None) -> ET.Element:
    el = ET.Element(tag)
    if text is not None:
        el.text = text
    for c in children:
        el.append(c)
    return el


def _render(root: ET.Element) -> bytes:
    root.set("xmlns", XMLNS)
    return (
        b'<?xml version="1.0" encoding="UTF-8"?>'
        + ET.tostring(root, encoding="utf-8")
    )


_STATUS = {
    200: "OK", 204: "No Content", 206: "Partial Content",
    400: "Bad Request", 403: "Forbidden", 404: "Not Found",
    405: "Method Not Allowed", 409: "Conflict",
    416: "Range Not Satisfiable", 500: "Internal Server Error",
    501: "Not Implemented",
}


class S3Frontend:
    def __init__(self, store: RGWStore, host: str = "127.0.0.1", port: int = 0):
        self.store = store
        self.host, self.port = host, port
        self._server: asyncio.AbstractServer | None = None

    @property
    def addr(self) -> tuple[str, int]:
        return (self.host, self.port)

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("rgw: listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- HTTP plumbing -------------------------------------------------

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                status, headers, body = await self._handle(req)
                await self._respond(writer, status, headers, body,
                                    head_only=req.method == "HEAD")
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _read_request(self, reader) -> _HTTPRequest | None:
        try:
            line = await reader.readline()
        except (ConnectionError, OSError):
            return None
        if not line:
            return None
        try:
            method, target, _version = line.decode().split()
        except ValueError:
            return None
        headers: dict[str, str] = {}
        while True:
            hline = await reader.readline()
            if hline in (b"\r\n", b"\n", b""):
                break
            name, _, val = hline.decode().partition(":")
            headers[name.strip().lower()] = val.strip()
        try:
            length = int(headers.get("content-length", "0"))
        except ValueError:
            return None
        if length > MAX_BODY or length < 0:
            return None
        body = await reader.readexactly(length) if length else b""
        parsed = urllib.parse.urlsplit(target)
        return _HTTPRequest(method.upper(), parsed.path, parsed.query,
                            headers, body)

    async def _respond(self, writer, status: int, headers: dict, body: bytes,
                       head_only: bool = False) -> None:
        headers.setdefault("content-length", str(len(body)))
        lines = [f"HTTP/1.1 {status} {_STATUS.get(status, '?')}\r\n"]
        lines += [f"{k}: {v}\r\n" for k, v in headers.items()]
        lines.append("\r\n")
        writer.write("".join(lines).encode())
        if body and not head_only:
            writer.write(body)
        await writer.drain()

    # -- auth + dispatch -----------------------------------------------

    def _error(self, e: RGWError) -> tuple[int, dict, bytes]:
        body = _render(_xml(
            "Error",
            _xml("Code", text=e.code),
            _xml("Message", text=str(e)),
        ))
        return e.status, {"content-type": "application/xml"}, body

    async def _authenticate(self, req: _HTTPRequest) -> None:
        auth_hdr = req.headers.get("authorization", "")
        if not auth_hdr:
            raise RGWError("AccessDenied", 403, "anonymous access denied")
        try:
            parsed = sigv4.parse_authorization(auth_hdr)
            user = await self.store.get_user_by_access_key(parsed.access_key)
            if user is None:
                raise RGWError("InvalidAccessKeyId", 403, parsed.access_key)
            sigv4.verify(req.method, req.path, req.query, req.headers,
                         req.body, user["secret_key"])
        except sigv4.SigV4Error as e:
            raise RGWError(e.code, 403, str(e))
        req.uid = user["uid"]

    async def _handle(self, req: _HTTPRequest) -> tuple[int, dict, bytes]:
        try:
            await self._authenticate(req)
            parts = req.path.lstrip("/").split("/", 1)
            bucket_name = urllib.parse.unquote(parts[0])
            key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
            if not bucket_name:
                return await self._service(req)
            if not key:
                return await self._bucket(req, bucket_name)
            return await self._object(req, bucket_name, key)
        except RGWError as e:
            return self._error(e)
        except Exception:
            log.exception("rgw: internal error on %s %s", req.method, req.path)
            return self._error(RGWError("InternalError", 500, "internal"))

    # -- service ops ----------------------------------------------------

    async def _service(self, req) -> tuple[int, dict, bytes]:
        if req.method != "GET":
            raise RGWError("MethodNotAllowed", 405, req.method)
        buckets = await self.store.list_buckets(req.uid)
        root = _xml(
            "ListAllMyBucketsResult",
            _xml("Owner", _xml("ID", text=req.uid)),
            _xml("Buckets", *[
                _xml("Bucket",
                     _xml("Name", text=b["name"]),
                     _xml("CreationDate", text=b["created"]))
                for b in buckets
            ]),
        )
        return 200, {"content-type": "application/xml"}, _render(root)

    # -- bucket ops ------------------------------------------------------

    async def _bucket(self, req, name: str) -> tuple[int, dict, bytes]:
        if req.method == "PUT":
            placement = req.headers.get("x-rgw-placement")  # extension
            await self.store.create_bucket(name, req.uid, placement)
            return 200, {"location": f"/{name}"}, b""
        if req.method == "DELETE":
            await self.store.delete_bucket(name, req.uid)
            return 204, {}, b""
        if req.method == "HEAD":
            await self.store.get_bucket(name)
            return 200, {}, b""
        if req.method == "GET":
            bucket = await self.store.get_bucket(name)
            if "uploads" in req.params:
                root = _xml("ListMultipartUploadsResult",
                            _xml("Bucket", text=name))
                return 200, {"content-type": "application/xml"}, _render(root)
            return await self._list_objects_v2(req, bucket)
        raise RGWError("MethodNotAllowed", 405, req.method)

    async def _list_objects_v2(self, req, bucket) -> tuple[int, dict, bytes]:
        prefix = req.params.get("prefix", "")
        delimiter = req.params.get("delimiter", "")
        max_keys = _int_param(req.params.get("max-keys", "1000"), "max-keys")
        token = req.params.get("continuation-token", "")
        start_after = req.params.get("start-after", "")
        marker = token or start_after
        res = await self.store.list_objects(
            bucket, prefix=prefix, delimiter=delimiter,
            marker=marker, max_keys=max_keys)
        children = [
            _xml("Name", text=bucket["name"]),
            _xml("Prefix", text=prefix),
            _xml("KeyCount", text=str(
                len(res["entries"]) + len(res["common_prefixes"]))),
            _xml("MaxKeys", text=str(max_keys)),
            _xml("IsTruncated", text="true" if res["truncated"] else "false"),
        ]
        if res["truncated"]:
            children.append(
                _xml("NextContinuationToken", text=res["next_marker"]))
        for key, meta in res["entries"]:
            children.append(_xml(
                "Contents",
                _xml("Key", text=key),
                _xml("LastModified", text=meta.get("mtime", "")),
                _xml("ETag", text=f"\"{meta.get('etag', '')}\""),
                _xml("Size", text=str(meta.get("size", 0))),
            ))
        for cp in res["common_prefixes"]:
            children.append(_xml("CommonPrefixes", _xml("Prefix", text=cp)))
        root = _xml("ListBucketResult", *children)
        return 200, {"content-type": "application/xml"}, _render(root)

    # -- object ops ------------------------------------------------------

    async def _object(self, req, bucket_name: str, key: str):
        bucket = await self.store.get_bucket(bucket_name)
        if req.method == "PUT":
            if "partnumber" in {k.lower() for k in req.params}:
                return await self._upload_part(req, bucket, key)
            ct = req.headers.get("content-type", "binary/octet-stream")
            meta = await self.store.put_object(bucket, key, req.body, ct)
            return 200, {"etag": f"\"{meta['etag']}\""}, b""
        if req.method == "POST":
            if "uploads" in req.params:
                ct = req.headers.get("content-type", "binary/octet-stream")
                upload_id = await self.store.initiate_multipart(bucket, key, ct)
                root = _xml(
                    "InitiateMultipartUploadResult",
                    _xml("Bucket", text=bucket_name),
                    _xml("Key", text=key),
                    _xml("UploadId", text=upload_id),
                )
                return 200, {"content-type": "application/xml"}, _render(root)
            if "uploadId" in req.params:
                return await self._complete_multipart(req, bucket, key)
            raise RGWError("MethodNotAllowed", 405, "POST")
        if req.method in ("GET", "HEAD"):
            if "uploadId" in req.params and req.method == "GET":
                parts = await self.store.list_parts(
                    bucket, key, req.params["uploadId"])
                root = _xml(
                    "ListPartsResult",
                    _xml("Bucket", text=bucket_name),
                    _xml("Key", text=key),
                    _xml("UploadId", text=req.params["uploadId"]),
                    *[_xml("Part",
                           _xml("PartNumber", text=str(p["part_number"])),
                           _xml("ETag", text=f"\"{p['etag']}\""),
                           _xml("Size", text=str(p["size"])))
                      for p in parts],
                )
                return 200, {"content-type": "application/xml"}, _render(root)
            return await self._get_object(req, bucket, key)
        if req.method == "DELETE":
            if "uploadId" in req.params:
                await self.store.abort_multipart(
                    bucket, key, req.params["uploadId"])
                return 204, {}, b""
            await self.store.delete_object(bucket, key)
            return 204, {}, b""
        raise RGWError("MethodNotAllowed", 405, req.method)

    async def _get_object(self, req, bucket, key):
        rng = req.headers.get("range", "")
        meta = await self.store.head_object(bucket, key)
        size = meta["size"]
        status = 200
        off, length = 0, None
        resp_headers = {}
        if rng:
            off, end_incl = _parse_range(rng, size)
            length = end_incl - off + 1
            status = 206
            resp_headers["content-range"] = f"bytes {off}-{end_incl}/{size}"
        if req.method == "HEAD":
            body = b""
            resp_headers["content-length"] = str(
                length if length is not None else size)
        else:
            _meta, body = await self.store.get_object(bucket, key, off, length)
        resp_headers.update({
            "etag": f"\"{meta['etag']}\"",
            "last-modified": meta.get("mtime", ""),
            "content-type": meta.get("content_type", "binary/octet-stream"),
            "accept-ranges": "bytes",
        })
        return status, resp_headers, body

    async def _upload_part(self, req, bucket, key):
        params = {k.lower(): v for k, v in req.params.items()}
        upload_id = params.get("uploadid")
        if not upload_id:
            raise RGWError("InvalidArgument", 400, "uploadId required")
        part_num = _int_param(params.get("partnumber", "0"), "partNumber")
        etag = await self.store.upload_part(
            bucket, key, upload_id, part_num, req.body)
        return 200, {"etag": f"\"{etag}\""}, b""

    async def _complete_multipart(self, req, bucket, key):
        upload_id = req.params["uploadId"]
        try:
            root = ET.fromstring(req.body)
        except ET.ParseError:
            raise RGWError("MalformedXML", 400, "bad CompleteMultipartUpload")
        parts: list[tuple[int, str]] = []
        for part in root:
            if not part.tag.endswith("Part"):
                continue
            pn = etag = None
            for child in part:
                if child.tag.endswith("PartNumber"):
                    try:
                        pn = int(child.text)
                    except (TypeError, ValueError):
                        raise RGWError("MalformedXML", 400, "bad PartNumber")
                elif child.tag.endswith("ETag"):
                    etag = entag_strip(child.text or "")
            if pn is None or etag is None:
                raise RGWError("MalformedXML", 400, "Part missing fields")
            parts.append((pn, etag))
        meta = await self.store.complete_multipart(bucket, key, upload_id, parts)
        out = _xml(
            "CompleteMultipartUploadResult",
            _xml("Bucket", text=bucket["name"]),
            _xml("Key", text=key),
            _xml("ETag", text=f"\"{meta['etag']}\""),
        )
        return 200, {"content-type": "application/xml"}, _render(out)


def _int_param(value: str, name: str) -> int:
    try:
        return int(value)
    except ValueError:
        raise RGWError("InvalidArgument", 400, f"bad {name}: {value!r}")


def _parse_range(value: str, size: int) -> tuple[int, int]:
    """'bytes=a-b' (also 'a-' and '-suffix') -> (first, last) inclusive."""
    if not value.startswith("bytes="):
        raise RGWError("InvalidRange", 416, value)
    spec = value[len("bytes="):].split(",")[0].strip()
    first_s, _, last_s = spec.partition("-")
    try:
        if first_s == "":           # suffix: last N bytes
            n = int(last_s)
            if n <= 0 or size == 0:
                raise ValueError
            return max(0, size - n), size - 1
        first = int(first_s)
        last = int(last_s) if last_s else size - 1
    except ValueError:
        raise RGWError("InvalidRange", 416, value)
    if first >= size or first > last:
        raise RGWError("InvalidRange", 416, value)
    return first, min(last, size - 1)
